"""Join-as-a-service: the resident daemon that keeps the mesh warm.

The benchmark drivers bootstrap, compile, run, and exit; a service for
heavy traffic cannot pay bootstrap + trace + compile per query. This
module turns the PR 1-5 layers into serving machinery around one
long-lived process:

- :class:`JoinService` — the in-process engine: ONE communicator (the
  mesh bootstrapped once, exactly as ``benchmarks/launch.py`` /
  ``parallel/bootstrap.py`` set it up), ONE
  :class:`~..service.programs.JoinProgramCache` (the warm path is a
  lookup + dispatch), request admission (a bounded pending count —
  loud :class:`AdmissionError` refusals instead of an unbounded
  queue), per-request watchdog deadlines (``parallel/watchdog.py`` —
  a wedged collective becomes a structured ``HangError``, not a dead
  server), per-request telemetry spans, and the capacity retry ladder
  + wire-integrity verification routed through the cache
  (``distributed_inner_join(program_cache=...)``).
- :func:`JoinService.join_batched` — K small requests micro-batched
  into one padded SPMD step (:mod:`..service.batching`), unpacked per
  request at settle.
- the live observability layer (docs/OBSERVABILITY.md "Live service
  metrics"): every request gets a ``request_id`` minted at admission
  and threaded through the telemetry span, every event it emits, the
  per-rank JSONL, the Perfetto trace, and the wire response; a
  lock-protected :class:`~..telemetry.live.LiveMetrics` (latency
  histograms with derivable p50/p95/p99, per-op and per-signature
  counters, rolling QPS) behind the ``metrics`` wire op (JSON +
  Prometheus text exposition) and the client ``--watch`` console; a
  :class:`~..telemetry.live.FlightRecorder` ring dumped as
  ``flightrecorder.json`` on poison or terminal error; and a
  :class:`~..telemetry.history.WorkloadHistory` store
  (``history.jsonl`` under the cache's ``persist_dir``) recording
  each request's counter signature, indicators, resolved knobs, and
  wall time — ROADMAP item 5's autotuner substrate.
- the TCP daemon (``tpu-join-service`` / ``python -m
  distributed_join_tpu.service.server``): one JSON object per line in,
  one per line out. The wire carries QUERIES (table generator specs +
  join options — the serving demo), not table bytes; embed
  :class:`JoinService` directly for resident data. ``--smoke`` runs
  the CI acceptance protocol (docs/SERVICE.md): cold query, warm
  repeat (must add zero traces), 16 small joins sequential vs batched
  (batched must win wall clock), a live-metrics scrape (quantiles
  must be non-degenerate), and a poison drill on a throwaway service
  (an induced hang must dump a schema-valid flight recorder),
  emitting a JSON record whose counter signature the ``perfgate``
  lane gates against ``results/baselines/service_smoke.json``.

End-of-run ``--diagnose`` and the telemetry/robustness flags work
exactly as on the drivers (``run_guarded`` owns them); the
``--guard-deadline-s`` flag is re-pointed at PER-REQUEST deadlines —
guarding the whole daemon would kill a healthy resident server.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import socket
import socketserver
import sys
import threading
import time
from typing import Optional

from distributed_join_tpu import telemetry
from distributed_join_tpu.service import batching
from distributed_join_tpu.service.programs import JoinProgramCache
from distributed_join_tpu.telemetry import history as tel_history
from distributed_join_tpu.telemetry import live as tel_live
from distributed_join_tpu.telemetry import tracectx


class AdmissionError(RuntimeError):
    """The service refused the request at admission (pending queue or
    batch size over the configured bound) — a structured, retryable
    refusal instead of an unbounded queue hiding an overloaded mesh."""


class DrainingError(AdmissionError):
    """The service is draining (the ``drain`` wire op or SIGTERM): new
    admissions are refused while in-flight requests finish under their
    watchdog deadlines. Structurally an :class:`AdmissionError` so
    retry-with-backoff clients treat it as 'try another replica' — the
    fleet router's replace path depends on this clean handoff."""


def _backend_platform() -> Optional[str]:
    """The serving backend's platform name for history entries (the
    cost-model calibration seam trusts only real-hardware walls). The
    service has long since touched devices by observe time, so this
    never triggers a fresh backend init."""
    try:
        import jax

        return jax.default_backend()
    except Exception:  # pragma: no cover - backend-dependent
        return None


def _count_groups(valid) -> Optional[int]:
    """Host count of the fused pipeline's valid GROUPS — groups-sized,
    so cheap. The result table is row-sharded: materialize it only
    when every shard is addressable (np.asarray on non-addressable
    shards crashes multi-controller — the resident.py/stageprof
    discipline); otherwise report the count as unknown (None) rather
    than crash or sum a partial local view."""
    import jax
    import numpy as np

    if isinstance(valid, jax.Array) and not valid.is_fully_addressable:
        return None
    return int(np.asarray(valid).sum())


@dataclasses.dataclass
class ServiceConfig:
    """Serving policy knobs (the per-run driver flags, made resident).

    ``request_deadline_s`` is the per-request watchdog bound (None =
    unguarded); ``auto_retry``/``verify_integrity`` are the ladder and
    wire-integrity contracts of ``distributed_inner_join``, applied to
    every request; ``persist_dir`` arms the cache's on-disk AOT tier.
    ``history_dir`` (default: ``persist_dir``) arms the per-request
    workload-history store, bounded by ``history_max_entries`` (live
    entries kept per signature before the file compacts older ones
    into rollup lines; None = unbounded); ``flight_records`` sizes the
    postmortem ring, and ``flight_recorder_path`` pins where a
    poison/terminal dump lands (default: the telemetry session dir,
    else the history dir, else cwd).

    ``auto_tune`` arms the history-driven autotuner
    (:class:`~..planning.tuner.JoinTuner`): every join consults the
    per-signature tuned-config table — fed live by this service's own
    request history, pre-loaded from ``tuner_history`` (default: the
    history store's file, so a restarted server keeps its tuning) —
    and repeat workloads dispatch pre-sized at the rung their ladder
    previously escalated to (zero retry recompiles). Off by default:
    tuner-off is the exact historical dispatch path.
    """

    auto_retry: int = 2
    verify_integrity: bool = False
    request_deadline_s: Optional[float] = None
    max_pending: int = 8
    max_batch_requests: int = 64
    max_programs: int = 128
    persist_dir: Optional[str] = None
    history_dir: Optional[str] = None
    history_max_entries: Optional[int] = None
    auto_tune: bool = False
    tuner_history: Optional[str] = None
    flight_records: int = 256
    flight_recorder_path: Optional[str] = None
    # Resident build tables (service/resident.py; docs/SERVICE.md
    # "Resident build tables"): how many named tables may live
    # on-device, the delta-headroom factor a registration sizes its
    # shards with, the fixed delta slot (so repeat appends share one
    # prep + merge program), and how many pending LSM runs accumulate
    # before an append triggers the maintenance merge on its own.
    max_resident_tables: int = 8
    resident_capacity_factor: float = 1.5
    delta_slot_rows: int = 1024
    maintain_runs: int = 4


class JoinService:
    """The in-process serving engine. Thread-safe: admission is a
    bounded counter, execution serializes on one lock (one mesh runs
    one program at a time; queueing beyond ``max_pending`` is refused,
    not buffered)."""

    def __init__(self, comm, config: Optional[ServiceConfig] = None):
        self.comm = comm
        self.config = config or ServiceConfig()
        self.cache = JoinProgramCache(
            comm, persist_dir=self.config.persist_dir,
            max_entries=self.config.max_programs)
        self._exec_lock = threading.Lock()
        self._admit_lock = threading.Lock()
        self._pending = 0
        self._pending_hwm = 0
        self._request_seq = 0
        # Per-service nonce in every minted id: a client-supplied id
        # (echoed verbatim) can then never collide with the minted
        # namespace, and ids stay unique across server restarts too.
        self._id_stamp = os.urandom(3).hex()
        self.served = 0
        self.rejected = 0
        self.failed = 0
        # Aggregation-pushdown accounting (docs/AGGREGATION.md): how
        # many requests ran the fused join+aggregate pipeline, how
        # many of those dispatched warm (zero new traces), and the
        # groups they emitted — exposed via stats() and the
        # djtpu_agg_* Prometheus gauges.
        self.agg_queries = 0
        self.agg_warm_hits = 0
        self.agg_groups_emitted = 0
        # Multi-operator query plans (docs/QUERY.md): whole plans
        # served as ONE compiled SPMD program, how many of those
        # dispatched warm (zero new traces across every operator),
        # and the widest plan seen (operator count including a fused
        # aggregate) — stats() "query" and the djtpu_query_* gauges.
        self.query_plans = 0
        self.query_warm_hits = 0
        self.query_operators_max = 0
        self.live = tel_live.LiveMetrics()
        self.recorder = tel_live.FlightRecorder(
            self.config.flight_records)
        self.flight_recorder_dumped: Optional[str] = None
        hist_dir = self.config.history_dir or self.config.persist_dir
        # explicit join: the dir may not exist yet, and history_path
        # only maps EXISTING directories to their history.jsonl
        self.history = (tel_history.WorkloadHistory(
            os.path.join(hist_dir, tel_history.HISTORY_FILENAME),
            max_entries_per_signature=self.config.history_max_entries)
            if hist_dir else None)
        # The autotuner (docs/OBSERVABILITY.md "Autotuner"): per-
        # signature tuned-config table, pre-loaded from the persisted
        # history (restart warmth) and fed live by _observe so a
        # mis-sized pre-size is corrected for the very next request.
        self.tuner = None
        if self.config.auto_tune:
            from distributed_join_tpu.planning.tuner import JoinTuner

            preload = self.config.tuner_history or (
                self.history.path if self.history is not None
                else None)
            self.tuner = JoinTuner(preload)
        # Resident build tables (docs/SERVICE.md "Resident build
        # tables"): named, generation-stamped on-device build images
        # served by probe-only programs through the SAME program
        # cache (so resident warm paths share its LRU/disk tiers and
        # trace accounting).
        from distributed_join_tpu.service.resident import (
            ResidentTableRegistry,
        )

        self.resident = ResidentTableRegistry(
            comm, self.cache,
            max_tables=self.config.max_resident_tables,
            capacity_factor=self.config.resident_capacity_factor,
            delta_slot_rows=self.config.delta_slot_rows,
            maintain_runs=self.config.maintain_runs)
        # Per-signature predicted-wall memo (plan construction is
        # cheap host arithmetic, but one join stream hits the same
        # signature thousands of times). Bounded; cleared wholesale.
        self._pred_cache: dict = {}
        # Set (to the HangError description) when a request blew its
        # deadline: the timed-out join keeps running on its detached
        # watchdog worker, so dispatching ANOTHER program onto the
        # same mesh would interleave two SPMD programs on one device
        # set. Fail-stop: every later join is refused loudly (ping/
        # stats still answer) until an operator restarts the server —
        # the serving analog of the drivers' hard exit after HangError.
        self.poisoned: Optional[str] = None
        # Set (to the drain reason) by drain()/SIGTERM: new admissions
        # refuse with DrainingError while in-flight requests finish —
        # the clean half of the fleet's drain-and-replace handoff
        # (docs/FLEET.md; a poisoned replica is the unclean half).
        self.draining: Optional[str] = None

    # -- admission -----------------------------------------------------

    def _mint_request_id(self, request_id) -> str:
        """Admission-lock-held: the one place request ids come from.
        A client-supplied id is honored (capped) so callers can thread
        their own correlation keys end to end."""
        self._request_seq += 1
        if request_id:
            rid = str(request_id)
            if len(rid) > 64:
                # Cap length without aliasing: two long client ids
                # sharing a 64-char prefix must stay distinct.
                import hashlib

                rid = (rid[:48] + "-"
                       + hashlib.sha256(rid.encode()).hexdigest()[:15])
            return rid
        return f"req-{self._id_stamp}-{self._request_seq:06d}"

    def _admit(self, op: str, request_id=None) -> str:
        tenant = tel_history.current_tenant()
        tstamp = {"tenant": tenant} if tenant is not None else {}
        with self._admit_lock:
            rid = self._mint_request_id(request_id)
            if self.poisoned is not None:
                self.rejected += 1
                telemetry.event("request_rejected", reason="poisoned",
                                request_id=rid)
                self.live.record_request(op, "rejected",
                                         tenant=tenant)
                self.recorder.record(request_id=rid, op=op,
                                     signature=None,
                                     outcome="rejected",
                                     reason="poisoned", **tstamp)
                raise AdmissionError(
                    "mesh poisoned by a hung request "
                    f"({self.poisoned}); restart the server"
                )
            if self.draining is not None:
                self.rejected += 1
                telemetry.event("request_rejected", reason="draining",
                                request_id=rid)
                self.live.record_request(op, "rejected",
                                         tenant=tenant)
                self.recorder.record(request_id=rid, op=op,
                                     signature=None,
                                     outcome="rejected",
                                     reason="draining", **tstamp)
                raise DrainingError(
                    f"service draining ({self.draining}); "
                    "retry on another replica"
                )
            if self._pending >= self.config.max_pending:
                self.rejected += 1
                telemetry.event("request_rejected", reason="pending",
                                pending=self._pending, request_id=rid)
                self.live.record_request(op, "rejected",
                                         tenant=tenant)
                self.recorder.record(request_id=rid, op=op,
                                     signature=None,
                                     outcome="rejected",
                                     reason="pending", **tstamp)
                raise AdmissionError(
                    f"{self._pending} requests already pending "
                    f"(max_pending={self.config.max_pending}); "
                    "retry with backoff"
                )
            self._pending += 1
            if self._pending > self._pending_hwm:
                self._pending_hwm = self._pending
        return rid

    def _release(self):
        with self._admit_lock:
            self._pending -= 1

    # -- the request paths --------------------------------------------

    def join(self, build, probe, key="key", *, request_id=None,
             op: str = "join", tenant: Optional[str] = None, **opts):
        """One admitted, watchdog-guarded, span-wrapped join through
        the program cache. Returns the ``JoinResult`` (with
        ``retry_report`` / ``integrity_report`` attributes exactly as
        ``distributed_inner_join`` attaches them, plus the host-side
        ``new_traces`` and ``request_id``). ``tenant`` (None = the
        wire handler's scope, or the default tenant) stamps the
        request's accounting and selects the tuner namespace — it
        never reaches the compiled program (the shared program cache
        is tenant-free by construction: workload signatures cover
        shapes/dtypes/knobs only, so two tenants with one workload
        share one executable)."""
        with tel_history.tenant_scope(
                tenant if tenant is not None
                else tel_history.current_tenant()):
            return self._join_scoped(build, probe, key,
                                     request_id=request_id, op=op,
                                     **opts)

    def _join_scoped(self, build, probe, key="key", *,
                     request_id=None, op: str = "join", **opts):
        from distributed_join_tpu.parallel.distributed_join import (
            distributed_inner_join,
        )
        from distributed_join_tpu.parallel.watchdog import (
            HangError,
            call_with_deadline,
        )

        rid = self._admit(op, request_id)
        t_start = time.perf_counter()
        sig = None
        predicted = plan_digest = None
        outcome = "failed"
        res = None
        err: Optional[BaseException] = None
        new_traces = cache_hits = 0
        agg_spec = opts.get("aggregate")
        agg_rec = agg_spec.as_record() if agg_spec is not None else None
        try:
            # Inside the try: anything raising after _admit must still
            # release the pending-admission slot in the finally.
            sig = self._workload_signature(build, probe, key, opts)
            predicted, plan_digest = self._predicted_wall(
                sig, build, probe, key, opts)
            with self._exec_lock:
                # Re-check under the EXEC lock: a request admitted
                # before a hang can be parked here while the hanging
                # request poisons the mesh and releases this lock —
                # it must not dispatch alongside the detached worker.
                with self._admit_lock:
                    if self.poisoned is not None:
                        self.rejected += 1
                        outcome = "rejected"
                        telemetry.event("request_rejected",
                                        reason="poisoned",
                                        request_id=rid)
                        raise AdmissionError(
                            "mesh poisoned by a hung request "
                            f"({self.poisoned}); restart the server")

                if self.tuner is not None:
                    # Pin the tuner's READ namespace to this request's
                    # tenant while the exec lock serializes dispatch:
                    # recommend() deep inside distributed_inner_join
                    # (and the watchdog's worker thread, which cannot
                    # see this thread's scope) consults it.
                    self.tuner.active_tenant = \
                        tel_history.current_tenant()

                def run_once():
                    return distributed_inner_join(
                        build, probe, self.comm, key=key,
                        auto_retry=self.config.auto_retry,
                        verify_integrity=self.config.verify_integrity,
                        program_cache=self.cache,
                        tuner=self.tuner, **opts)

                deadline = self.config.request_deadline_s
                traces0 = self.cache.traces
                hits0 = self.cache.hits
                try:
                    # request_scope tags EVERY event/span this request
                    # emits — ladder rungs, cache traces, watchdog
                    # events, even from worker threads — with rid, so
                    # one grep correlates wire response, JSONL, and
                    # Perfetto views.
                    with telemetry.request_scope(rid), \
                            telemetry.span("request", request_id=rid,
                                           op=op, signature=sig) as sp:
                        if deadline is None:
                            res = run_once()
                        else:
                            res = call_with_deadline(
                                run_once, deadline,
                                what=f"request {rid}")
                        if sp is not None:
                            sp.sync_on(res.total)
                except Exception as exc:
                    new_traces = self.cache.traces - traces0
                    cache_hits = self.cache.hits - hits0
                    if isinstance(exc, HangError):
                        outcome = "hang"
                        with self._admit_lock:
                            self.poisoned = str(exc)
                    raise
                self.served += 1
                # Trace accounting captured UNDER the exec lock — a
                # concurrent connection's cold compile must not be
                # misattributed to this request (host-side attribute,
                # the retry_report pattern).
                new_traces = self.cache.traces - traces0
                cache_hits = self.cache.hits - hits0
                outcome = "served"
                if agg_spec is not None:
                    # The fused pipeline's result table holds GROUPS
                    # (valid marks real ones).
                    groups = _count_groups(res.table.valid)
                    agg_rec = dict(agg_rec, groups=groups)
                    with self._admit_lock:
                        self.agg_queries += 1
                        if new_traces == 0:
                            self.agg_warm_hits += 1
                        if groups is not None:
                            self.agg_groups_emitted += groups
                    object.__setattr__(res, "agg_groups", groups)
                object.__setattr__(res, "new_traces", new_traces)
                object.__setattr__(res, "request_id", rid)
                return res
        except BaseException as exc:
            err = exc
            # Counted HERE (not in the dispatch-level handler) so a
            # request that dies before dispatch — e.g. signature
            # computation on a malformed input — is a failure too;
            # poisoned-recheck refusals already counted as rejected.
            # Under the admit lock: pre-dispatch failures run outside
            # the exec lock, so concurrent increments would race.
            if outcome != "rejected":
                if isinstance(exc, Exception):
                    with self._admit_lock:
                        self.failed += 1
                else:
                    # Ctrl-C / SystemExit mid-request is an ABORT of
                    # the process, not a failure of the workload —
                    # it must not pollute the per-signature failure
                    # trend the history store exists to show.
                    outcome = "aborted"
            raise
        finally:
            # Release the admission slot BEFORE the bookkeeping
            # fan-out: _observe does file I/O (history append, a
            # poison-time flight dump) and uses no admission state —
            # holding the slot through it would reject concurrent
            # requests for no reason.
            self._release()
            self._observe(rid, op, sig, outcome, res, err,
                          time.perf_counter() - t_start,
                          new_traces, cache_hits, predicted,
                          plan_digest, aggregate=agg_rec)

    def join_batched(self, requests, key="key", *,
                     slot_build_rows=None, slot_probe_rows=None,
                     with_rows: bool = False, request_id=None, **opts):
        """Micro-batch ``requests`` (``(build, probe)`` pairs sharing
        one schema and ``key``) into one SPMD step and unpack per
        request. Returns ``batching.split``'s per-request records."""
        if len(requests) > self.config.max_batch_requests:
            with self._admit_lock:
                rid = self._mint_request_id(request_id)
                self.rejected += 1
            telemetry.event("request_rejected", reason="batch_size",
                            batch=len(requests), request_id=rid)
            self.live.record_request("batch", "rejected")
            self.recorder.record(request_id=rid, op="batch",
                                 signature=None, outcome="rejected",
                                 reason="batch_size")
            raise AdmissionError(
                f"batch of {len(requests)} exceeds max_batch_requests="
                f"{self.config.max_batch_requests}"
            )
        try:
            mb = batching.combine(
                requests, key=key, slot_build_rows=slot_build_rows,
                slot_probe_rows=slot_probe_rows)
        except Exception as exc:
            # A malformed batch (schema mismatch, oversize slot) dies
            # BEFORE self.join's accounting — it must still be
            # visible to operators as a failure, not only to the one
            # client that sent it.
            with self._admit_lock:
                rid = self._mint_request_id(request_id)
                self.failed += 1
            error = f"{type(exc).__name__}: {exc}"
            telemetry.event("request_failed", reason="batch_combine",
                            request_id=rid, error=error)
            self.live.record_request("batch", "failed")
            self.recorder.record(request_id=rid, op="batch",
                                 signature=None, outcome="failed",
                                 reason="batch_combine", error=error)
            raise
        res = self.join(mb.build, mb.probe, key=list(mb.key),
                        request_id=request_id, op="batch", **opts)
        results = batching.split(res, mb, with_rows=with_rows)
        for r in results:
            # the batch shares one program resolution; the count is
            # replicated per request for the wire's convenience
            r["new_traces"] = getattr(res, "new_traces", 0)
            r["request_id"] = getattr(res, "request_id", None)
        return results

    def resident_join(self, table: str, probe, *, request_id=None,
                      **opts):
        """One probe-only join against resident table ``table``
        (docs/SERVICE.md "Resident build tables"): admission,
        watchdog deadline, span, and accounting exactly as
        :meth:`join`, with dispatch routed through
        :meth:`~.resident.ResidentTableRegistry.join` — pending delta
        runs are LSM-merged first, and a warm repeat is a zero-trace
        dict-lookup dispatch. The history entry is stamped
        ``resident`` (cold full joins carry ``resident: null``)."""
        from distributed_join_tpu.parallel.watchdog import (
            HangError,
            call_with_deadline,
        )
        op = "resident_join"
        rid = self._admit(op, request_id)
        t_start = time.perf_counter()
        sig = None
        predicted = plan_digest = None
        outcome = "failed"
        res = None
        err: Optional[BaseException] = None
        new_traces = cache_hits = 0
        resident_rec = None
        agg_spec = opts.get("aggregate")
        agg_rec = agg_spec.as_record() if agg_spec is not None else None
        try:
            sig = self.resident.workload_signature(
                table, probe, dict(opts))
            with self._exec_lock:
                with self._admit_lock:
                    if self.poisoned is not None:
                        self.rejected += 1
                        outcome = "rejected"
                        telemetry.event("request_rejected",
                                        reason="poisoned",
                                        request_id=rid)
                        raise AdmissionError(
                            "mesh poisoned by a hung request "
                            f"({self.poisoned}); restart the server")

                def run_once():
                    # --verify-integrity rides the probe-only
                    # program's digest rungs (PR 12;
                    # make_probe_join_step(with_integrity=)) — the
                    # full join's contract on the resident path.
                    return self.resident.join(
                        table, probe,
                        auto_retry=self.config.auto_retry,
                        verify_integrity=self.config
                        .verify_integrity,
                        tuner=self.tuner, **opts)

                deadline = self.config.request_deadline_s
                traces0 = self.cache.traces
                hits0 = self.cache.hits
                try:
                    with telemetry.request_scope(rid), \
                            telemetry.span("request", request_id=rid,
                                           op=op, signature=sig,
                                           table=table) as sp:
                        if deadline is None:
                            res = run_once()
                        else:
                            res = call_with_deadline(
                                run_once, deadline,
                                what=f"request {rid}")
                        if sp is not None:
                            sp.sync_on(res.total)
                except Exception as exc:
                    new_traces = self.cache.traces - traces0
                    cache_hits = self.cache.hits - hits0
                    if isinstance(exc, HangError):
                        outcome = "hang"
                        with self._admit_lock:
                            self.poisoned = str(exc)
                    raise
                self.served += 1
                new_traces = self.cache.traces - traces0
                cache_hits = self.cache.hits - hits0
                outcome = "served"
                resident_rec = getattr(res, "resident", None)
                if agg_spec is not None:
                    groups = _count_groups(res.table.valid)
                    agg_rec = dict(agg_rec, groups=groups)
                    with self._admit_lock:
                        self.agg_queries += 1
                        if new_traces == 0:
                            self.agg_warm_hits += 1
                        if groups is not None:
                            self.agg_groups_emitted += groups
                    object.__setattr__(res, "agg_groups", groups)
                object.__setattr__(res, "new_traces", new_traces)
                object.__setattr__(res, "request_id", rid)
                return res
        except BaseException as exc:
            err = exc
            if outcome != "rejected":
                if isinstance(exc, Exception):
                    with self._admit_lock:
                        self.failed += 1
                else:
                    outcome = "aborted"
            raise
        finally:
            self._release()
            if resident_rec is None:
                # Failed/refused requests still stamp the handle they
                # targeted so the history shows WHICH table refused.
                resident_rec = {"table": table, "generation": None}
            self._observe(rid, op, sig, outcome, res, err,
                          time.perf_counter() - t_start,
                          new_traces, cache_hits, predicted,
                          plan_digest, resident=resident_rec,
                          aggregate=agg_rec)

    def query(self, tables: dict, plan, *, request_id=None, **opts):
        """One admitted multi-operator plan (docs/QUERY.md) through
        the program cache: the WHOLE plan — every join plus a fused
        aggregate — is one compiled SPMD program keyed on the plan
        digest, so a repeat of the same plan over same-shaped tables
        dispatches warm with zero new traces. Admission, the exec
        lock's poisoned re-check, the watchdog deadline, and
        ``_observe`` bookkeeping follow :meth:`join` exactly; the
        result is the :class:`~..parallel.query_exec.QueryResult`
        with ``new_traces`` / ``request_id`` attached."""
        from distributed_join_tpu.parallel.query_exec import (
            distributed_query,
        )
        from distributed_join_tpu.parallel.watchdog import (
            HangError,
            call_with_deadline,
        )

        op = "query"
        rid = self._admit(op, request_id)
        t_start = time.perf_counter()
        plan_digest = plan.digest()
        # Rung-stable signature for history/live grouping: the digest
        # already folds in tables+ops+options, so it IS the workload.
        sig = f"queryplan-{plan_digest[:16]}"
        outcome = "failed"
        res = None
        err: Optional[BaseException] = None
        new_traces = cache_hits = 0
        agg_rec = None
        try:
            with self._exec_lock:
                with self._admit_lock:
                    if self.poisoned is not None:
                        self.rejected += 1
                        outcome = "rejected"
                        telemetry.event("request_rejected",
                                        reason="poisoned",
                                        request_id=rid)
                        raise AdmissionError(
                            "mesh poisoned by a hung request "
                            f"({self.poisoned}); restart the server")

                def run_once():
                    return distributed_query(
                        tables, plan, self.comm,
                        auto_retry=self.config.auto_retry,
                        program_cache=self.cache, **opts)

                deadline = self.config.request_deadline_s
                traces0 = self.cache.traces
                hits0 = self.cache.hits
                try:
                    with telemetry.request_scope(rid), \
                            telemetry.span("request", request_id=rid,
                                           op=op, signature=sig) as sp:
                        if deadline is None:
                            res = run_once()
                        else:
                            res = call_with_deadline(
                                run_once, deadline,
                                what=f"request {rid}")
                        if sp is not None:
                            sp.sync_on(res.total)
                except Exception as exc:
                    new_traces = self.cache.traces - traces0
                    cache_hits = self.cache.hits - hits0
                    if isinstance(exc, HangError):
                        outcome = "hang"
                        with self._admit_lock:
                            self.poisoned = str(exc)
                    raise
                self.served += 1
                new_traces = self.cache.traces - traces0
                cache_hits = self.cache.hits - hits0
                outcome = "served"
                groups = None
                agg_wire = plan.ops[-1].aggregate
                if agg_wire is not None:
                    groups = _count_groups(res.table.valid)
                    agg_rec = dict(agg_wire, groups=groups)
                with self._admit_lock:
                    self.query_plans += 1
                    if new_traces == 0:
                        self.query_warm_hits += 1
                    self.query_operators_max = max(
                        self.query_operators_max, plan.n_operators())
                    if groups is not None:
                        self.agg_groups_emitted += groups
                object.__setattr__(res, "groups", groups)
                object.__setattr__(res, "new_traces", new_traces)
                object.__setattr__(res, "request_id", rid)
                return res
        except BaseException as exc:
            err = exc
            if outcome != "rejected":
                if isinstance(exc, Exception):
                    with self._admit_lock:
                        self.failed += 1
                else:
                    outcome = "aborted"
            raise
        finally:
            self._release()
            self._observe(rid, op, sig, outcome, res, err,
                          time.perf_counter() - t_start,
                          new_traces, cache_hits, None,
                          plan_digest, aggregate=agg_rec)

    def _table_op(self, op: str, table: str, fn, request_id=None):
        """Admission + exec-lock + accounting wrapper for the
        resident table-management ops (register/append/drop). They
        dispatch prep/merge programs on the serving mesh, so they
        carry the SAME request semantics as a join: poisoned re-check
        under the exec lock (a parked op must not dispatch alongside
        a hung request's detached worker), the per-request watchdog
        deadline, and hang-poisoning."""
        from distributed_join_tpu.parallel.watchdog import (
            HangError,
            call_with_deadline,
        )

        rid = self._admit(op, request_id)
        t_start = time.perf_counter()
        outcome = "failed"
        err: Optional[BaseException] = None
        out = None
        new_traces = 0
        sig = f"res-tbl-{table}"
        try:
            with self._exec_lock:
                with self._admit_lock:
                    if self.poisoned is not None:
                        self.rejected += 1
                        outcome = "rejected"
                        telemetry.event("request_rejected",
                                        reason="poisoned",
                                        request_id=rid)
                        raise AdmissionError(
                            "mesh poisoned by a hung request "
                            f"({self.poisoned}); restart the server")
                deadline = self.config.request_deadline_s
                traces0 = self.cache.traces
                try:
                    with telemetry.request_scope(rid), \
                            telemetry.span("request", request_id=rid,
                                           op=op, table=table):
                        if deadline is None:
                            out = fn()
                        else:
                            out = call_with_deadline(
                                fn, deadline, what=f"request {rid}")
                except Exception as exc:
                    new_traces = self.cache.traces - traces0
                    if isinstance(exc, HangError):
                        outcome = "hang"
                        with self._admit_lock:
                            self.poisoned = str(exc)
                    raise
                new_traces = self.cache.traces - traces0
                self.served += 1
                outcome = "served"
                return out
        except BaseException as exc:
            err = exc
            if outcome != "rejected":
                if isinstance(exc, Exception):
                    with self._admit_lock:
                        self.failed += 1
                else:
                    outcome = "aborted"
            raise
        finally:
            self._release()
            handle = self.resident.peek(table)
            gen = handle.generation if handle is not None else None
            self._observe(rid, op, sig, outcome, None, err,
                          time.perf_counter() - t_start,
                          new_traces, 0, None, None,
                          resident={"table": table,
                                    "generation": gen})

    def note_refused_resident(self, table: str, request_id,
                              exc: BaseException) -> str:
        """Account a resident request refused BEFORE admission (the
        wire handler's handle lookup: unknown or poisoned table).
        Every other refusal the service makes lands in the live
        metrics, the flight ring, and the history store — an operator
        diagnosing a burst of client errors must see these too."""
        with self._admit_lock:
            rid = self._mint_request_id(request_id)
            self.failed += 1
        self._observe(rid, "resident_join", f"res-tbl-{table}",
                      "failed", None, exc, 0.0, 0, 0, None, None,
                      resident={"table": table, "generation": None})
        return rid

    def register_table(self, name: str, build, key="key", *,
                       replace: bool = False, request_id=None,
                       wire_spec=None) -> dict:
        """Run the expensive build-side 2/3 ONCE and hold the result
        resident under ``name`` (the ``register`` wire op)."""
        def doit():
            handle = self.resident.register(name, build, key=key,
                                            replace=replace)
            if wire_spec is not None:
                handle.wire_spec = dict(wire_spec)
                # The base key column powers per-request probe
                # generation without regenerating the build
                # (_probe_from_spec); one extra resident column, wire
                # demo plane only.
                kname = key if isinstance(key, str) else key[0]
                handle.wire_build_keys = build.columns[kname]
            return {"table": name, **handle.stats()}

        return self._table_op("register", name, doit,
                              request_id=request_id)

    def append_rows(self, name: str, delta, *,
                    maintain: Optional[bool] = None,
                    request_id=None) -> dict:
        """Land a delta as a sorted run on ``name``'s LSM queue (the
        ``append`` wire op); the maintenance pass merges per the
        configured ``maintain_runs`` policy (or immediately with
        ``maintain=True``)."""
        def doit():
            handle = self.resident.append(name, delta,
                                          maintain=maintain)
            return {"table": name, **handle.stats()}

        return self._table_op("append", name, doit,
                              request_id=request_id)

    def drop_table(self, name: str, *, request_id=None) -> dict:
        def doit():
            self.resident.drop(name)
            return {"table": name, "dropped": True}

        return self._table_op("drop", name, doit,
                              request_id=request_id)

    def explain(self, build, probe, key="key", **opts) -> dict:
        """ADMISSION-FREE dry run (the ``explain`` wire op): resolve
        the plan + roofline cost prediction for exactly the program a
        ``join`` with these tables/options would dispatch, plus the
        cache-hit verdict — resident executable, persisted blob, or a
        fresh trace. Pure host arithmetic over shapes: no admission
        slot, no exec lock, no mesh, ZERO traces or compiles (the
        tables may be ShapeDtypeStructs — nothing reads data).

        The plan's digest equals the program cache's key for the
        corresponding join (first ladder rung), so the verdict can
        never disagree with what dispatch would actually do."""
        t0 = time.perf_counter()
        try:
            plan = self._plan_for(build, probe, key, opts)
            out = {
                "plan": plan.as_record(),
                "cost": plan.cost,
                "cache": self.cache.predict_hit(plan.digest),
            }
            if self.tuner is not None:
                # The tuner's verdict for this workload — which knobs
                # a join would dispatch with and WHY (the operator's
                # "why was this knob chosen" surface). Resolved via
                # the SAME path the join's dispatch uses (tables +
                # opts through tuner.resolve, shape geometry
                # included), so the shape-gated policies — headroom
                # bump, ragged wire switch — answer here exactly as
                # they would at dispatch. Note the plan/cache verdict
                # above describes the STATIC resolution; a
                # history-tuned join dispatches under the tuned
                # sizing's own signature.
                out["tuned"] = self.tuner.resolve(
                    self.comm, build, probe, key=key,
                    with_integrity=self.config.verify_integrity,
                    opts=opts).as_record()
        except BaseException:
            # A failing dry run (unknown option, malformed spec) must
            # be visible on the operator surfaces too, not only to the
            # one client that sent it.
            self.live.record_request("explain", "failed")
            raise
        # Visible to operators like any other op (latency + outcome in
        # the live metrics), but never in the flight recorder — the
        # postmortem ring is for requests that touched the mesh.
        self.live.record_request(
            "explain", "served", latency_s=time.perf_counter() - t0)
        return out

    def _plan_for(self, build, probe, key, opts):
        """THE one plan construction for both the explain op and the
        per-request prediction: normalize the service-level options
        exactly as :meth:`join`'s dispatch resolves them —
        ``with_metrics`` is FORWARDED (session-resolved only when the
        caller left it None, like the cache key), ``with_integrity``
        defaults to the service policy — so the plan digest always
        equals the cache key the corresponding join dispatches under."""
        from distributed_join_tpu import planning

        o = dict(opts)
        wi = o.pop("with_integrity", self.config.verify_integrity)
        return planning.explain_join(build, probe, self.comm, key=key,
                                     verify_integrity=wi, **o)

    # -- live observability -------------------------------------------

    def _predicted_wall(self, sig, build, probe, key, opts):
        """``(predicted_wall_s, plan_digest16)`` for this request
        (memoized per workload signature — one join stream repeats one
        signature). The plan digest is the FULL first-rung cache key,
        truncated like the workload signature but distinct from it
        (the workload hash deliberately ignores ladder sizing so a
        workload keeps one identity across rungs). Never fails a
        request: an unplannable option set predicts ``(None, None)``."""
        if sig in self._pred_cache:
            return self._pred_cache[sig]
        try:
            plan = self._plan_for(build, probe, key, opts)
            val = (plan.cost.get("total_s"), plan.digest[:16])
        except Exception:
            val = (None, None)
        if len(self._pred_cache) >= 512:
            self._pred_cache.clear()
        self._pred_cache[sig] = val
        return val

    def _workload_signature(self, build, probe, key, opts) -> str:
        """The stable workload identity the live layer keys on (flight
        records, per-signature counters, history lines): the program
        cache's canonical signature digest, truncated. Coarser than
        the per-rung entries the cache stores (the ladder resolves its
        sizing at dispatch) — one workload keeps one hash across its
        rungs. Delegates to :func:`..planning.tuner.workload_
        signature` — the SAME function the tuner's lookup inside
        ``distributed_inner_join`` uses, so the history's writer and
        its reader can never key apart."""
        from distributed_join_tpu.planning.tuner import (
            workload_signature,
        )

        o = dict(opts)
        wm = o.pop("with_metrics", None)
        wi = o.pop("with_integrity", self.config.verify_integrity)
        return workload_signature(self.comm, build, probe, key=key,
                                  with_metrics=wm, with_integrity=wi,
                                  **o)

    def _observe(self, rid, op, sig, outcome, res, err, elapsed_s,
                 new_traces, cache_hits, predicted_wall_s=None,
                 plan_digest=None, resident=None, aggregate=None):
        """Per-request accounting fan-out: live metrics, the flight-
        recorder ring, the workload-history store, and the poison-time
        flight dump. Observability must never turn a served request
        into a failure (or mask the request's own error), so the whole
        fan-out is guarded."""
        try:
            retry_rec = None
            rung_path = None
            matches = None
            overflow = None
            # The distributed-trace stamp: the handler installed the
            # wire-carried context for this request's scope; None for
            # in-process callers without one. Flight records and
            # history lines carry it so a postmortem groups by
            # trace_id across every process of the fleet.
            trace = telemetry.current_trace()
            # Tenant stamp (docs/FLEET.md "Multi-tenancy"): installed
            # by the wire handler's tenant_scope (None for in-process
            # callers without one) — rides live counters, the flight
            # ring, and the history line like the trace context.
            tenant = tel_history.current_tenant()
            tuned = (getattr(res, "tuned", None)
                     if res is not None else None)
            if res is not None and outcome == "served":
                rr = getattr(res, "retry_report", None)
                if rr is not None:
                    retry_rec = rr.as_record()
                    rung_path = [a.action for a in rr.attempts]
                matches = int(res.total)
                overflow = bool(res.overflow)
            counts = tel_history.retry_counts(retry_rec)
            error = (f"{type(err).__name__}: {err}"
                     if err is not None else None)
            self.live.record_request(
                op, outcome,
                latency_s=elapsed_s if outcome == "served" else None,
                signature=sig, cache_hits=cache_hits,
                new_traces=new_traces,
                retry_rungs=max(counts["n_attempts"] - 1, 0),
                integrity_retries=counts["integrity_retries"],
                tenant=tenant)
            self.recorder.record(
                request_id=rid, op=op, signature=sig,
                **({"tenant": tenant} if tenant is not None else {}),
                # The first-rung program-cache key (truncated) — a
                # postmortem record correlates directly with explain
                # artifacts and cache entries; distinct from the
                # coarser rung-stable workload signature above.
                plan_digest=plan_digest, outcome=outcome,
                elapsed_s=round(elapsed_s, 6), matches=matches,
                overflow=overflow, new_traces=new_traces,
                cache_hits=cache_hits, rung_path=rung_path,
                tuned=tel_history.tuned_summary(tuned),
                resident=resident, aggregate=aggregate, error=error,
                trace=trace)
            if self.history is not None or self.tuner is not None:
                tel = (getattr(res, "telemetry", None)
                       if res is not None else None)
                entry = tel_history.request_entry(
                    request_id=rid, op=op, signature=sig,
                    outcome=outcome, wall_s=elapsed_s,
                    new_traces=new_traces, cache_hits=cache_hits,
                    matches=matches, retry_record=retry_rec,
                    metrics=tel.to_dict() if tel is not None else None,
                    predicted_wall_s=predicted_wall_s,
                    tuned=tuned, platform=_backend_platform(),
                    resident=resident, aggregate=aggregate,
                    error=error, trace=trace, tenant=tenant)
                if self.history is not None:
                    self.history.append(entry)
                if self.tuner is not None:
                    # Close the loop in-process: the next request of
                    # this signature sees this outcome — including a
                    # corrected rung after a mis-sized pre-size.
                    self.tuner.observe_entry(entry)
            if outcome == "hang":
                self.dump_flight_recorder(
                    f"poisoned: request {rid} blew its deadline")
        except Exception as exc:  # noqa: BLE001 - bookkeeping boundary
            telemetry.event("observability_error", request_id=rid,
                            error=f"{type(exc).__name__}: {exc}")

    def dump_flight_recorder(self, reason: str) -> Optional[str]:
        """Dump the last-N request ring as ``flightrecorder.json``
        (``telemetry.analyze check`` validates the schema). Called on
        poison and on daemon terminal error; also safe to call any
        time for a live snapshot."""
        path = self.config.flight_recorder_path
        if path is None:
            s = telemetry.sink()
            base = (s.dir if s is not None
                    else self.config.history_dir
                    or self.config.persist_dir or ".")
            path = os.path.join(base, tel_live.FLIGHT_RECORDER_FILENAME)
        try:
            # A poison dump cut while a traced request is active
            # carries that request's trace context, so the postmortem
            # joins the fleet timeline of the request that hung.
            path = self.recorder.dump(
                path, reason, trace=telemetry.current_trace())
        except OSError as exc:
            telemetry.event("flightrecorder_dump_failed", path=path,
                            error=f"{type(exc).__name__}: {exc}")
            return None
        self.flight_recorder_dumped = path
        telemetry.event("flightrecorder_dumped", path=path,
                        reason=reason)
        return path

    # -- lifecycle (drain / quiesce) ----------------------------------

    def quiesce(self, timeout_s: float = 30.0,
                settle_admissions: bool = False) -> bool:
        """Wait (bounded) until no request holds the exec lock —
        i.e. nothing is dispatching on the mesh right now. The
        ``shutdown`` wire op calls this so its ``{"ok": true}`` reply
        can no longer race a still-dispatching join on another
        connection; False = a request was still running (or the mesh
        is poisoned-busy) when the bound expired.

        ``settle_admissions`` additionally waits for the admission
        count to reach zero FIRST — a join already admitted and
        parked on the exec lock would otherwise dispatch right after
        a momentarily-free lock was observed (callers close the
        admission window via ``draining`` before asking for this)."""
        deadline = time.monotonic() + max(timeout_s, 0.0)
        if settle_admissions:
            while True:
                with self._admit_lock:
                    pending = self._pending
                if pending == 0:
                    break
                if time.monotonic() >= deadline:
                    return False
                time.sleep(0.05)
        acquired = self._exec_lock.acquire(
            timeout=max(deadline - time.monotonic(), 0.0))
        if acquired:
            self._exec_lock.release()
        return acquired

    def drain(self, reason: str = "drain requested",
              settle_timeout_s: float = 60.0) -> dict:
        """Graceful drain (the ``drain`` wire op and the daemon's
        SIGTERM handler): refuse new admissions with
        :class:`DrainingError`, let in-flight requests finish under
        their watchdog deadlines (bounded wait), then flush the
        history store and dump the flight recorder so nothing the
        process learned is lost at exit. Idempotent; returns the
        settle record the wire echoes."""
        with self._admit_lock:
            first = self.draining is None
            if first:
                self.draining = reason
        if first:
            telemetry.event("service_draining", reason=reason)
        settled = self.quiesce(timeout_s=settle_timeout_s,
                               settle_admissions=True)
        if self.history is not None:
            # Line-buffered writes already flushed per entry; closing
            # releases the handle (a straggler append reopens it).
            self.history.close()
        path = self.dump_flight_recorder(f"drained: {reason}")
        with self._admit_lock:
            pending = self._pending
        telemetry.event("service_drained", reason=reason,
                        settled=settled, pending=pending)
        return {"draining": True, "drained": settled,
                "pending": pending, "reason": reason,
                "flightrecorder": path}

    def stats(self) -> dict:
        with self._admit_lock:
            pending = self._pending
            hwm = self._pending_hwm
        return {
            "served": self.served,
            "failed": self.failed,
            "rejected": self.rejected,
            "pending": pending,
            "inflight": pending,
            "pending_hwm": hwm,
            "uptime_s": round(self.live.uptime_s(), 3),
            "qps_60s": round(self.live.qps(), 3),
            "latency": self.live.overall_latency(),
            "latency_by_op": self.live.latency_by_op(),
            "poisoned": self.poisoned,
            "draining": self.draining,
            "cache": self.cache.stats(),
            "resident": self.resident.stats(),
            "aggregate": {
                "queries": self.agg_queries,
                "warm_hits": self.agg_warm_hits,
                "groups_emitted": self.agg_groups_emitted,
            },
            "query": {
                "plans": self.query_plans,
                "warm_hits": self.query_warm_hits,
                "operators_max": self.query_operators_max,
            },
            "tuner": (self.tuner.stats() if self.tuner is not None
                      else None),
            # Per-tenant served/shed/QPS/latency (docs/FLEET.md
            # "Multi-tenancy"): {} until some request carries a
            # tenant — the --watch console's per-tenant segment.
            "tenants": self.live.tenants_summary(),
        }

    def metrics_snapshot(self) -> dict:
        """The ``metrics`` wire op's JSON body: the live accumulator
        plus the service/cache counters."""
        snap = self.live.snapshot()
        snap["stats"] = self.stats()
        snap["flight_records"] = len(self.recorder)
        snap["history_path"] = (self.history.path
                                if self.history is not None else None)
        return snap

    def prometheus_metrics(self) -> str:
        """Prometheus text exposition of the same state (the
        ``metrics`` op with ``format: "prometheus"``)."""
        st = self.stats()
        cache = st["cache"]
        resident = st["resident"]
        return self.live.to_prometheus(gauges={
            "pending": st["pending"],
            "pending_high_water": st["pending_hwm"],
            "poisoned": int(bool(st["poisoned"])),
            "draining": int(bool(st["draining"])),
            "served_requests": st["served"],
            "failed_requests": st["failed"],
            "rejected_requests": st["rejected"],
            "program_cache_entries": cache["entries"],
            "program_cache_max_entries": cache["max_entries"],
            "program_cache_occupancy": cache["occupancy"],
            "program_cache_hits": cache["hits"],
            "program_cache_misses": cache["misses"],
            "program_cache_traces": cache["traces"],
            "program_cache_disk_loads": cache["disk_loads"],
            "program_cache_disk_load_failures":
                cache["disk_load_failures"],
            "program_cache_disk_persists": cache["disk_persists"],
            "program_cache_lru_evictions": cache["lru_evictions"],
            "program_cache_integrity_evictions":
                cache["integrity_evictions"],
            "program_cache_generation_evictions":
                cache["generation_evictions"],
            # Resident build tables (docs/OBSERVABILITY.md "Resident
            # metrics"): how much build-side work is held on-device
            # and how often the probe-only warm path serves it.
            "resident_tables": resident["count"],
            "resident_bytes": resident["bytes_resident"],
            "resident_generation_max": resident["generation_max"],
            "resident_probe_joins_total": resident["probe_joins"],
            "resident_warm_probe_joins_total":
                resident["warm_probe_joins"],
            "resident_refused_total": resident["refused"],
            # Aggregation pushdown (docs/AGGREGATION.md): fused
            # join+aggregate traffic — queries, zero-trace warm hits,
            # groups emitted instead of materialized rows.
            "agg_queries_total": st["aggregate"]["queries"],
            "agg_warm_hits_total": st["aggregate"]["warm_hits"],
            "agg_groups_emitted_total":
                st["aggregate"]["groups_emitted"],
            # Multi-operator query plans (docs/QUERY.md): whole-plan
            # single-program traffic and its warm-dispatch rate.
            "query_plans_total": st["query"]["plans"],
            "query_warm_hits_total": st["query"]["warm_hits"],
            "query_operators_max": st["query"]["operators_max"],
        })


# -- the wire protocol -------------------------------------------------

# Join options a wire request may set (everything else a query could
# name is a server-side policy, not a per-request knob).
_WIRE_JOIN_OPTS = (
    "shuffle", "over_decomposition", "shuffle_capacity_factor",
    "out_capacity_factor", "compression_bits", "skew_threshold",
    "dcn_codec", "aggregate", "sort_mode", "sort_segments",
)

# Plan-level defaults a `query` wire request may set; applied to
# every operator of the plan (the plan's own per-op options win).
# No "aggregate" here — a plan carries its own fused aggregate.
_WIRE_QUERY_OPTS = (
    "shuffle", "over_decomposition", "shuffle_capacity_factor",
    "out_capacity_factor", "compression_bits", "skew_threshold",
    "dcn_codec",
)


def _tables_from_spec(spec: dict):
    """Generate the (build, probe) pair a wire query names. The demo
    data plane: deterministic generator tables keyed by the request's
    seed — a resident deployment embeds :class:`JoinService` and hands
    it real device tables instead."""
    from distributed_join_tpu.utils.generators import (
        generate_build_probe_tables,
    )

    return generate_build_probe_tables(
        seed=int(spec.get("seed", 42)),
        build_nrows=int(spec["build_nrows"]),
        probe_nrows=int(spec["probe_nrows"]),
        rand_max=(int(spec["rand_max"]) if spec.get("rand_max")
                  else None),
        selectivity=float(spec.get("selectivity", 0.3)),
        unique_build_keys=bool(spec.get("unique_build_keys", False)),
    )


def _query_from_spec(spec: dict):
    """The ``(tables, plan)`` pair a ``query`` wire request names.
    Demo data plane like :func:`_tables_from_spec`: the canonical
    TPC-H plan plus deterministic generator tables keyed by the
    request's seed, filtered by the query's predicates — an embedding
    deployment calls :meth:`JoinService.query` with real tables and
    an arbitrary :class:`~..planning.query.QueryPlan` instead."""
    from distributed_join_tpu.planning.query import tpch_query_plan
    from distributed_join_tpu.utils.tpch import (
        generate_tpch_query_tables,
        query_filters,
    )

    q = str(spec.get("query", "q3"))
    plan = tpch_query_plan(q)
    tables = generate_tpch_query_tables(
        seed=int(spec.get("seed", 42)),
        scale_factor=float(spec.get("scale_factor", 0.01)))
    return query_filters(tables, q), plan


def _join_opts_from_spec(spec: dict) -> dict:
    opts = {k: spec[k] for k in _WIRE_JOIN_OPTS if spec.get(k)
            is not None}
    if "aggregate" in opts:
        # The wire form ({"group_by": [...], "aggs": [["sum", col],
        # ...], ...}) becomes the canonical AggregateSpec the step,
        # signature, and plan all key on (docs/AGGREGATION.md).
        from distributed_join_tpu.ops.aggregate import AggregateSpec

        opts["aggregate"] = AggregateSpec.from_wire(opts["aggregate"])
    return opts


def _build_from_spec(spec: dict):
    """The build-side table a ``register``/``append`` wire request
    names (demo data plane, like :func:`_tables_from_spec`): a
    deterministic generator table keyed by the request's seed. A
    resident deployment embeds :class:`JoinService` and hands
    ``register_table`` real device tables instead.

    The PRNG key is derived EXACTLY as
    ``generate_build_probe_tables(seed=...)`` derives its build key
    (the first split of ``PRNGKey(seed)``), so a resident ``join``
    whose probe spec reuses the registration seed draws its hit keys
    from the table that was actually registered — ``selectivity``
    keeps its hit-fraction meaning on the wire."""
    import jax

    from distributed_join_tpu.utils.generators import (
        generate_build_table,
    )

    rows = int(spec["rows"])
    kb, _ = jax.random.split(
        jax.random.PRNGKey(int(spec.get("seed", 42))))
    return generate_build_table(
        kb,
        rows,
        int(spec.get("rand_max") or rows),
        unique_keys=bool(spec.get("unique_keys", False)),
    )


def _probe_from_spec(spec: dict, handle):
    """The probe table of a resident ``join`` request: drawn against
    the registered table's stashed base KEY column, so
    ``selectivity`` keeps its hit-fraction meaning without
    regenerating the whole build per request (per-request wire work
    scales with the probe, not the resident table — the point of the
    resident tier). The PRNG key derivation matches
    ``generate_build_probe_tables`` exactly (the second split), so a
    request seed equal to the registration seed draws the identical
    probe the combined generator would."""
    import jax

    from distributed_join_tpu.utils.generators import (
        generate_build_probe_tables,
        generate_probe_table,
    )

    base = handle.wire_spec or {}
    rows = int(spec["probe_nrows"])
    seed = int(spec.get("seed", base.get("seed", 42)))
    rand_max = (int(spec.get("rand_max") or base.get("rand_max") or 0)
                or int(base.get("rows", rows)))
    if handle.wire_build_keys is not None:
        _, kp = jax.random.split(jax.random.PRNGKey(seed))
        return generate_probe_table(
            kp, rows, rand_max,
            float(spec.get("selectivity", 0.3)),
            handle.wire_build_keys,
        )
    # Tables registered in-process (no wire spec): fall back to the
    # combined generator at the probe's own scale.
    _, probe = generate_build_probe_tables(
        seed=seed,
        build_nrows=int(base.get("rows", rows)),
        probe_nrows=rows,
        rand_max=rand_max,
        selectivity=float(spec.get("selectivity", 0.3)),
        unique_build_keys=bool(base.get("unique_keys", False)),
    )
    return probe


class _Handler(socketserver.StreamRequestHandler):
    """One JSON object per line in -> one JSON object per line out."""

    def handle(self):
        for raw in self.rfile:
            line = raw.decode("utf-8").strip()
            if not line:
                continue
            req = None
            ctx = None
            try:
                req = json.loads(line)
                # Distributed tracing (docs/OBSERVABILITY.md): adopt
                # the wire-carried trace as this process's scope — a
                # fresh span id parented on the SENDER's span, so
                # every event/span/flight/history record this request
                # emits here carries the cross-process causal key.
                # None when the request carries no trace (tracing is
                # always optional, and off = the exact old path).
                ctx = tracectx.child_of_wire(req)
                # Multi-tenancy (docs/FLEET.md): the optional wire
                # `tenant` field scopes this request's accounting —
                # admission refusals, live counters, flight records,
                # history lines — exactly like the trace context.
                # Absent = default tenant = the pre-tenancy records.
                with telemetry.request_scope(None, trace=ctx), \
                        tel_history.tenant_scope(req.get("tenant")):
                    resp = self._dispatch(req)
            except Exception as exc:  # noqa: BLE001 - wire boundary:
                # a bad request must answer THAT client, not kill the
                # daemon serving everyone else.
                resp = {"ok": False, "error": type(exc).__name__,
                        "message": str(exc)}
            if ctx is not None and isinstance(resp, dict):
                # Echo the server-side span so the caller can record
                # the edge without grepping this process's files.
                resp.setdefault(tracectx.TRACE_FIELD,
                                tracectx.to_wire(ctx))
            self.wfile.write(
                (json.dumps(resp) + "\n").encode("utf-8"))
            self.wfile.flush()
            if isinstance(req, dict) \
                    and req.get("op") in ("shutdown", "drain") \
                    and resp.get("ok"):
                return

    def _dispatch(self, req: dict) -> dict:
        service: JoinService = self.server.service
        op = req.get("op")
        if op == "ping":
            return {"ok": True, "op": "ping"}
        if op == "stats":
            return {"ok": True, **service.stats()}
        if op == "metrics":
            if req.get("format") == "prometheus":
                return {"ok": True, "op": "metrics",
                        "format": "prometheus",
                        "prometheus": service.prometheus_metrics()}
            return {"ok": True, "op": "metrics",
                    "metrics": service.metrics_snapshot()}
        if op == "shutdown":
            # Close the admission window FIRST (a join admitted after
            # the quiesce would still race the exit), then wait
            # (bounded) for any join still dispatching on OTHER
            # connections before acknowledging: the old reply-first
            # behavior let a smoke-driven shutdown race a live SPMD
            # dispatch. shutdown() itself must not run on the handler
            # thread (it joins the serve_forever loop, which is
            # waiting on us).
            with service._admit_lock:
                if service.draining is None:
                    service.draining = "shutdown"
            quiesced = service.quiesce(
                timeout_s=float(req.get("quiesce_timeout_s", 30.0)),
                settle_admissions=True)
            threading.Thread(target=self.server.shutdown,
                             daemon=True).start()
            return {"ok": True, "op": "shutdown",
                    "quiesced": quiesced}
        if op == "drain":
            # Graceful handoff (docs/FLEET.md): refuse new admissions
            # with DrainingError, settle in-flight requests under
            # their watchdog deadlines, flush history + flight
            # recorder, then exit 0 exactly like shutdown.
            rec = service.drain(
                reason=str(req.get("reason", "drain wire op")),
                settle_timeout_s=float(
                    req.get("settle_timeout_s", 60.0)))
            threading.Thread(target=self.server.shutdown,
                             daemon=True).start()
            return {"ok": True, "op": "drain", **rec}
        if op == "explain":
            # Admission-free dry run: the spec's shapes become
            # abstract tables (the generator schema — no data, no
            # device), and the plan/cost/cache verdict comes back
            # with ZERO traces or compiles (docs/SERVICE.md).
            from distributed_join_tpu import planning

            build, probe = planning.abstract_tables(
                int(req["build_nrows"]), int(req["probe_nrows"]))
            out = service.explain(build, probe,
                                  **_join_opts_from_spec(req))
            return {"ok": True, "op": "explain", **out}
        if op == "register":
            # Resident build tables (docs/SERVICE.md "Resident build
            # tables"): run the build-side 2/3 once and hold the
            # sorted shards on-device under req["name"].
            build = _build_from_spec(req)
            rec = service.register_table(
                str(req["name"]), build,
                replace=bool(req.get("replace", False)),
                request_id=req.get("request_id"),
                wire_spec={k: req[k] for k in
                           ("rows", "seed", "rand_max", "unique_keys")
                           if req.get(k) is not None})
            return {"ok": True, "op": "register", **rec}
        if op == "append":
            delta = _build_from_spec(req)
            rec = service.append_rows(
                str(req["name"]), delta,
                maintain=req.get("maintain"),
                request_id=req.get("request_id"))
            return {"ok": True, "op": "append", **rec}
        if op == "drop":
            rec = service.drop_table(str(req["name"]),
                                     request_id=req.get("request_id"))
            return {"ok": True, "op": "drop", **rec}
        if op == "tables":
            return {"ok": True, "op": "tables",
                    **service.resident.stats()}
        if op == "join" and req.get("table"):
            # Probe-only serving against a registered table: the wire
            # ships the PROBE spec only — the build side never rides
            # the wire again.
            name = str(req["table"])
            from distributed_join_tpu.service.resident import (
                ResidentError,
                StaleGenerationError,
            )

            try:
                handle = service.resident.get(name)
            except ResidentError as exc:
                # Refused before admission — still observed (history
                # line, flight record, live failure counter).
                service.note_refused_resident(
                    name, req.get("request_id"), exc)
                raise
            ming = req.get("min_generation")
            if ming is not None and handle.generation < int(ming):
                # Generation fence (docs/FAILURE_SEMANTICS.md): this
                # holder missed an append fan-out — serving now would
                # silently exclude the missed delta. Refuse loudly;
                # the fleet router retries on an up-to-date holder.
                exc = StaleGenerationError(
                    f"resident table {name!r} is at generation "
                    f"{handle.generation} < required {int(ming)} "
                    "(this holder missed an append); probe-only "
                    "serving refused — retry on an up-to-date holder")
                service.note_refused_resident(
                    name, req.get("request_id"), exc)
                raise exc
            probe = _probe_from_spec(req, handle)
            t0 = time.perf_counter()
            res = service.resident_join(
                name, probe, request_id=req.get("request_id"),
                **_join_opts_from_spec(req))
            elapsed = time.perf_counter() - t0
            return {
                "ok": True,
                "request_id": getattr(res, "request_id", None),
                "table": name,
                "resident": getattr(res, "resident", None),
                "matches": int(res.total),
                "groups": getattr(res, "agg_groups", None),
                "overflow": bool(res.overflow),
                "elapsed_s": elapsed,
                "new_traces": getattr(res, "new_traces", 0),
                "retry": res.retry_report.as_record(),
                "cache": service.cache.stats(),
            }
        if op == "join":
            build, probe = _tables_from_spec(req)
            t0 = time.perf_counter()
            res = service.join(build, probe,
                               request_id=req.get("request_id"),
                               **_join_opts_from_spec(req))
            matches = int(res.total)
            elapsed = time.perf_counter() - t0
            retry = res.retry_report.as_record()
            return {
                "ok": True,
                # minted at admission; one id correlates this
                # response with the daemon's JSONL/trace views
                "request_id": getattr(res, "request_id", None),
                "matches": matches,
                # Aggregation pushdown: the group count the fused
                # pipeline emitted (None for materializing joins).
                "groups": getattr(res, "agg_groups", None),
                "overflow": bool(res.overflow),
                "elapsed_s": elapsed,
                # accounted under the service's exec lock, so a
                # concurrent connection's compile is never billed here
                "new_traces": getattr(res, "new_traces", 0),
                "retry": retry,
                "cache": service.cache.stats(),
            }
        if op == "batch":
            specs = req.get("requests") or []
            pairs = [_tables_from_spec(s) for s in specs]
            t0 = time.perf_counter()
            results = service.join_batched(
                pairs,
                request_id=req.get("request_id"),
                slot_build_rows=req.get("slot_build_rows"),
                slot_probe_rows=req.get("slot_probe_rows"),
                **_join_opts_from_spec(req))
            elapsed = time.perf_counter() - t0
            return {
                "ok": True,
                "request_id": (results[0]["request_id"]
                               if results else None),
                "requests": results,
                "matches": sum(r["matches"] for r in results),
                "elapsed_s": elapsed,
                "new_traces": (results[0]["new_traces"]
                               if results else 0),
                "cache": service.cache.stats(),
            }
        if op == "query":
            # One multi-operator plan as ONE SPMD program
            # (docs/QUERY.md): the wire names a canonical TPC-H query
            # and the demo data plane's shape knobs; the whole chain
            # — every join plus the fused aggregate — resolves
            # through the program cache under the plan digest.
            tables, plan = _query_from_spec(req)
            opts = {k: req[k] for k in _WIRE_QUERY_OPTS
                    if req.get(k) is not None}
            t0 = time.perf_counter()
            res = service.query(tables, plan,
                                request_id=req.get("request_id"),
                                **opts)
            elapsed = time.perf_counter() - t0
            return {
                "ok": True,
                "request_id": getattr(res, "request_id", None),
                "query": req.get("query", "q3"),
                "digest": plan.digest(),
                "n_operators": plan.n_operators(),
                "rows": int(res.total),
                "op_totals": [int(t) for t in res.op_totals],
                "groups": getattr(res, "groups", None),
                "overflow": bool(res.overflow),
                "retry_attempts": getattr(res, "retry_attempts", 0),
                "elapsed_s": elapsed,
                "new_traces": getattr(res, "new_traces", 0),
                "cache": service.cache.stats(),
            }
        raise ValueError(f"unknown op {op!r} (ops: ping, stats, "
                         "metrics, explain, join, batch, query, "
                         "register, append, tables, drop, drain, "
                         "shutdown)")


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr, service: JoinService):
        super().__init__(addr, _Handler)
        self.service = service


def start_daemon(service: JoinService, host: str = "127.0.0.1",
                 port: int = 0):
    """Bind + serve on a background thread; returns ``(server, port)``.
    ``server.shutdown()`` (or the wire ``shutdown`` op) stops it."""
    server = _Server((host, port), service)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, server.server_address[1]


class ServiceClient:
    """Line-protocol client over one persistent connection (the smoke
    protocol, the ``--watch`` console, the fleet router's replica
    legs, and tests; also a template for real callers).

    ``retries`` arms bounded reconnect with jittered exponential
    backoff: a torn connection, a half-written response line, or a
    restarting daemon is reconnected and the payload RESENT — but
    only for the IDEMPOTENT ops (:data:`RESENDABLE_OPS`: reads plus
    join/batch, whose specs recompute the same answer). A mutating
    op (``append``, ``register``, ...) whose connection tears after
    the write may already have been APPLIED, so it fails loudly
    instead of double-applying. The terminal
    :class:`ConnectionError` surfaces the attempt count; the default
    ``retries=0`` keeps the historical fail-on-first-tear
    behavior."""

    # Ops a retry-armed client may safely resend after a torn
    # connection: reads, plus the query ops whose wire carries specs
    # (same spec -> same deterministic answer). Everything else
    # mutates server state (append would double-apply its delta).
    RESENDABLE_OPS = frozenset(
        ("ping", "stats", "metrics", "explain", "tables",
         "join", "batch"))

    def __init__(self, host: str, port: int, timeout_s: float = 600.0,
                 *, retries: int = 0, backoff_s: float = 0.2):
        self._addr = (host, port)
        self._timeout_s = timeout_s
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self._sock = None
        self._file = None
        self._with_retries(self._connect, "connect to")

    def _connect(self):
        self.close()
        self._sock = socket.create_connection(self._addr,
                                              self._timeout_s)
        self._file = self._sock.makefile("rw", encoding="utf-8",
                                         newline="\n")

    def _with_retries(self, fn, what: str):
        import random

        attempts = 0
        delay = self.backoff_s
        while True:
            attempts += 1
            try:
                return fn()
            except (OSError, ValueError) as exc:
                # OSError covers refused/reset/timeout; ValueError is
                # json.loads on a torn half-written response line.
                # _no_resend marks the mutating-op tear guard —
                # terminal by design, never retried.
                self.close()
                if attempts > self.retries \
                        or getattr(exc, "_no_resend", False):
                    raise ConnectionError(
                        f"cannot {what} {self._addr[0]}:"
                        f"{self._addr[1]} after {attempts} attempt(s)"
                        f": {type(exc).__name__}: {exc}") from exc
                time.sleep(delay * random.uniform(0.5, 1.5))
                delay *= 2

    def send(self, payload: dict) -> dict:
        # Distributed tracing (docs/OBSERVABILITY.md): every wire op
        # carries a trace context. A caller-supplied one (the router's
        # per-attempt child span, a console's --trace-id) rides
        # untouched; otherwise THIS client is the trace root and mints
        # it here — once per logical send, so a reconnect-and-resend
        # (including the HA-takeover resend) JOINS the original trace
        # instead of starting a new one.
        if payload.get(tracectx.TRACE_FIELD) is None:
            payload = tracectx.attach(payload, tracectx.mint())
        ctx = tracectx.from_wire(payload)
        resendable = payload.get("op") in self.RESENDABLE_OPS
        wrote = {"flag": False}

        def once():
            if self._file is None:
                self._connect()
            if wrote["flag"]:
                # Resend of the SAME trace: narrate the link so the
                # timeline shows the abandoned attempt (no-op with
                # telemetry off).
                telemetry.event(
                    "client_resend", op=payload.get("op"),
                    request_id=payload.get("request_id"),
                    **tracectx.stamp(ctx))
            if wrote["flag"] and not resendable:
                # The earlier attempt's write may have been applied
                # server-side; a mutating op must not go out twice.
                err = ConnectionError(
                    f"connection torn after sending mutating op "
                    f"{payload.get('op')!r}; not resending (the op "
                    "may already have been applied)")
                err._no_resend = True
                raise err
            wrote["flag"] = True
            self._file.write(json.dumps(payload) + "\n")
            self._file.flush()
            line = self._file.readline()
            if not line:
                raise ConnectionError(
                    "service closed the connection")
            return json.loads(line)

        return self._with_retries(once, "reach")

    def close(self) -> None:
        for h in (self._file, self._sock):
            try:
                if h is not None:
                    h.close()
            except OSError:  # pragma: no cover - teardown boundary
                pass
        self._file = self._sock = None


# -- the operator watch console ----------------------------------------


def watch(host: str, port: int, interval_s: float = 2.0,
          count: int = 0, out=None, retries: int = 3,
          trace_id: Optional[str] = None) -> int:
    """Poll a RUNNING daemon's ``metrics`` op and render one console
    line per poll — the operator's ``top`` for the join service. Read
    only: no mesh, no bootstrap, works from any machine that can reach
    the port. ``count=0`` polls until interrupted. ``retries`` rides
    the client's jittered-backoff reconnect, so a replica restarting
    under the console (the fleet's replace path) resumes instead of
    tearing the watch down; only a daemon gone past the budget yields
    the one-line error (with the attempt count) and rc 1."""
    out = out or sys.stdout
    try:
        client = ServiceClient(host, port, timeout_s=30.0,
                               retries=retries)
    except OSError as exc:
        # An operator console answers with one line, not a traceback.
        print(f"cannot reach daemon at {host}:{port}: {exc}",
              file=out, flush=True)
        return 1
    polls = 0

    def ms(v):
        return f"{v * 1e3:.1f}ms" if v else "-"

    try:
        while True:
            poll: dict = {"op": "metrics"}
            if trace_id:
                # Client-minted trace id, honored end to end (capped
                # under the request-id prefix+sha256 scheme): every
                # poll is a fresh root span of the SAME trace, so one
                # timeline shows the whole watch session.
                poll = tracectx.attach(poll, tracectx.mint(trace_id))
            resp = client.send(poll)
            if not resp.get("ok"):
                print(f"metrics op failed: {resp}", file=out,
                      flush=True)
                return 1
            m = resp["metrics"]
            st = m["stats"]
            lat = st.get("latency") or {}
            line = (
                f"up {m['uptime_s']:8.1f}s  "
                f"qps {m['qps_60s']:6.2f}  "
                f"served {st['served']:6d}  "
                f"failed {st['failed']:4d}  "
                f"rejected {st['rejected']:4d}  "
                f"inflight {st['inflight']:2d}  "
                f"p50 {ms(lat.get('p50_s'))}  "
                f"p95 {ms(lat.get('p95_s'))}  "
                f"p99 {ms(lat.get('p99_s'))}  "
                f"cache {st['cache']['hits']}h/"
                f"{st['cache']['traces']}t"
            )
            # Per-op quantiles (the overall p50/p95/p99 above hides a
            # slow batch path behind fast joins).
            for opname, ol in sorted(
                    (st.get("latency_by_op") or {}).items()):
                line += (f"  {opname}[{ms(ol.get('p50_s'))}/"
                         f"{ms(ol.get('p95_s'))}/"
                         f"{ms(ol.get('p99_s'))}]")
            # Per-tenant segment (docs/FLEET.md "Multi-tenancy"):
            # QPS, shed count, p95 per tenant — absent entirely for
            # tenant-free traffic, so the pre-tenancy line survives
            # byte-identical.
            for tname, ts in sorted(
                    (st.get("tenants") or {}).items()):
                tlat = ts.get("latency") or {}
                line += (f"  {tname}{{qps "
                         f"{ts.get('qps_60s') or 0:.2f} shed "
                         f"{ts.get('shed') or 0} p95 "
                         f"{ms(tlat.get('p95_s'))}}}")
            if st.get("poisoned"):
                line += f"  POISONED: {st['poisoned']}"
            print(line, file=out, flush=True)
            polls += 1
            if count and polls >= count:
                return 0
            time.sleep(interval_s)
    except KeyboardInterrupt:
        return 0
    except (OSError, ValueError) as exc:
        # daemon restarted or went away mid-poll (ConnectionError,
        # socket timeout, a torn half-written response line =
        # JSONDecodeError) — report and exit, don't stack-trace
        print(f"lost daemon at {host}:{port}: {exc}", file=out,
              flush=True)
        return 1
    finally:
        client.close()


# -- the CLI daemon ----------------------------------------------------


def parse_args(argv=None):
    from distributed_join_tpu.benchmarks import (
        add_platform_arg,
        add_robustness_args,
        add_telemetry_args,
    )

    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="TCP port (0 = ephemeral; the bound port is "
                        "printed on the 'listening' line)")
    p.add_argument("--n-ranks", type=int, default=None,
                   help="mesh size; default all visible devices")
    p.add_argument("--communicator", default="tpu")
    p.add_argument("--slices", type=int, default=None,
                   help="serve on a 2-D (slice, chip) hierarchical "
                        "mesh (docs/HIERARCHY.md); wire joins may "
                        "then request shuffle='hierarchical'")
    p.add_argument("--auto-retry", type=int, default=2,
                   help="capacity-ladder budget applied to every "
                        "request (rungs reuse cached executables)")
    p.add_argument("--max-pending", type=int, default=8,
                   help="admission bound: requests beyond this many "
                        "pending are refused, not queued")
    p.add_argument("--max-batch-requests", type=int, default=64)
    p.add_argument("--max-programs", type=int, default=128,
                   help="resident-executable bound (LRU-evicted in "
                        "memory; persisted blobs survive): the wire "
                        "lets every request pick its own table shape, "
                        "and each shape is a compiled program")
    p.add_argument("--max-resident-tables", type=int, default=8,
                   help="resident build tables held on-device at "
                        "once (register refuses beyond this; "
                        "docs/SERVICE.md 'Resident build tables')")
    p.add_argument("--resident-capacity-factor", type=float,
                   default=1.5,
                   help="delta headroom a registration sizes its "
                        "resident shards with (appended rows beyond "
                        "capacity refuse loudly at merge time)")
    p.add_argument("--maintain-runs", type=int, default=4,
                   help="pending LSM delta runs that trigger the "
                        "maintenance merge on append (joins always "
                        "merge any pending queue first)")
    p.add_argument("--persist-dir", default=None, metavar="DIR",
                   help="persist compiled executables under DIR (the "
                        "AOT serialization tier): a restarted server "
                        "skips even the first trace")
    p.add_argument("--history-dir", default=None, metavar="DIR",
                   help="append one workload-history line per request "
                        "to DIR/history.jsonl (telemetry/history.py — "
                        "counter signature, indicators, resolved "
                        "knobs, wall time; summarize with `analyze "
                        "history`). Default: --persist-dir when set")
    p.add_argument("--history-max-entries", type=int, default=None,
                   metavar="N",
                   help="bound the history store: keep the last N "
                        "live entries per workload signature, "
                        "compacting older ones into one rolled-up "
                        "summary line per signature (the per-"
                        "signature trend survives, the file stops "
                        "growing). Default: unbounded")
    p.add_argument("--flight-records", type=int, default=256,
                   help="flight-recorder ring size: the last-N "
                        "per-request records dumped as "
                        "flightrecorder.json on poison or terminal "
                        "error")
    p.add_argument("--flight-recorder-path", default=None,
                   metavar="FILE",
                   help="where the flight-recorder dump lands "
                        "(default: the telemetry session dir, else "
                        "the history dir, else ./flightrecorder.json)")
    p.add_argument("--watch", action="store_true",
                   help="do not serve: poll the RUNNING daemon at "
                        "--host/--port and print one metrics line per "
                        "poll (the operator console; ctrl-C to stop)")
    p.add_argument("--watch-interval-s", type=float, default=2.0,
                   help="seconds between --watch polls")
    p.add_argument("--watch-count", type=int, default=0,
                   help="stop --watch after N polls (0 = until "
                        "interrupted)")
    p.add_argument("--trace-id", default=None, metavar="ID",
                   help="client-minted distributed-trace id attached "
                        "to every --watch poll and smoke request "
                        "(capped/aliased like long request ids; "
                        "docs/OBSERVABILITY.md 'Distributed "
                        "tracing')")
    p.add_argument("--smoke", action="store_true",
                   help="run the CI smoke protocol against an "
                        "in-process daemon instead of serving: warm "
                        "cache discipline + batched-vs-sequential + "
                        "metrics scrape + poison drill "
                        "(docs/SERVICE.md), JSON record on stdout")
    p.add_argument("--smoke-small-rows", type=int, default=256,
                   help="rows per small join in the smoke's batched-"
                        "vs-sequential comparison")
    p.add_argument("--smoke-batch", type=int, default=16,
                   help="small joins per smoke micro-batch")
    p.add_argument("--smoke-resident-joins", type=int, default=3,
                   help="timed joins per side in the smoke's "
                        "resident A/B (probe-only vs cold full "
                        "join; min wall is compared)")
    p.add_argument("--smoke-no-wall-gate", action="store_true",
                   help="report the batched-vs-sequential wall clocks "
                        "but do not FAIL on them (the perfgate lane "
                        "gates counters only; the service lane keeps "
                        "the strict timing gate)")
    p.add_argument("--fault-plan", default=None, metavar="JSON",
                   help="wrap the communicator in a SCRIPTED "
                        "FaultPlan (parallel/faults.py fields as one "
                        "JSON object, e.g. '{\"dispatch_delay_s\": "
                        "3.0}') — the fleet chaos harness's seam for "
                        "arming one replica with a deterministic "
                        "outage; --chaos-seed draws a random plan "
                        "instead")
    p.add_argument("--json-output", default=None)
    add_platform_arg(p)
    add_telemetry_args(p)
    add_robustness_args(p)
    return p.parse_args(argv)


def _service_from_args(args) -> JoinService:
    from distributed_join_tpu.benchmarks import (
        apply_platform,
        maybe_chaos_communicator,
    )
    from distributed_join_tpu.parallel.communicator import (
        make_communicator,
    )

    apply_platform(args.platform, args.n_ranks)
    comm = maybe_chaos_communicator(
        make_communicator(args.communicator, n_ranks=args.n_ranks,
                          n_slices=getattr(args, "slices", None)),
        args)
    if getattr(args, "fault_plan", None):
        from distributed_join_tpu.parallel.faults import (
            FaultInjectingCommunicator,
            plan_from_record,
        )

        comm = FaultInjectingCommunicator(
            comm, plan_from_record(json.loads(args.fault_plan)))
    cfg = ServiceConfig(
        auto_retry=args.auto_retry,
        verify_integrity=args.verify_integrity,
        request_deadline_s=args.request_deadline_s,
        max_pending=args.max_pending,
        max_batch_requests=args.max_batch_requests,
        max_programs=args.max_programs,
        persist_dir=args.persist_dir,
        history_dir=args.history_dir,
        history_max_entries=args.history_max_entries,
        # --auto-tune (shared flag, benchmarks.add_robustness_args):
        # bare = learn from this service's own history store; a PATH
        # additionally pre-loads that file's trends at startup.
        auto_tune=args.auto_tune is not None,
        tuner_history=(args.auto_tune or None),
        flight_records=args.flight_records,
        flight_recorder_path=args.flight_recorder_path,
        max_resident_tables=args.max_resident_tables,
        resident_capacity_factor=args.resident_capacity_factor,
        maintain_runs=args.maintain_runs,
    )
    return JoinService(comm, cfg)


def run(args) -> dict:
    service = _service_from_args(args)
    from distributed_join_tpu.benchmarks import report

    if args.smoke:
        record = run_smoke(service, args)
    else:
        server = _Server((args.host, args.port), service)
        port = server.server_address[1]

        def _drain_and_stop():
            service.drain(reason="SIGTERM")
            server.shutdown()

        def _on_sigterm(signum, frame):  # noqa: ARG001 - signal API
            # Graceful drain off the signal frame: refuse new
            # admissions, settle in-flight, flush artifacts, exit 0
            # (the fleet's replace path SIGTERMs before SIGKILL).
            threading.Thread(target=_drain_and_stop,
                             daemon=True).start()

        try:
            import signal

            # Installed BEFORE the listening line: a supervisor that
            # SIGTERMs the instant the port is announced must hit
            # the graceful path, not the default disposition.
            signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:  # pragma: no cover - non-main thread
            pass
        print(f"join-service listening on {args.host}:{port}",
              flush=True)
        try:
            # Serve on THIS thread; the wire shutdown op (handled on a
            # connection thread) calls server.shutdown(), which makes
            # serve_forever return.
            server.serve_forever()
        except KeyboardInterrupt:
            pass
        except BaseException:
            # Terminal daemon error: leave the postmortem ring next
            # to the failure record (poison dumps already happened at
            # hang time; this covers everything else fatal).
            service.dump_flight_recorder("daemon terminal error")
            raise
        finally:
            server.server_close()
        record = {"benchmark": "service", **service.stats()}
    report(
        f"join-service: {record.get('served', 0)} request(s) served, "
        f"{record['cache']['traces']} trace(s), "
        f"{record['cache']['hits']} cache hit(s)",
        record, args.json_output,
    )
    return record


def _poison_drill(n_ranks: int, args) -> dict:
    """The smoke's fail-stop rehearsal: on a THROWAWAY service (its
    own communicator + cache — the real serving mesh is untouched), a
    fault-delayed request blows its watchdog deadline, the poison flag
    trips, a follow-up request is refused, and the flight recorder
    dumps a schema-valid ``flightrecorder.json`` — the postmortem loop
    of docs/OBSERVABILITY.md, end to end. Runs with
    ``with_metrics=False`` so the detached worker that eventually
    finishes the hung join cannot emit device metrics over the main
    smoke's (baseline-gated) counter block."""
    from distributed_join_tpu.parallel.communicator import (
        make_communicator,
    )
    from distributed_join_tpu.parallel.faults import (
        FaultInjectingCommunicator,
        FaultPlan,
    )
    from distributed_join_tpu.parallel.watchdog import HangError
    from distributed_join_tpu.utils.generators import (
        generate_build_probe_tables,
    )

    comm = FaultInjectingCommunicator(
        make_communicator(getattr(args, "communicator", "tpu"),
                          n_ranks=n_ranks),
        FaultPlan(dispatch_delay_s=3.0))
    drill = JoinService(comm, ServiceConfig(
        auto_retry=0, request_deadline_s=0.75,
        flight_recorder_path=args.flight_recorder_path))
    b, p = generate_build_probe_tables(
        seed=11, build_nrows=512, probe_nrows=1024, rand_max=256,
        selectivity=0.5)
    try:
        drill.join(b, p, with_metrics=False, out_capacity_factor=4.0)
    except HangError:
        pass
    else:
        raise RuntimeError(
            "poison drill: the delayed request did not hang")
    if not drill.poisoned:
        raise RuntimeError("poison drill: service was not poisoned")
    try:
        drill.join(b, p, with_metrics=False, out_capacity_factor=4.0)
    except AdmissionError:
        pass
    else:
        raise RuntimeError(
            "poison drill: the poisoned service accepted a join")
    path = drill.flight_recorder_dumped
    if not path or not os.path.exists(path):
        raise RuntimeError("poison drill: no flightrecorder.json "
                           "was dumped on poison")
    # Drain the detached watchdog worker before the process moves on:
    # it is still tracing/running the hung join, and it must not
    # overlap the interpreter's exit.
    for t in threading.enumerate():
        if t.name.startswith("watchdog-request"):
            t.join(timeout=120.0)
    return {
        "poisoned": True,
        "flightrecorder": path,
        "flight_records": len(drill.recorder),
        "rejected_after_poison": drill.rejected,
    }


def _resident_drill(service: JoinService, args, violations) -> dict:
    """The smoke's resident A/B (docs/SERVICE.md "Resident build
    tables"): register a build table once, then N probe-only joins vs
    N cold full joins of the same query — the warm probe-only joins
    must add ZERO traces and (unless ``--smoke-no-wall-gate``) beat
    the warm full joins on the noise-robust minimum wall; after two
    LSM delta merges the probe-only answer must equal the pandas
    oracle over the combined build. Runs IN-PROCESS with
    ``with_metrics=False`` on every join so the telemetry session's
    final counter block — the ``service_smoke`` baseline gate — stays
    exactly the batched join's. The returned record carries a
    deterministic counter signature gated against
    ``results/baselines/resident_smoke.json`` (the ``resident`` and
    ``perfgate`` lanes)."""
    from distributed_join_tpu.utils.generators import (
        generate_build_probe_tables,
        generate_build_table,
    )
    import jax

    n_joins = args.smoke_resident_joins
    build, probe = generate_build_probe_tables(
        seed=7, build_nrows=16384, probe_nrows=2048, rand_max=8192,
        selectivity=0.5)
    deltas = [generate_build_table(jax.random.PRNGKey(s), 1024, 8192)
              for s in (8, 9)]
    opts = dict(with_metrics=False, out_capacity_factor=3.0)
    name = "smoke_dim"

    reg = service.register_table(name, build)

    def timed(fn):
        walls, matches, traces = [], [], 0
        for _ in range(n_joins):
            t0 = time.perf_counter()
            res = fn()
            walls.append(time.perf_counter() - t0)
            matches.append(int(res.total))
            traces += getattr(res, "new_traces", 0)
        return walls, matches, traces

    # Warm both programs outside the timing (compiles happen here).
    service.join(build, probe, **opts)
    service.resident_join(name, probe, **opts)
    cold_walls, cold_matches, cold_traces = timed(
        lambda: service.join(build, probe, **opts))
    po_walls, po_matches, po_traces = timed(
        lambda: service.resident_join(name, probe, **opts))
    if po_traces or cold_traces:
        violations.append(
            f"resident drill: timed warm passes traced programs "
            f"(probe-only {po_traces}, cold {cold_traces})")
    if po_matches != cold_matches:
        violations.append(
            f"resident drill: probe-only matches {po_matches} != "
            f"cold full-join matches {cold_matches}")
    if min(po_walls) >= min(cold_walls) \
            and not args.smoke_no_wall_gate:
        violations.append(
            f"resident drill: warm probe-only ({min(po_walls):.4f}s "
            "min) did not beat the warm cold full join "
            f"({min(cold_walls):.4f}s min)")

    # Streaming ingestion: two delta appends, each merged LSM-style;
    # the probe-only answer must match the pandas oracle over the
    # combined build, and the repeat at the new generation is warm.
    for d in deltas:
        service.append_rows(name, d, maintain=True)
    handle = service.resident.get(name)
    res_after = service.resident_join(name, probe, **opts)
    res_warm = service.resident_join(name, probe, **opts)
    if res_warm.new_traces:
        violations.append(
            "resident drill: post-append warm repeat traced "
            f"{res_warm.new_traces} program(s)")
    import pandas as pd

    combined = pd.concat([build.to_pandas()]
                         + [d.to_pandas() for d in deltas])
    oracle_after = len(combined.merge(probe.to_pandas(), on="key"))
    if int(res_after.total) != oracle_after:
        violations.append(
            f"resident drill: matches after {len(deltas)} LSM "
            f"merges = {int(res_after.total)} != pandas oracle "
            f"{oracle_after}")
    if handle.generation != 1 + len(deltas):
        violations.append(
            f"resident drill: generation {handle.generation} != "
            f"{1 + len(deltas)} after {len(deltas)} appends")

    stats = service.resident.stats()
    return {
        "kind": "resident_drill",
        "benchmark": "resident_smoke",
        "n_ranks": service.comm.n_ranks,
        "table": name,
        "registered_rows": reg["rows"],
        "joins_per_side": n_joins,
        "cold_wall_min_s": min(cold_walls),
        "probe_only_wall_min_s": min(po_walls),
        "probe_only_speedup": (min(cold_walls) / min(po_walls)
                               if min(po_walls) else None),
        "resident": stats["tables"][name],
        # The deterministic gate body: integer counters only — walls
        # are never part of a counter signature.
        "counter_signature": {
            "signature_version": 1,
            "n_ranks": service.comm.n_ranks,
            "counters": {
                "base_rows": reg["rows"],
                "delta_rows_appended": 2048,
                "generation": handle.generation,
                "lsm_merges": stats["tables"][name]["merges"],
                "matches_cold": cold_matches[0],
                "matches_probe_only": po_matches[0],
                "matches_after_appends": int(res_after.total),
                "warm_probe_new_traces": int(po_traces),
                "resident_bytes": stats["tables"][name][
                    "bytes_resident"],
            },
        },
    }


def run_smoke(service: JoinService, args) -> dict:
    """The acceptance protocol, end to end THROUGH the daemon's TCP
    loop (docs/SERVICE.md "CI smoke"):

    1. cold query Q compiles; the identical warm repeat must add ZERO
       traces and report a cache hit;
    2. N small joins, warmed, timed sequentially (N dispatches of one
       cached program) vs micro-batched (ONE dispatch) — the batch
       must win wall clock and return the same per-request matches;
    3. the ``metrics`` op must return non-degenerate latency
       quantiles over the warm traffic (and a Prometheus rendering),
       and every join response must echo a unique ``request_id``;
    4. a poison drill on a throwaway service: an induced hang must
       poison it, refuse the next request, and dump a schema-valid
       ``flightrecorder.json``; when a history store is armed
       (``--history-dir``), the smoke's traffic must land >= 2
       distinct workload signatures in it.

    Raises RuntimeError on any violation (run_guarded turns it into a
    failure record with rc != 0)."""
    server, port = start_daemon(service, "127.0.0.1", 0)
    client = ServiceClient("127.0.0.1", port, retries=2)
    violations = []

    def send_ok(payload, what):
        if getattr(args, "trace_id", None):
            # Client-minted trace id honored end to end (capped under
            # the request-id scheme): every smoke request is a fresh
            # root span of the operator's one trace.
            payload = tracectx.attach(
                payload, tracectx.mint(args.trace_id))
        resp = client.send(payload)
        if not resp.get("ok"):
            # surface the service's OWN error, not a downstream
            # KeyError on the missing response fields
            raise RuntimeError(f"{what} failed: {resp}")
        return resp

    try:
        q = {"op": "join", "build_nrows": 4096, "probe_nrows": 4096,
             "seed": 42, "selectivity": 0.3,
             "out_capacity_factor": 3.0}
        cold = send_ok(q, "cold query")
        warm = send_ok(q, "warm query")
        if warm["new_traces"] != 0:
            violations.append(
                f"warm repeat traced {warm['new_traces']} new "
                "program(s); the warm path must be run-only")
        if warm["matches"] != cold["matches"]:
            violations.append("warm matches != cold matches")
        if not cold.get("request_id") or not warm.get("request_id"):
            violations.append("join responses did not echo a "
                              "request_id")
        elif warm["request_id"] == cold["request_id"]:
            violations.append("request ids are not unique per request")

        # EXPLAIN dry-run of the query just served: the plan must come
        # back with ZERO new traces (admission-free host arithmetic)
        # and predict the resident executable as a cache hit.
        traces_before = client.send({"op": "stats"})["cache"]["traces"]
        exp = send_ok({**{kk: v for kk, v in q.items()
                          if kk != "op"}, "op": "explain"},
                      "explain dry-run")
        traces_after = client.send({"op": "stats"})["cache"]["traces"]
        if traces_after != traces_before:
            violations.append(
                f"explain op traced {traces_after - traces_before} "
                "program(s); the dry-run path must not compile")
        if not exp.get("plan", {}).get("signature_digest"):
            violations.append("explain response carries no plan "
                              "digest")
        if not exp.get("cache", {}).get("resident"):
            violations.append(
                "explain did not predict the warm query's resident "
                f"program as a cache hit: {exp.get('cache')}")

        rows = args.smoke_small_rows
        small = [
            {"op": "join", "build_nrows": rows, "probe_nrows": rows,
             "seed": 100 + i, "selectivity": 0.5,
             "rand_max": max(rows // 2, 1),
             "out_capacity_factor": 3.0}
            for i in range(args.smoke_batch)
        ]
        batch_req = {
            "op": "batch", "out_capacity_factor": 3.0,
            "requests": [{k: v for k, v in s.items() if k != "op"}
                         for s in small],
        }
        # Warm both shapes (compile happens here, outside the timing).
        seq_warm = [send_ok(s, "sequential warm-up") for s in small]
        send_ok(batch_req, "batch warm-up")
        # Timed, all-warm passes.
        t0 = time.perf_counter()
        seq = [send_ok(s, "timed sequential") for s in small]
        seq_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        batched = send_ok(batch_req, "timed batch")
        batched_s = time.perf_counter() - t0
        if any(r["new_traces"] for r in seq) or batched["new_traces"]:
            violations.append("timed pass traced new programs")
        seq_matches = [r["matches"] for r in seq]
        batch_matches = [r["matches"] for r in batched["requests"]]
        if seq_matches != batch_matches:
            violations.append(
                f"batched per-request matches {batch_matches} != "
                f"sequential {seq_matches} — cross-request "
                "contamination or lost rows")
        if batched_s >= seq_s and not args.smoke_no_wall_gate:
            violations.append(
                f"batched step ({batched_s:.4f}s) did not beat "
                f"{len(small)} sequential warm calls ({seq_s:.4f}s)")

        # Live metrics scrape: after the warm traffic the latency
        # histogram must yield non-degenerate, ordered quantiles, and
        # the Prometheus rendering must carry the request counters.
        met = send_ok({"op": "metrics"}, "metrics scrape")
        join_lat = met["metrics"]["ops"]["join"]["latency"]
        p50, p95, p99 = (join_lat.get("p50_s"), join_lat.get("p95_s"),
                         join_lat.get("p99_s"))
        if not p50 or not p95 or not p99 or not (p50 <= p95 <= p99):
            violations.append(
                "degenerate latency quantiles after warm traffic: "
                f"p50={p50} p95={p95} p99={p99}")
        prom = send_ok({"op": "metrics", "format": "prometheus"},
                       "prometheus scrape")
        if "djtpu_requests_total" not in prom.get("prometheus", ""):
            violations.append("prometheus exposition is missing "
                              "djtpu_requests_total")

        stats = client.send({"op": "stats"})
        if stats.get("uptime_s") is None or \
                stats.get("pending_hwm", 0) < 1:
            violations.append(
                "stats is missing uptime/admission high-water mark")
        client.send({"op": "shutdown"})
    finally:
        client.close()
        server.server_close()

    # The history store (when armed) must have seen the smoke's
    # distinct workloads — the substrate `analyze history` summarizes.
    history_info = None
    if service.history is not None:
        entries, _ = tel_history.load_history(service.history.path)
        hsum = tel_history.summarize(entries)
        history_info = {
            "path": service.history.path,
            "n_entries": hsum["n_entries"],
            "n_signatures": hsum["n_signatures"],
        }
        if hsum["n_signatures"] < 2:
            violations.append(
                f"history store holds {hsum['n_signatures']} "
                "signature(s); the smoke's traffic spans >= 2")

    # Resident A/B: in-process against the same (still-live) service
    # object — the TCP loop above is untouched, and every drill join
    # runs with_metrics=False so the baseline-gated counter block
    # stays the batched join's. The wire shutdown above closed the
    # admission window (service.draining = "shutdown"); reopen it for
    # the in-process drills — a real process would have exited, and
    # the drills stand in for a fresh incarnation sharing the warm
    # caches.
    with service._admit_lock:
        service.draining = None
    resident_drill = _resident_drill(service, args, violations)

    drill = _poison_drill(service.comm.n_ranks, args)

    record = {
        "benchmark": "service_smoke",
        "n_ranks": service.comm.n_ranks,
        "warm_new_traces": warm["new_traces"],
        "matches_per_join": cold["matches"],
        "explain": {
            "plan_digest": exp.get("plan", {}).get(
                "signature_digest"),
            "predicted_wall_s": exp.get("cost", {}).get("total_s"),
            "cache": exp.get("cache"),
        },
        "small_rows": args.smoke_small_rows,
        "batch_requests": args.smoke_batch,
        "sequential_s": seq_s,
        "batched_s": batched_s,
        "batched_speedup": seq_s / batched_s if batched_s else None,
        "batch_matches": batch_matches,
        "served": stats["served"],
        "uptime_s": stats.get("uptime_s"),
        "latency": stats.get("latency"),
        "qps_60s": stats.get("qps_60s"),
        "pending_hwm": stats.get("pending_hwm"),
        "cache": stats["cache"],
        "history": history_info,
        "resident_drill": resident_drill,
        "poison_drill": drill,
        "violations": violations,
        # the warmup responses keep the smoke honest in the record
        "warmup_sequential_matches": [r["matches"] for r in seq_warm],
    }
    if violations:
        from distributed_join_tpu.benchmarks import report

        report("service smoke FAILED", record, args.json_output)
        raise RuntimeError("service smoke violations: "
                           + "; ".join(violations))
    return record


def main(argv=None):
    from distributed_join_tpu.benchmarks import run_guarded
    from distributed_join_tpu.parallel.watchdog import (
        resolve_guard_deadline,
    )

    args = parse_args(argv)
    if args.watch:
        # Read-only console against an already-running daemon: no
        # mesh, no bootstrap, no run_guarded record.
        if not args.port:
            print("--watch needs the --port of a running daemon",
                  file=sys.stderr)
            return 2
        return watch(args.host, args.port,
                     interval_s=args.watch_interval_s,
                     count=args.watch_count,
                     trace_id=getattr(args, "trace_id", None))
    # --guard-deadline-s bounds each REQUEST, not the daemon: resolve
    # it now, then zero the flag so run_guarded leaves the (healthy,
    # long-lived) server unguarded. An explicit 0 also stops
    # resolve_guard_deadline falling through to the env var.
    args.request_deadline_s = resolve_guard_deadline(args)
    args.guard_deadline_s = 0
    return run_guarded(run, args, benchmark="service")


if __name__ == "__main__":
    sys.exit(main())
