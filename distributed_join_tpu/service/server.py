"""Join-as-a-service: the resident daemon that keeps the mesh warm.

The benchmark drivers bootstrap, compile, run, and exit; a service for
heavy traffic cannot pay bootstrap + trace + compile per query. This
module turns the PR 1-5 layers into serving machinery around one
long-lived process:

- :class:`JoinService` — the in-process engine: ONE communicator (the
  mesh bootstrapped once, exactly as ``benchmarks/launch.py`` /
  ``parallel/bootstrap.py`` set it up), ONE
  :class:`~..service.programs.JoinProgramCache` (the warm path is a
  lookup + dispatch), request admission (a bounded pending count —
  loud :class:`AdmissionError` refusals instead of an unbounded
  queue), per-request watchdog deadlines (``parallel/watchdog.py`` —
  a wedged collective becomes a structured ``HangError``, not a dead
  server), per-request telemetry spans, and the capacity retry ladder
  + wire-integrity verification routed through the cache
  (``distributed_inner_join(program_cache=...)``).
- :func:`JoinService.join_batched` — K small requests micro-batched
  into one padded SPMD step (:mod:`..service.batching`), unpacked per
  request at settle.
- the TCP daemon (``tpu-join-service`` / ``python -m
  distributed_join_tpu.service.server``): one JSON object per line in,
  one per line out. The wire carries QUERIES (table generator specs +
  join options — the serving demo), not table bytes; embed
  :class:`JoinService` directly for resident data. ``--smoke`` runs
  the CI acceptance protocol (docs/SERVICE.md): cold query, warm
  repeat (must add zero traces), 16 small joins sequential vs batched
  (batched must win wall clock), emitting a JSON record whose counter
  signature the ``perfgate`` lane gates against
  ``results/baselines/service_smoke.json``.

End-of-run ``--diagnose`` and the telemetry/robustness flags work
exactly as on the drivers (``run_guarded`` owns them); the
``--guard-deadline-s`` flag is re-pointed at PER-REQUEST deadlines —
guarding the whole daemon would kill a healthy resident server.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import socket
import socketserver
import sys
import threading
import time
from typing import Optional

from distributed_join_tpu import telemetry
from distributed_join_tpu.service import batching
from distributed_join_tpu.service.programs import JoinProgramCache


class AdmissionError(RuntimeError):
    """The service refused the request at admission (pending queue or
    batch size over the configured bound) — a structured, retryable
    refusal instead of an unbounded queue hiding an overloaded mesh."""


@dataclasses.dataclass
class ServiceConfig:
    """Serving policy knobs (the per-run driver flags, made resident).

    ``request_deadline_s`` is the per-request watchdog bound (None =
    unguarded); ``auto_retry``/``verify_integrity`` are the ladder and
    wire-integrity contracts of ``distributed_inner_join``, applied to
    every request; ``persist_dir`` arms the cache's on-disk AOT tier.
    """

    auto_retry: int = 2
    verify_integrity: bool = False
    request_deadline_s: Optional[float] = None
    max_pending: int = 8
    max_batch_requests: int = 64
    max_programs: int = 128
    persist_dir: Optional[str] = None


class JoinService:
    """The in-process serving engine. Thread-safe: admission is a
    bounded counter, execution serializes on one lock (one mesh runs
    one program at a time; queueing beyond ``max_pending`` is refused,
    not buffered)."""

    def __init__(self, comm, config: Optional[ServiceConfig] = None):
        self.comm = comm
        self.config = config or ServiceConfig()
        self.cache = JoinProgramCache(
            comm, persist_dir=self.config.persist_dir,
            max_entries=self.config.max_programs)
        self._exec_lock = threading.Lock()
        self._admit_lock = threading.Lock()
        self._pending = 0
        self.served = 0
        self.rejected = 0
        self.failed = 0
        # Set (to the HangError description) when a request blew its
        # deadline: the timed-out join keeps running on its detached
        # watchdog worker, so dispatching ANOTHER program onto the
        # same mesh would interleave two SPMD programs on one device
        # set. Fail-stop: every later join is refused loudly (ping/
        # stats still answer) until an operator restarts the server —
        # the serving analog of the drivers' hard exit after HangError.
        self.poisoned: Optional[str] = None

    # -- admission -----------------------------------------------------

    def _admit(self):
        with self._admit_lock:
            if self.poisoned is not None:
                self.rejected += 1
                telemetry.event("request_rejected", reason="poisoned")
                raise AdmissionError(
                    "mesh poisoned by a hung request "
                    f"({self.poisoned}); restart the server"
                )
            if self._pending >= self.config.max_pending:
                self.rejected += 1
                telemetry.event("request_rejected", reason="pending",
                                pending=self._pending)
                raise AdmissionError(
                    f"{self._pending} requests already pending "
                    f"(max_pending={self.config.max_pending}); "
                    "retry with backoff"
                )
            self._pending += 1

    def _release(self):
        with self._admit_lock:
            self._pending -= 1

    # -- the request paths --------------------------------------------

    def join(self, build, probe, key="key", **opts):
        """One admitted, watchdog-guarded, span-wrapped join through
        the program cache. Returns the ``JoinResult`` (with
        ``retry_report`` / ``integrity_report`` attributes exactly as
        ``distributed_inner_join`` attaches them)."""
        from distributed_join_tpu.parallel.distributed_join import (
            distributed_inner_join,
        )
        from distributed_join_tpu.parallel.watchdog import (
            call_with_deadline,
        )

        self._admit()
        try:
            with self._exec_lock:
                # Re-check under the EXEC lock: a request admitted
                # before a hang can be parked here while the hanging
                # request poisons the mesh and releases this lock —
                # it must not dispatch alongside the detached worker.
                with self._admit_lock:
                    if self.poisoned is not None:
                        self.rejected += 1
                        telemetry.event("request_rejected",
                                        reason="poisoned")
                        raise AdmissionError(
                            "mesh poisoned by a hung request "
                            f"({self.poisoned}); restart the server")
                rid = self.served + self.failed

                def run_once():
                    return distributed_inner_join(
                        build, probe, self.comm, key=key,
                        auto_retry=self.config.auto_retry,
                        verify_integrity=self.config.verify_integrity,
                        program_cache=self.cache, **opts)

                deadline = self.config.request_deadline_s
                traces0 = self.cache.traces
                try:
                    with telemetry.span("request", id=rid) as sp:
                        if deadline is None:
                            res = run_once()
                        else:
                            res = call_with_deadline(
                                run_once, deadline,
                                what=f"request {rid}")
                        if sp is not None:
                            sp.sync_on(res.total)
                except Exception as exc:
                    self.failed += 1
                    from distributed_join_tpu.parallel.watchdog import (
                        HangError,
                    )

                    if isinstance(exc, HangError):
                        with self._admit_lock:
                            self.poisoned = str(exc)
                    raise
                self.served += 1
                # Trace accounting captured UNDER the exec lock — a
                # concurrent connection's cold compile must not be
                # misattributed to this request (host-side attribute,
                # the retry_report pattern).
                object.__setattr__(res, "new_traces",
                                   self.cache.traces - traces0)
                return res
        finally:
            self._release()

    def join_batched(self, requests, key="key", *,
                     slot_build_rows=None, slot_probe_rows=None,
                     with_rows: bool = False, **opts):
        """Micro-batch ``requests`` (``(build, probe)`` pairs sharing
        one schema and ``key``) into one SPMD step and unpack per
        request. Returns ``batching.split``'s per-request records."""
        if len(requests) > self.config.max_batch_requests:
            with self._admit_lock:
                self.rejected += 1
            telemetry.event("request_rejected", reason="batch_size",
                            batch=len(requests))
            raise AdmissionError(
                f"batch of {len(requests)} exceeds max_batch_requests="
                f"{self.config.max_batch_requests}"
            )
        mb = batching.combine(
            requests, key=key, slot_build_rows=slot_build_rows,
            slot_probe_rows=slot_probe_rows)
        res = self.join(mb.build, mb.probe, key=list(mb.key), **opts)
        results = batching.split(res, mb, with_rows=with_rows)
        for r in results:
            # the batch shares one program resolution; the count is
            # replicated per request for the wire's convenience
            r["new_traces"] = getattr(res, "new_traces", 0)
        return results

    def stats(self) -> dict:
        return {
            "served": self.served,
            "failed": self.failed,
            "rejected": self.rejected,
            "pending": self._pending,
            "poisoned": self.poisoned,
            "cache": self.cache.stats(),
        }


# -- the wire protocol -------------------------------------------------

# Join options a wire request may set (everything else a query could
# name is a server-side policy, not a per-request knob).
_WIRE_JOIN_OPTS = (
    "shuffle", "over_decomposition", "shuffle_capacity_factor",
    "out_capacity_factor", "compression_bits", "skew_threshold",
)


def _tables_from_spec(spec: dict):
    """Generate the (build, probe) pair a wire query names. The demo
    data plane: deterministic generator tables keyed by the request's
    seed — a resident deployment embeds :class:`JoinService` and hands
    it real device tables instead."""
    from distributed_join_tpu.utils.generators import (
        generate_build_probe_tables,
    )

    return generate_build_probe_tables(
        seed=int(spec.get("seed", 42)),
        build_nrows=int(spec["build_nrows"]),
        probe_nrows=int(spec["probe_nrows"]),
        rand_max=(int(spec["rand_max"]) if spec.get("rand_max")
                  else None),
        selectivity=float(spec.get("selectivity", 0.3)),
        unique_build_keys=bool(spec.get("unique_build_keys", False)),
    )


def _join_opts_from_spec(spec: dict) -> dict:
    return {k: spec[k] for k in _WIRE_JOIN_OPTS if spec.get(k)
            is not None}


class _Handler(socketserver.StreamRequestHandler):
    """One JSON object per line in -> one JSON object per line out."""

    def handle(self):
        for raw in self.rfile:
            line = raw.decode("utf-8").strip()
            if not line:
                continue
            req = None
            try:
                req = json.loads(line)
                resp = self._dispatch(req)
            except Exception as exc:  # noqa: BLE001 - wire boundary:
                # a bad request must answer THAT client, not kill the
                # daemon serving everyone else.
                resp = {"ok": False, "error": type(exc).__name__,
                        "message": str(exc)}
            self.wfile.write(
                (json.dumps(resp) + "\n").encode("utf-8"))
            self.wfile.flush()
            if isinstance(req, dict) and req.get("op") == "shutdown" \
                    and resp.get("ok"):
                return

    def _dispatch(self, req: dict) -> dict:
        service: JoinService = self.server.service
        op = req.get("op")
        if op == "ping":
            return {"ok": True, "op": "ping"}
        if op == "stats":
            return {"ok": True, **service.stats()}
        if op == "shutdown":
            # shutdown() must not run on the handler thread (it joins
            # the serve_forever loop, which is waiting on us).
            threading.Thread(target=self.server.shutdown,
                             daemon=True).start()
            return {"ok": True, "op": "shutdown"}
        if op == "join":
            build, probe = _tables_from_spec(req)
            t0 = time.perf_counter()
            res = service.join(build, probe,
                               **_join_opts_from_spec(req))
            matches = int(res.total)
            elapsed = time.perf_counter() - t0
            retry = res.retry_report.as_record()
            return {
                "ok": True,
                "matches": matches,
                "overflow": bool(res.overflow),
                "elapsed_s": elapsed,
                # accounted under the service's exec lock, so a
                # concurrent connection's compile is never billed here
                "new_traces": getattr(res, "new_traces", 0),
                "retry": retry,
                "cache": service.cache.stats(),
            }
        if op == "batch":
            specs = req.get("requests") or []
            pairs = [_tables_from_spec(s) for s in specs]
            t0 = time.perf_counter()
            results = service.join_batched(
                pairs,
                slot_build_rows=req.get("slot_build_rows"),
                slot_probe_rows=req.get("slot_probe_rows"),
                **_join_opts_from_spec(req))
            elapsed = time.perf_counter() - t0
            return {
                "ok": True,
                "requests": results,
                "matches": sum(r["matches"] for r in results),
                "elapsed_s": elapsed,
                "new_traces": (results[0]["new_traces"]
                               if results else 0),
                "cache": service.cache.stats(),
            }
        raise ValueError(f"unknown op {op!r} (ops: ping, stats, join, "
                         "batch, shutdown)")


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr, service: JoinService):
        super().__init__(addr, _Handler)
        self.service = service


def start_daemon(service: JoinService, host: str = "127.0.0.1",
                 port: int = 0):
    """Bind + serve on a background thread; returns ``(server, port)``.
    ``server.shutdown()`` (or the wire ``shutdown`` op) stops it."""
    server = _Server((host, port), service)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, server.server_address[1]


class ServiceClient:
    """Line-protocol client over one persistent connection (the smoke
    protocol and tests; also a template for real callers)."""

    def __init__(self, host: str, port: int, timeout_s: float = 600.0):
        self._sock = socket.create_connection((host, port), timeout_s)
        self._file = self._sock.makefile("rw", encoding="utf-8",
                                         newline="\n")

    def send(self, payload: dict) -> dict:
        self._file.write(json.dumps(payload) + "\n")
        self._file.flush()
        line = self._file.readline()
        if not line:
            raise ConnectionError("service closed the connection")
        return json.loads(line)

    def close(self) -> None:
        self._file.close()
        self._sock.close()


# -- the CLI daemon ----------------------------------------------------


def parse_args(argv=None):
    from distributed_join_tpu.benchmarks import (
        add_platform_arg,
        add_robustness_args,
        add_telemetry_args,
    )

    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="TCP port (0 = ephemeral; the bound port is "
                        "printed on the 'listening' line)")
    p.add_argument("--n-ranks", type=int, default=None,
                   help="mesh size; default all visible devices")
    p.add_argument("--communicator", default="tpu")
    p.add_argument("--auto-retry", type=int, default=2,
                   help="capacity-ladder budget applied to every "
                        "request (rungs reuse cached executables)")
    p.add_argument("--max-pending", type=int, default=8,
                   help="admission bound: requests beyond this many "
                        "pending are refused, not queued")
    p.add_argument("--max-batch-requests", type=int, default=64)
    p.add_argument("--max-programs", type=int, default=128,
                   help="resident-executable bound (LRU-evicted in "
                        "memory; persisted blobs survive): the wire "
                        "lets every request pick its own table shape, "
                        "and each shape is a compiled program")
    p.add_argument("--persist-dir", default=None, metavar="DIR",
                   help="persist compiled executables under DIR (the "
                        "AOT serialization tier): a restarted server "
                        "skips even the first trace")
    p.add_argument("--smoke", action="store_true",
                   help="run the CI smoke protocol against an "
                        "in-process daemon instead of serving: warm "
                        "cache discipline + batched-vs-sequential "
                        "(docs/SERVICE.md), JSON record on stdout")
    p.add_argument("--smoke-small-rows", type=int, default=256,
                   help="rows per small join in the smoke's batched-"
                        "vs-sequential comparison")
    p.add_argument("--smoke-batch", type=int, default=16,
                   help="small joins per smoke micro-batch")
    p.add_argument("--smoke-no-wall-gate", action="store_true",
                   help="report the batched-vs-sequential wall clocks "
                        "but do not FAIL on them (the perfgate lane "
                        "gates counters only; the service lane keeps "
                        "the strict timing gate)")
    p.add_argument("--json-output", default=None)
    add_platform_arg(p)
    add_telemetry_args(p)
    add_robustness_args(p)
    return p.parse_args(argv)


def _service_from_args(args) -> JoinService:
    from distributed_join_tpu.benchmarks import (
        apply_platform,
        maybe_chaos_communicator,
    )
    from distributed_join_tpu.parallel.communicator import (
        make_communicator,
    )

    apply_platform(args.platform, args.n_ranks)
    comm = maybe_chaos_communicator(
        make_communicator(args.communicator, n_ranks=args.n_ranks),
        args)
    cfg = ServiceConfig(
        auto_retry=args.auto_retry,
        verify_integrity=args.verify_integrity,
        request_deadline_s=args.request_deadline_s,
        max_pending=args.max_pending,
        max_batch_requests=args.max_batch_requests,
        max_programs=args.max_programs,
        persist_dir=args.persist_dir,
    )
    return JoinService(comm, cfg)


def run(args) -> dict:
    service = _service_from_args(args)
    from distributed_join_tpu.benchmarks import report

    if args.smoke:
        record = run_smoke(service, args)
    else:
        server = _Server((args.host, args.port), service)
        port = server.server_address[1]
        print(f"join-service listening on {args.host}:{port}",
              flush=True)
        try:
            # Serve on THIS thread; the wire shutdown op (handled on a
            # connection thread) calls server.shutdown(), which makes
            # serve_forever return.
            server.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            server.server_close()
        record = {"benchmark": "service", **service.stats()}
    report(
        f"join-service: {record.get('served', 0)} request(s) served, "
        f"{record['cache']['traces']} trace(s), "
        f"{record['cache']['hits']} cache hit(s)",
        record, args.json_output,
    )
    return record


def run_smoke(service: JoinService, args) -> dict:
    """The acceptance protocol, end to end THROUGH the daemon's TCP
    loop (docs/SERVICE.md "CI smoke"):

    1. cold query Q compiles; the identical warm repeat must add ZERO
       traces and report a cache hit;
    2. N small joins, warmed, timed sequentially (N dispatches of one
       cached program) vs micro-batched (ONE dispatch) — the batch
       must win wall clock and return the same per-request matches.

    Raises RuntimeError on any violation (run_guarded turns it into a
    failure record with rc != 0)."""
    server, port = start_daemon(service, "127.0.0.1", 0)
    client = ServiceClient("127.0.0.1", port)
    violations = []

    def send_ok(payload, what):
        resp = client.send(payload)
        if not resp.get("ok"):
            # surface the service's OWN error, not a downstream
            # KeyError on the missing response fields
            raise RuntimeError(f"{what} failed: {resp}")
        return resp

    try:
        q = {"op": "join", "build_nrows": 4096, "probe_nrows": 4096,
             "seed": 42, "selectivity": 0.3,
             "out_capacity_factor": 3.0}
        cold = send_ok(q, "cold query")
        warm = send_ok(q, "warm query")
        if warm["new_traces"] != 0:
            violations.append(
                f"warm repeat traced {warm['new_traces']} new "
                "program(s); the warm path must be run-only")
        if warm["matches"] != cold["matches"]:
            violations.append("warm matches != cold matches")

        rows = args.smoke_small_rows
        small = [
            {"op": "join", "build_nrows": rows, "probe_nrows": rows,
             "seed": 100 + i, "selectivity": 0.5,
             "rand_max": max(rows // 2, 1),
             "out_capacity_factor": 3.0}
            for i in range(args.smoke_batch)
        ]
        batch_req = {
            "op": "batch", "out_capacity_factor": 3.0,
            "requests": [{k: v for k, v in s.items() if k != "op"}
                         for s in small],
        }
        # Warm both shapes (compile happens here, outside the timing).
        seq_warm = [send_ok(s, "sequential warm-up") for s in small]
        send_ok(batch_req, "batch warm-up")
        # Timed, all-warm passes.
        t0 = time.perf_counter()
        seq = [send_ok(s, "timed sequential") for s in small]
        seq_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        batched = send_ok(batch_req, "timed batch")
        batched_s = time.perf_counter() - t0
        if any(r["new_traces"] for r in seq) or batched["new_traces"]:
            violations.append("timed pass traced new programs")
        seq_matches = [r["matches"] for r in seq]
        batch_matches = [r["matches"] for r in batched["requests"]]
        if seq_matches != batch_matches:
            violations.append(
                f"batched per-request matches {batch_matches} != "
                f"sequential {seq_matches} — cross-request "
                "contamination or lost rows")
        if batched_s >= seq_s and not args.smoke_no_wall_gate:
            violations.append(
                f"batched step ({batched_s:.4f}s) did not beat "
                f"{len(small)} sequential warm calls ({seq_s:.4f}s)")
        stats = client.send({"op": "stats"})
        client.send({"op": "shutdown"})
    finally:
        client.close()
        server.server_close()
    record = {
        "benchmark": "service_smoke",
        "n_ranks": service.comm.n_ranks,
        "warm_new_traces": warm["new_traces"],
        "matches_per_join": cold["matches"],
        "small_rows": args.smoke_small_rows,
        "batch_requests": args.smoke_batch,
        "sequential_s": seq_s,
        "batched_s": batched_s,
        "batched_speedup": seq_s / batched_s if batched_s else None,
        "batch_matches": batch_matches,
        "served": stats["served"],
        "cache": stats["cache"],
        "violations": violations,
        # the warmup responses keep the smoke honest in the record
        "warmup_sequential_matches": [r["matches"] for r in seq_warm],
    }
    if violations:
        from distributed_join_tpu.benchmarks import report

        report("service smoke FAILED", record, args.json_output)
        raise RuntimeError("service smoke violations: "
                           + "; ".join(violations))
    return record


def main(argv=None):
    from distributed_join_tpu.benchmarks import run_guarded
    from distributed_join_tpu.parallel.watchdog import (
        resolve_guard_deadline,
    )

    args = parse_args(argv)
    # --guard-deadline-s bounds each REQUEST, not the daemon: resolve
    # it now, then zero the flag so run_guarded leaves the (healthy,
    # long-lived) server unguarded. An explicit 0 also stops
    # resolve_guard_deadline falling through to the env var.
    args.request_deadline_s = resolve_guard_deadline(args)
    args.guard_deadline_s = 0
    return run_guarded(run, args, benchmark="service")


if __name__ == "__main__":
    sys.exit(main())
