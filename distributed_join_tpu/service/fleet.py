"""Fault-tolerant serving fleet: a signature-affinity router over N
``tpu-join-service`` replicas (docs/FLEET.md; ROADMAP item 6).

One daemon = one mesh = one SPMD program at a time, and the
deliberately conservative hang semantics of docs/FAILURE_SEMANTICS.md
mean a poisoned daemon refuses traffic until a human restarts it. The
fleet tier makes the failure domain ONE REPLICA instead of the
service:

- **Routing is signature-affine.** The router hashes the SAME
  canonical workload-signature digest the program cache and tuner key
  on (:func:`~..planning.tuner.workload_signature` over abstract
  tables built from the wire spec — zero traces, zero devices), so a
  repeat workload lands where its executable is already resident;
  table-management ops hash the table name, so ``register`` and the
  probe-only ``join`` of one handle co-locate. A shared AOT
  ``--persist-dir`` is the compiled-program distribution tier: any
  replica (including a cold replacement) loads a sibling's programs
  with zero traces.
- **Replica lifecycle.** Periodic ``stats`` health probes plus
  per-request outcome accounting drive a state machine
  (healthy -> suspect -> drained). A replica that reports
  ``poisoned``, times out, or drops its connection is DRAINED (no new
  requests; in-flight completes or deadlines out under the replica's
  own watchdog) and REPLACED (respawned on its device subset, warm
  via the shared persist dir) — never left refusing traffic forever.
- **Failover.** A request whose replica dies mid-flight retries on
  the next affine replica under a bounded
  :func:`~..parallel.faults.retry_with_backoff` budget with a
  per-request deadline — idempotent because the wire carries query
  SPECS, not table bytes. Duplicate dispatch is fenced by request id:
  a second arrival of an id still in flight is refused, and a
  superseded attempt's connection is abandoned (its late answer is
  never read).
- **Load shedding.** Admission at the router is driven by per-replica
  inflight counters plus the ``qps_60s``/p95 figures of the replicas'
  own :class:`~..telemetry.live.LiveMetrics` snapshots (taken by the
  health probe): when no replica is admittable the router answers a
  structured ``AdmissionError`` instead of queueing unboundedly.
- **Observability.** The router keeps its own
  :class:`~..telemetry.live.LiveMetrics` /
  :class:`~..telemetry.live.FlightRecorder` /
  :class:`~..telemetry.history.WorkloadHistory` (entries stamped with
  the serving replica), and exposes fleet-level Prometheus gauges
  ``djtpu_fleet_{replicas,healthy,suspect,drained,failovers_total,
  shed_total,replaced_total}`` next to the usual request counters.

``python -m distributed_join_tpu.service.fleet`` (``tpu-join-fleet``)
serves the same line-JSON wire protocol as one daemon — clients do not
change. ``--smoke`` runs the CI acceptance protocol (the ``fleet``
lane of ``scripts/run_tier1.sh``): a 2-replica CPU-mesh fleet, warm
affinity discipline, ONE SCRIPTED REPLICA KILL mid-traffic, and gates
on oracle equality, drain+replace observed, bounded retry count, and
a zero-trace warm repeat on the replacement.

The chaos soak lives in ``parallel/chaos.py --fleet`` (kill / hang /
corrupt one replica mid-soak, every non-refused answer graded against
the pandas oracle).
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import os
import subprocess
import sys
import threading
import time
from typing import Callable, Optional

from distributed_join_tpu import telemetry
from distributed_join_tpu.service.server import (
    AdmissionError,
    ServiceClient,
    _join_opts_from_spec,
)
from distributed_join_tpu.telemetry import history as tel_history
from distributed_join_tpu.telemetry import live as tel_live


class FleetError(RuntimeError):
    """A fleet-level structured failure (failover budget exhausted,
    duplicate in-flight request id) — answered on the wire, never an
    unstructured crash of the router."""


@dataclasses.dataclass
class FleetConfig:
    """Router policy knobs (docs/FLEET.md).

    ``replica_ranks`` is each replica's mesh size (the affinity hash
    binds it — the workload signature covers ``n_ranks``);
    ``persist_dir`` is the SHARED compiled-program distribution tier
    (a replacement replica cold-loads its predecessor's programs with
    zero traces). ``probe_interval_s`` paces the health prober;
    ``suspect_strikes`` is how many consecutive probe/request
    failures turn suspicion into a drain. ``retry_budget`` bounds
    failover attempts per request (total attempts = budget + 1) and
    ``request_deadline_s`` bounds the whole request across attempts.
    ``max_inflight_per_replica`` plus the optional ``shed_p95_s`` /
    ``shed_qps`` bounds (read from the replicas' probed LiveMetrics
    snapshots) drive admission — beyond them the router sheds with a
    structured ``AdmissionError``.
    """

    n_replicas: int = 2
    replica_ranks: int = 2
    persist_dir: Optional[str] = None
    probe_interval_s: float = 1.0
    probe_timeout_s: float = 5.0
    spawn_timeout_s: float = 180.0
    drain_settle_s: float = 10.0
    suspect_strikes: int = 2
    retry_budget: int = 2
    retry_backoff_s: float = 0.1
    request_deadline_s: float = 300.0
    max_inflight_per_replica: int = 4
    shed_p95_s: Optional[float] = None
    shed_qps: Optional[float] = None
    respawn: bool = True
    history_dir: Optional[str] = None
    flight_records: int = 256
    flight_recorder_path: Optional[str] = None


# -- replica backends --------------------------------------------------


class ProcessReplica:
    """One ``tpu-join-service`` subprocess (the production backend:
    disjoint hosts on hardware, per-process virtual CPU meshes in
    tests). The constructor blocks until the daemon's ``listening``
    line names its port."""

    def __init__(self, argv: list, spawn_timeout_s: float = 180.0):
        self.argv = list(argv)
        self.proc = subprocess.Popen(
            self.argv, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        # One reader thread owns stdout for the process's lifetime:
        # it resolves the 'listening' line AND keeps the pipe from
        # filling afterwards (a bare readline-with-timeout would race
        # Python's stream buffering).
        self._listening = threading.Event()
        self._addr: Optional[tuple] = None
        self._reader = threading.Thread(target=self._read_stdout,
                                        daemon=True)
        self._reader.start()
        if not self._listening.wait(spawn_timeout_s) \
                or self._addr is None:
            rc = self.proc.poll()
            self.proc.kill()
            raise FleetError(
                f"replica did not report a listening port within "
                f"{spawn_timeout_s}s (rc={rc}): "
                f"{' '.join(self.argv)}")
        self.host, self.port = self._addr

    def _read_stdout(self):
        try:
            for line in self.proc.stdout:
                if self._addr is None and "listening on " in line:
                    addr = line.rsplit("listening on ",
                                       1)[1].strip()
                    host, port = addr.rsplit(":", 1)
                    self._addr = (host, int(port))
                    self._listening.set()
        except (OSError, ValueError):  # pragma: no cover - teardown
            pass
        finally:
            # EOF before the listening line = the replica died at
            # spawn; release the constructor immediately.
            self._listening.set()

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        """Hard stop (SIGKILL) — the chaos harness's scripted death."""
        if self.alive():
            self.proc.kill()
        self.proc.wait(timeout=30.0)

    def stop(self, timeout_s: float = 10.0) -> None:
        """Reap after a graceful drain attempt: SIGTERM (the daemon's
        handler drains and exits 0), bounded wait, then SIGKILL."""
        if self.alive():
            self.proc.terminate()
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=30.0)


class InProcessReplica:
    """One in-process :class:`~.server.JoinService` behind a real TCP
    daemon — the fleet TEST backend: replicas over DISJOINT device
    subsets of the one CPU mesh, spawn/kill in milliseconds, no
    subprocess bootstrap. ``kill`` closes the listening socket
    (connection-refused to the router, exactly what a dead process
    looks like on the wire)."""

    def __init__(self, service):
        from distributed_join_tpu.service.server import start_daemon

        self.service = service
        self.server, self.port = start_daemon(service)
        self.host = "127.0.0.1"
        self._dead = False

    def alive(self) -> bool:
        return not self._dead

    def kill(self) -> None:
        self._dead = True
        self.server.shutdown()
        self.server.server_close()

    def stop(self, timeout_s: float = 10.0) -> None:  # noqa: ARG002
        if not self._dead:
            self.kill()


def in_process_fleet_factory(n_replicas: int, ranks_per_replica: int,
                             service_config=None,
                             comm_wrap: Optional[Callable] = None,
                             persist_dir: Optional[str] = None):
    """A replica factory over DISJOINT CPU-mesh device subsets:
    replica ``i`` serves devices ``[i*k, (i+1)*k)`` — the test-side
    realization of 'the failure domain is one replica'. ``comm_wrap``
    (index, generation, comm) -> comm lets tests arm one replica with
    a :class:`~..parallel.faults.FaultInjectingCommunicator`.

    ``persist_dir`` arms PER-SLOT persist subdirs (``r0/``, ``r1/``,
    ...): an AOT blob binds its device assignment, so two in-process
    replicas on DIFFERENT subsets of one runtime cannot share blobs —
    but a replacement respawned on the SAME subset restarts warm,
    which is what the in-process tests lock. Cross-replica sharing is
    real only across processes (each subprocess sees its own
    ``cpu:0..k-1``) and is locked by the subprocess smoke."""
    import jax

    from distributed_join_tpu.parallel.communicator import (
        TpuCommunicator,
    )
    from distributed_join_tpu.parallel.mesh import make_mesh
    from distributed_join_tpu.service.server import (
        JoinService,
        ServiceConfig,
    )

    devices = jax.devices()
    need = n_replicas * ranks_per_replica
    if need > len(devices):
        raise ValueError(
            f"{n_replicas} replicas x {ranks_per_replica} ranks needs "
            f"{need} devices, have {len(devices)}")

    def factory(index: int, generation: int) -> InProcessReplica:
        subset = devices[index * ranks_per_replica:
                         (index + 1) * ranks_per_replica]
        comm = TpuCommunicator(mesh=make_mesh(devices=subset))
        if comm_wrap is not None:
            comm = comm_wrap(index, generation, comm)
        cfg = service_config or ServiceConfig()
        if persist_dir is not None:
            cfg = dataclasses.replace(
                cfg, persist_dir=os.path.join(persist_dir,
                                              f"r{index}"))
        return InProcessReplica(JoinService(comm, cfg))

    return factory


def process_fleet_factory(config: FleetConfig,
                          platform: str = "cpu",
                          extra_args: Optional[list] = None,
                          replica_overrides: Optional[dict] = None):
    """The default replica factory: one ``tpu-join-service``
    subprocess per replica, sharing ``config.persist_dir``.

    ``replica_overrides`` maps a replica index to a dict applied ONLY
    on generation 0 (the chaos harness's scripted outage; every
    replacement respawns clean): ``"fault_plan"`` (a FaultPlan JSON
    record, forwarded as ``--fault-plan``), ``"extra_args"`` (extra
    argv tokens — e.g. the hang scenario's ``--guard-deadline-s``),
    and ``"persist": False`` (exclude the victim from the shared
    persist dir, so a corruption-armed replica must TRACE — a
    corrupted trace must never enter the fleet's distribution
    tier)."""

    def factory(index: int, generation: int) -> ProcessReplica:
        override = ((replica_overrides or {}).get(index) or {}
                    if generation == 0 else {})
        argv = [
            sys.executable, "-m",
            "distributed_join_tpu.service.server",
            "--host", "127.0.0.1", "--port", "0",
            "--platform", platform,
            "--n-ranks", str(config.replica_ranks),
        ]
        if config.persist_dir and override.get("persist", True):
            argv += ["--persist-dir", config.persist_dir]
        plan = override.get("fault_plan")
        if plan is not None:
            argv += ["--fault-plan", json.dumps(plan)]
        argv += list(extra_args or [])
        argv += list(override.get("extra_args") or [])
        return ProcessReplica(argv,
                              spawn_timeout_s=config.spawn_timeout_s)

    return factory


# -- the router --------------------------------------------------------


@dataclasses.dataclass
class _Replica:
    """Router-side replica bookkeeping (the state machine's subject).
    ``state``: starting -> healthy -> suspect -> drained (-> healthy
    again after replacement; ``failed`` when a respawn itself died)."""

    index: int
    backend: object
    generation: int = 0
    state: str = "healthy"
    strikes: int = 0
    inflight: int = 0
    last_stats: Optional[dict] = None
    drained_reason: Optional[str] = None
    drained_at: Optional[float] = None
    replaced_at: Optional[float] = None

    def addr(self):
        return self.backend.host, self.backend.port


class _AffinityStub:
    """The n_ranks/n_slices view :func:`workload_signature` needs —
    the router hashes signatures without a mesh or devices."""

    def __init__(self, n_ranks: int, n_slices: int = 1):
        self.n_ranks = n_ranks
        self.n_slices = n_slices


def affinity_key(req: dict, replica_ranks: int) -> str:
    """THE canonical routing digest of one wire request (module-level
    so harnesses can predict routing before a fleet exists). Join and
    explain specs hash through the workload-signature function the
    program cache and tuner key on (abstract tables from the spec's
    shapes — no data, no devices); table-management ops hash the
    table name so one handle's traffic co-locates; anything else
    hashes its canonical JSON."""
    op = req.get("op")
    table = req.get("table") or (
        req.get("name") if op in ("register", "append", "drop")
        else None)
    if table is not None:
        return hashlib.sha256(
            f"table:{table}".encode()).hexdigest()[:16]
    if op in ("join", "explain") and req.get("build_nrows"):
        try:
            from distributed_join_tpu.planning import abstract_tables
            from distributed_join_tpu.planning.tuner import (
                workload_signature,
            )

            build, probe = abstract_tables(
                int(req["build_nrows"]), int(req["probe_nrows"]))
            stub = _AffinityStub(replica_ranks)
            return workload_signature(
                stub, build, probe, with_metrics=False,
                **_join_opts_from_spec(req))
        except Exception as exc:  # noqa: BLE001 - fall to JSON hash
            # Loud: routing by the JSON fallback still works, but it
            # no longer matches the replicas' program-cache digests —
            # warm repeats would scatter. A silent fallback here made
            # that near-undiagnosable.
            telemetry.event(
                "fleet_affinity_fallback", op=op,
                error=f"{type(exc).__name__}: {exc}")
    basis = json.dumps(
        {k: repr(v) for k, v in req.items()
         if k not in ("request_id",)}, sort_keys=True)
    return hashlib.sha256(basis.encode()).hexdigest()[:16]


def affine_replica(req: dict, replica_ranks: int,
                   n_replicas: int) -> int:
    """The ring-walk START slot for ``req`` — the replica a healthy
    fleet serves it from (the chaos harness arms its victim here)."""
    key = affinity_key(req, replica_ranks)
    return int(key[:8], 16) % max(n_replicas, 1)


class FleetRouter:
    """The thin line-JSON TCP router fronting N replicas. Owns the
    replica set (spawn, probe, drain, replace), the affinity routing,
    the bounded failover loop, admission/shedding, and the fleet-level
    observability surfaces."""

    def __init__(self, replica_factory: Callable,
                 config: Optional[FleetConfig] = None):
        self.config = config or FleetConfig()
        self.factory = replica_factory
        self._lock = threading.Lock()
        self.replicas: list = []
        self.live = tel_live.LiveMetrics()
        self.recorder = tel_live.FlightRecorder(
            self.config.flight_records)
        self.history = (tel_history.WorkloadHistory(os.path.join(
            self.config.history_dir, tel_history.HISTORY_FILENAME))
            if self.config.history_dir else None)
        self.failovers_total = 0
        self.shed_total = 0
        self.replaced_total = 0
        self.drains_total = 0
        self.served = 0
        self.failed = 0
        self.rejected = 0
        self._request_seq = 0
        self._id_stamp = os.urandom(3).hex()
        self._inflight_ids: set = set()
        self._stop = threading.Event()
        self._prober: Optional[threading.Thread] = None
        self._replace_threads: list = []
        # Set by the wire `shutdown` op; the serving loop (main) and
        # embedding harnesses watch it to tear the fleet down.
        self.shutdown_requested = threading.Event()

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        """Spawn the replica set and start the health prober."""
        for i in range(self.config.n_replicas):
            self.replicas.append(
                _Replica(index=i, backend=self.factory(i, 0)))
        telemetry.event("fleet_started",
                        replicas=len(self.replicas))
        self._prober = threading.Thread(target=self._probe_loop,
                                        daemon=True,
                                        name="fleet-prober")
        self._prober.start()

    def stop(self, drain: bool = True) -> None:
        """Stop probing, settle any in-flight replacement, and reap
        every replica (graceful drain op first when ``drain``)."""
        self._stop.set()
        if self._prober is not None:
            self._prober.join(timeout=self.config.probe_interval_s
                              + self.config.probe_timeout_s + 5.0)
        # A _replace thread may be mid-spawn: join it (bounded) so
        # the freshly spawned backend lands in self.replicas and is
        # reaped below instead of leaking past shutdown.
        for t in self._replace_threads:
            t.join(timeout=self.config.spawn_timeout_s
                   + self.config.drain_settle_s + 10.0)
        for rep in self.replicas:
            if drain and rep.backend.alive():
                self._send_drain(rep)
            rep.backend.stop()
        if self.history is not None:
            self.history.close()
        if self.config.flight_recorder_path:
            self.dump_flight_recorder("fleet stopped")

    # -- affinity -----------------------------------------------------

    def affinity_key(self, req: dict) -> str:
        """The canonical routing digest of one wire request — see the
        module-level :func:`affinity_key`."""
        return affinity_key(req, self.config.replica_ranks)

    def _admittable(self, rep: _Replica) -> bool:
        """Admission policy: state + inflight bound + the optional
        p95/QPS bounds read from the replica's probed LiveMetrics
        snapshot (stale by at most one probe interval — shedding is a
        pressure valve, not an exact gate)."""
        if rep.state not in ("healthy", "suspect"):
            return False
        if rep.inflight >= self.config.max_inflight_per_replica:
            return False
        st = rep.last_stats or {}
        if self.config.shed_p95_s is not None:
            p95 = (st.get("latency") or {}).get("p95_s")
            if p95 is not None and p95 > self.config.shed_p95_s:
                return False
        if self.config.shed_qps is not None:
            qps = st.get("qps_60s")
            if qps is not None and qps > self.config.shed_qps:
                return False
        return True

    # -- the health prober + state machine ----------------------------

    def _probe_loop(self):
        while not self._stop.wait(self.config.probe_interval_s):
            for rep in list(self.replicas):
                if self._stop.is_set():
                    return
                # drained = replacement in progress; failed = respawn
                # itself died and the fleet serves on with n-1 — the
                # prober leaves both alone (re-striking a failed slot
                # would churn doomed respawns forever).
                if rep.state in ("drained", "failed"):
                    continue
                self._probe_one(rep)

    def _probe_one(self, rep: _Replica):
        try:
            client = ServiceClient(
                *rep.addr(), timeout_s=self.config.probe_timeout_s)
            try:
                st = client.send({"op": "stats"})
            finally:
                client.close()
        except (OSError, ValueError) as exc:
            self._strike(rep, f"probe: {type(exc).__name__}: {exc}")
            return
        rep.last_stats = st
        if st.get("poisoned"):
            self._drain(rep, f"probe saw poisoned: {st['poisoned']}")
        elif st.get("draining"):
            # Someone (an operator, SIGTERM) is draining it out from
            # under the fleet: stop routing to it and replace.
            self._drain(rep, "probe saw draining")
        else:
            with self._lock:
                rep.strikes = 0
                if rep.state == "suspect":
                    rep.state = "healthy"
                    telemetry.event("fleet_replica_recovered",
                                    replica=rep.index)

    def _strike(self, rep: _Replica, reason: str):
        """One probe/request failure: healthy -> suspect; strikes
        beyond the bound (or a dead process) -> drained."""
        with self._lock:
            rep.strikes += 1
            strikes = rep.strikes
            if rep.state == "healthy":
                rep.state = "suspect"
                telemetry.event("fleet_replica_suspect",
                                replica=rep.index, reason=reason)
        if strikes >= self.config.suspect_strikes \
                or not rep.backend.alive():
            self._drain(rep, reason)

    def _drain(self, rep: _Replica, reason: str):
        """drained + replacement kick-off; idempotent per incident
        (a failed slot is terminal until an operator intervenes)."""
        with self._lock:
            if rep.state in ("drained", "failed"):
                return
            rep.state = "drained"
            rep.drained_reason = reason
            rep.drained_at = time.monotonic()
            self.drains_total += 1
        telemetry.event("fleet_replica_drained", replica=rep.index,
                        reason=reason)
        self.recorder.record(
            request_id=f"fleet-replica-{rep.index}",
            op="drain_replica", signature=None, outcome="drained",
            reason=reason,
            replica={"index": rep.index,
                     "generation": rep.generation})
        if self.config.respawn and not self._stop.is_set():
            t = threading.Thread(target=self._replace, args=(rep,),
                                 daemon=True,
                                 name=f"fleet-replace-{rep.index}")
            with self._lock:
                self._replace_threads = [x for x in
                                         self._replace_threads
                                         if x.is_alive()] + [t]
            t.start()

    def _send_drain(self, rep: _Replica):
        """Best-effort graceful drain wire op (in-flight completes or
        deadlines out replica-side before the reap)."""
        try:
            client = ServiceClient(
                *rep.addr(), timeout_s=self.config.drain_settle_s
                + self.config.probe_timeout_s)
            try:
                client.send({"op": "drain",
                             "reason": "fleet drain",
                             "settle_timeout_s":
                                 self.config.drain_settle_s})
            finally:
                client.close()
        except (OSError, ValueError):
            pass

    def _replace(self, rep: _Replica):
        """Reap the drained replica and respawn its slot (same index,
        same device subset, next generation) — warm via the shared
        persist dir. A replacement failure marks the slot ``failed``
        (the fleet serves on with n-1; the prober leaves it alone)."""
        if rep.backend.alive():
            self._send_drain(rep)
        try:
            rep.backend.stop()
        except Exception as exc:  # noqa: BLE001 - reap boundary
            telemetry.event("fleet_replica_reap_error",
                            replica=rep.index, error=str(exc))
        if self._stop.is_set():
            # Teardown in progress: the old backend is reaped, but a
            # fresh spawn would only leak past stop() — leave the
            # slot drained.
            return
        try:
            backend = self.factory(rep.index, rep.generation + 1)
        except Exception as exc:  # noqa: BLE001 - respawn boundary
            with self._lock:
                rep.state = "failed"
            telemetry.event("fleet_replica_respawn_failed",
                            replica=rep.index,
                            error=f"{type(exc).__name__}: {exc}")
            return
        with self._lock:
            rep.backend = backend
            rep.generation += 1
            rep.state = "healthy"
            rep.strikes = 0
            rep.inflight = 0
            rep.last_stats = None
            rep.replaced_at = time.monotonic()
            self.replaced_total += 1
        telemetry.event("fleet_replica_replaced", replica=rep.index,
                        generation=rep.generation)

    # -- dispatch -----------------------------------------------------

    def _mint_request_id(self, request_id) -> str:
        with self._lock:
            self._request_seq += 1
            seq = self._request_seq
        if request_id:
            rid = str(request_id)
            if len(rid) > 64:
                # Cap length WITHOUT aliasing (the JoinService
                # scheme): two long ids sharing a 64-char prefix must
                # stay distinct — the duplicate-dispatch fence keys
                # on rid.
                rid = (rid[:48] + "-"
                       + hashlib.sha256(
                           rid.encode()).hexdigest()[:15])
            return rid
        return f"flt-{self._id_stamp}-{seq:06d}"

    def dispatch(self, req: dict) -> dict:
        """Route one wire request: affinity ring walk, admission,
        bounded failover, duplicate-id fencing, and the observability
        fan-out. Always returns a response dict (structured errors
        included) — the router never crashes a client."""
        from distributed_join_tpu.parallel.faults import (
            retry_with_backoff,
        )

        op = req.get("op", "?")
        rid = self._mint_request_id(req.get("request_id"))
        key = self.affinity_key(req)
        t0 = time.perf_counter()
        # The duplicate-dispatch fence: one id, one in-flight dispatch
        # at a time. A resend that arrives while the original is still
        # running PARKS until the original settles (the documented
        # reconnect-and-resend client pattern — its first answer was
        # lost with the torn connection, so the resend must be served,
        # idempotently, not refused), bounded by the request deadline.
        fence_deadline = time.monotonic() \
            + self.config.request_deadline_s
        while True:
            with self._lock:
                if rid not in self._inflight_ids:
                    self._inflight_ids.add(rid)
                    break
            if time.monotonic() >= fence_deadline:
                with self._lock:
                    self.rejected += 1
                self.live.record_request(op, "rejected")
                # Every refusal lands in the postmortem ring — this
                # is the one path that bypasses _observe's fan-out.
                self.recorder.record(
                    request_id=rid, op=op, signature=key,
                    outcome="rejected", reason="duplicate_fence")
                return {"ok": False, "error": "FleetError",
                        "message": f"request id {rid!r} still in "
                                   "flight past the request deadline "
                                   "(duplicate fenced)",
                        "request_id": rid}
            time.sleep(0.05)
        state = {"attempts": 0, "failovers": 0, "replica": None}
        outcome = "failed"
        resp = None
        try:
            resp = self._dispatch_attempts(
                req, rid, key, state, retry_with_backoff)
            outcome = "served" if resp.get("ok") else "failed"
            return resp
        except AdmissionError as exc:
            outcome = "rejected"
            with self._lock:
                self.shed_total += 1
                self.rejected += 1
            resp = {"ok": False, "error": "AdmissionError",
                    "message": str(exc), "shed": True,
                    "request_id": rid,
                    "fleet": {"attempts": state["attempts"]}}
            return resp
        except FleetError as exc:
            resp = {"ok": False, "error": "FleetError",
                    "message": str(exc), "request_id": rid,
                    "fleet": {"attempts": state["attempts"],
                              "failovers": state["failovers"]}}
            return resp
        finally:
            with self._lock:
                self._inflight_ids.discard(rid)
            self._observe(rid, op, key, outcome, state,
                          time.perf_counter() - t0, resp)

    def _dispatch_attempts(self, req, rid, key, state,
                           retry_with_backoff):
        deadline = time.monotonic() + self.config.request_deadline_s
        # index -> generation at HARD-failure time (dead connection,
        # hang, poison): a later attempt may return to the slot only
        # once it has been REPLACED. Transient busy/draining refusals
        # go in `soft_failed` instead — skipped on the first ring
        # pass but re-eligible on the fallback pass (the replica-side
        # pending bound drains between backoffs; fencing it on
        # generation would starve a small fleet into shedding).
        last_failed: dict = {}
        soft_failed: set = set()

        def attempt_once():
            state["attempts"] += 1
            rep = self._pick(key, last_failed, soft_failed)
            if rep is None:
                raise AdmissionError(
                    "fleet admission: no admittable replica "
                    f"(inflight bound "
                    f"{self.config.max_inflight_per_replica}"
                    " or p95/QPS shed policy); retry with backoff")
            state["replica"] = rep
            gen0 = rep.generation
            try:
                remaining = max(deadline - time.monotonic(), 0.1)
                client = ServiceClient(*rep.addr(),
                                       timeout_s=remaining)
                try:
                    resp = client.send(
                        {**req, "request_id": rid})
                finally:
                    # Superseded attempts are abandoned with their
                    # connection — a late answer is never read.
                    client.close()
            except (OSError, ValueError) as exc:
                self._strike(
                    rep, f"request {rid}: "
                         f"{type(exc).__name__}: {exc}")
                last_failed[rep.index] = gen0
                raise _AttemptFailed(
                    f"replica {rep.index} connection failed: "
                    f"{type(exc).__name__}: {exc}") from exc
            finally:
                with self._lock:
                    if rep.generation == gen0:
                        rep.inflight = max(rep.inflight - 1, 0)
            fault = self._replica_fault(resp)
            if fault is not None:
                if fault in ("hang", "poisoned"):
                    self._drain(rep, f"request {rid}: {fault}")
                    last_failed[rep.index] = gen0
                else:
                    # busy/draining: transient — steer the next
                    # attempt elsewhere, but stay re-eligible on the
                    # fallback pass.
                    soft_failed.add(rep.index)
                raise _AttemptFailed(
                    f"replica {rep.index} {fault}: "
                    f"{resp.get('message') or resp.get('error')}")
            return self._augment(resp, rep, state)

        try:
            resp, attempts = retry_with_backoff(
                attempt_once,
                max_attempts=self.config.retry_budget + 1,
                backoff_s=self.config.retry_backoff_s,
                deadline_s=self.config.request_deadline_s,
                retry_on=(_AttemptFailed,),
            )
            state["failovers"] = len(attempts) - 1
            with self._lock:
                self.failovers_total += state["failovers"]
            return resp
        except _AttemptFailed as exc:
            trail = getattr(exc, "_retry_attempts", [])
            state["failovers"] = max(len(trail) - 1, 0)
            with self._lock:
                self.failovers_total += state["failovers"]
            raise FleetError(
                f"request {rid} failed after {len(trail)} attempt(s) "
                f"(retry_budget={self.config.retry_budget}): "
                f"{exc}") from exc

    def _pick(self, key: str, exclude: dict,
              soft: Optional[set] = None) -> Optional[_Replica]:
        """Pick AND reserve (inflight slot taken under the one lock,
        so two concurrent dispatches can never both pass the
        admission bound). The caller releases the slot in its
        dispatch finally. ``exclude`` maps a HARD-failed replica's
        index to its generation AT failure: that slot becomes
        eligible again only once REPLACED (generation moved) — never
        handed the same request back while still the known-bad
        incarnation. ``soft`` holds transiently-refusing (busy/
        draining) indices: preferred-against on the first pass,
        re-eligible on the fallback pass."""
        with self._lock:
            n = len(self.replicas)
            if not n:
                return None
            start = int(key[:8], 16) % n
            order = [self.replicas[(start + k) % n]
                     for k in range(n)]
            for second_pass in (False, True):
                for rep in order:
                    if rep.index in exclude:
                        if not second_pass:
                            continue
                        if rep.generation <= exclude[rep.index]:
                            continue
                    if not second_pass and soft \
                            and rep.index in soft:
                        continue
                    if self._admittable(rep):
                        rep.inflight += 1
                        return rep
        return None

    @staticmethod
    def _replica_fault(resp: dict) -> Optional[str]:
        """Classify an error response: replica-fatal (failover-able)
        vs a client error passed through untouched. HangError and
        poisoned refusals mean the REPLICA is gone for serving
        purposes; draining/pending refusals mean try a sibling; any
        other error (ValueError, IntegrityError, ...) is the CLIENT's
        answer — a refusal the fleet must not mask."""
        if resp.get("ok"):
            return None
        err = resp.get("error")
        msg = str(resp.get("message", ""))
        if err == "HangError":
            return "hang"
        if err in ("AdmissionError", "DrainingError"):
            if "poisoned" in msg:
                return "poisoned"
            if "draining" in msg or err == "DrainingError":
                return "draining"
            return "busy"
        return None

    def _augment(self, resp: dict, rep: _Replica, state) -> dict:
        resp = dict(resp)
        resp["fleet"] = {
            "replica": rep.index,
            "generation": rep.generation,
            "attempts": state["attempts"],
            "failovers": state["attempts"] - 1,
        }
        return resp

    def _observe(self, rid, op, key, outcome, state, elapsed_s,
                 resp):
        """Fleet-side accounting fan-out (live metrics, flight ring,
        history line stamped with the serving replica). Never fails a
        request."""
        try:
            rep = state.get("replica")
            stamp = ({"index": rep.index,
                      "generation": rep.generation,
                      "port": rep.backend.port}
                     if rep is not None else None)
            with self._lock:
                if outcome == "served":
                    self.served += 1
                elif outcome == "failed":
                    self.failed += 1
            self.live.record_request(
                op, outcome,
                latency_s=elapsed_s if outcome == "served" else None,
                signature=key,
                new_traces=int((resp or {}).get("new_traces") or 0))
            self.recorder.record(
                request_id=rid, op=op, signature=key,
                outcome=outcome, elapsed_s=round(elapsed_s, 6),
                matches=(resp or {}).get("matches"),
                new_traces=(resp or {}).get("new_traces"),
                failovers=state.get("failovers", 0),
                replica=stamp,
                error=(None if (resp or {}).get("ok")
                       else (resp or {}).get("message")))
            if self.history is not None and op not in ("ping",
                                                       "stats",
                                                       "metrics"):
                self.history.append(tel_history.request_entry(
                    request_id=rid, op=op, signature=key,
                    outcome=outcome, wall_s=elapsed_s,
                    new_traces=int((resp or {}).get("new_traces")
                                   or 0),
                    matches=(resp or {}).get("matches"),
                    error=(None if (resp or {}).get("ok")
                           else str((resp or {}).get("message"))),
                    replica=stamp))
        except Exception as exc:  # noqa: BLE001 - bookkeeping boundary
            telemetry.event("fleet_observability_error",
                            request_id=rid,
                            error=f"{type(exc).__name__}: {exc}")

    # -- operator surfaces --------------------------------------------

    def dump_flight_recorder(self, reason: str) -> Optional[str]:
        """Dump the router's request ring (the daemon's postmortem
        contract, fleet-side): to ``flight_recorder_path``, else
        ``history_dir``. Called at stop when a path is configured;
        safe to call any time for a live snapshot."""
        path = self.config.flight_recorder_path
        if path is None:
            if self.config.history_dir is None:
                return None
            path = os.path.join(self.config.history_dir,
                                tel_live.FLIGHT_RECORDER_FILENAME)
        try:
            path = self.recorder.dump(path, reason)
        except OSError as exc:
            telemetry.event("fleet_flightrecorder_dump_failed",
                            path=path,
                            error=f"{type(exc).__name__}: {exc}")
            return None
        telemetry.event("fleet_flightrecorder_dumped", path=path,
                        reason=reason)
        return path

    def stats(self) -> dict:
        with self._lock:
            reps = [{
                "index": r.index,
                "generation": r.generation,
                "state": r.state,
                "port": getattr(r.backend, "port", None),
                "inflight": r.inflight,
                "strikes": r.strikes,
                "qps_60s": (r.last_stats or {}).get("qps_60s"),
                "p95_s": ((r.last_stats or {}).get("latency")
                          or {}).get("p95_s"),
                "poisoned": (r.last_stats or {}).get("poisoned"),
            } for r in self.replicas]
            counts: dict = {}
            for r in self.replicas:
                counts[r.state] = counts.get(r.state, 0) + 1
            return {
                "role": "fleet",
                "replicas": len(self.replicas),
                "healthy": counts.get("healthy", 0),
                "suspect": counts.get("suspect", 0),
                "drained": counts.get("drained", 0),
                "failed": counts.get("failed", 0),
                "failovers_total": self.failovers_total,
                "shed_total": self.shed_total,
                "replaced_total": self.replaced_total,
                "drains_total": self.drains_total,
                "served": self.served,
                "failed_requests": self.failed,
                "rejected": self.rejected,
                "qps_60s": round(self.live.qps(), 3),
                "uptime_s": round(self.live.uptime_s(), 3),
                "latency": self.live.overall_latency(),
                "replica_detail": reps,
            }

    def prometheus_metrics(self) -> str:
        st = self.stats()
        return self.live.to_prometheus(gauges={
            "fleet_replicas": st["replicas"],
            "fleet_healthy": st["healthy"],
            "fleet_suspect": st["suspect"],
            "fleet_drained": st["drained"],
            "fleet_failovers_total": st["failovers_total"],
            "fleet_shed_total": st["shed_total"],
            "fleet_replaced_total": st["replaced_total"],
            "fleet_drains_total": st["drains_total"],
        })

    def metrics_snapshot(self) -> dict:
        snap = self.live.snapshot()
        snap["stats"] = self.stats()
        snap["flight_records"] = len(self.recorder)
        snap["history_path"] = (self.history.path
                                if self.history is not None else None)
        return snap

    def drain_replica(self, index: int,
                      reason: str = "operator drain") -> dict:
        """The operator op: drain (and replace) one replica by
        index."""
        with self._lock:
            if not 0 <= index < len(self.replicas):
                raise ValueError(f"no replica {index}")
            rep = self.replicas[index]
        self._drain(rep, reason)
        return {"replica": index, "state": rep.state,
                "reason": reason}

    def wait_replaced(self, index: int, timeout_s: float = 60.0
                      ) -> bool:
        """Block until replica ``index`` is healthy at a HIGHER
        generation than when it was last drained (test/smoke
        helper)."""
        rep = self.replicas[index]
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if rep.state == "healthy" \
                        and rep.replaced_at is not None \
                        and (rep.drained_at is None
                             or rep.replaced_at >= rep.drained_at):
                    return True
            time.sleep(0.05)
        return False


class _AttemptFailed(RuntimeError):
    """One failover-able dispatch attempt failed (connection death or
    a replica-fatal response) — retried by the bounded
    retry_with_backoff loop, never surfaced raw."""


# -- the router TCP daemon ---------------------------------------------


def _route(router: FleetRouter, req: dict) -> dict:
    op = req.get("op")
    if op == "ping":
        return {"ok": True, "op": "ping", "role": "fleet"}
    if op == "stats":
        return {"ok": True, **router.stats()}
    if op == "metrics":
        if req.get("format") == "prometheus":
            return {"ok": True, "op": "metrics",
                    "format": "prometheus",
                    "prometheus": router.prometheus_metrics()}
        return {"ok": True, "op": "metrics",
                "metrics": router.metrics_snapshot()}
    if op == "drain":
        if req.get("replica") is None:
            # Refuse rather than proxy: routing a bare drain to an
            # affinity-chosen replica would silently recycle one warm
            # replica while telling the operator the service drained.
            raise ValueError(
                "fleet drain needs \"replica\": <index> (drain one "
                "slot, which is then replaced); to stop the whole "
                "fleet use {\"op\": \"shutdown\"}")
        rec = router.drain_replica(
            int(req["replica"]),
            reason=str(req.get("reason", "operator drain")))
        return {"ok": True, "op": "drain", **rec}
    if op == "shutdown":
        # FLEET-level shutdown: stop the router (replicas drained and
        # reaped by the serving loop) — never routed to a replica,
        # which would just kill one daemon and watch it be replaced.
        router.shutdown_requested.set()
        return {"ok": True, "op": "shutdown", "role": "fleet"}
    return router.dispatch(req)


def start_router_daemon(router: FleetRouter, host: str = "127.0.0.1",
                        port: int = 0):
    """Bind + serve the fleet wire on a background thread; returns
    ``(server, port)``. Same line-JSON protocol as one daemon."""
    import socketserver

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            for raw in self.rfile:
                line = raw.decode("utf-8").strip()
                if not line:
                    continue
                req = None
                try:
                    req = json.loads(line)
                    resp = _route(router, req)
                except Exception as exc:  # noqa: BLE001 - wire edge
                    resp = {"ok": False,
                            "error": type(exc).__name__,
                            "message": str(exc)}
                self.wfile.write(
                    (json.dumps(resp) + "\n").encode("utf-8"))
                self.wfile.flush()
                if isinstance(req, dict) \
                        and req.get("op") == "shutdown":
                    return

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    server = Server((host, port), Handler)
    thread = threading.Thread(target=server.serve_forever,
                              daemon=True)
    thread.start()
    return server, server.server_address[1]


# -- the CI smoke ------------------------------------------------------


def run_fleet_smoke(args) -> dict:
    """The ``fleet`` lane's acceptance protocol (docs/FLEET.md), end
    to end through real subprocess replicas and the router TCP loop:

    1. 2 replicas share one persist dir; a cold query Q compiles on
       its affine replica, the warm repeat must land on the SAME
       replica with zero new traces;
    2. ONE SCRIPTED KILL (SIGKILL) of that replica; the immediate
       repeat of Q must fail over to the sibling within the bounded
       retry budget and answer with the SAME match count (graded
       against the pandas oracle);
    3. the killed replica must be drained and a replacement spawned
       (healthy at generation 1) — observed, with the drain stamped
       within one probe interval of the kill;
    4. the post-replacement repeat of Q must dispatch on the
       replacement with ZERO new traces (the shared persist dir is
       the distribution tier);
    5. a concurrent burst at inflight bound 1 must shed >= 1 request
       with a structured AdmissionError (and never queue unboundedly);
    6. fleet Prometheus gauges and the flight/history stamps are
       emitted for ``analyze check``.

    Returns the JSON record (kind ``fleet_smoke``) whose deterministic
    counter signature the perfgate lane gates against
    ``results/baselines/fleet_smoke.json``.
    """
    import tempfile

    violations: list = []
    workdir_owned = args.persist_dir is None
    workdir = args.persist_dir or tempfile.mkdtemp(
        prefix="djtpu_fleet_smoke_")
    cfg = FleetConfig(
        n_replicas=2,
        replica_ranks=args.replica_ranks,
        persist_dir=os.path.join(workdir, "programs"),
        history_dir=(args.history_dir
                     or os.path.join(workdir, "history")),
        # The failover gate (failovers_total >= 1) needs the REQUEST
        # path to discover the scripted kill: a sub-second prober
        # could drain the victim first and serve the repeat on
        # attempt 1, tripping the gate spuriously. Drain latency is
        # gated from the kill either way (the strike path drains in
        # milliseconds).
        probe_interval_s=max(args.probe_interval_s, 5.0),
        retry_budget=2,
        max_inflight_per_replica=args.max_inflight,
        flight_recorder_path=args.flight_recorder_path,
        spawn_timeout_s=args.spawn_timeout_s,
    )
    router = FleetRouter(
        process_fleet_factory(cfg, platform=args.platform or "cpu"),
        cfg)
    router.start()
    server, port = start_router_daemon(router)
    client = ServiceClient("127.0.0.1", port, retries=2)

    q = {"op": "join", "build_nrows": 2048, "probe_nrows": 2048,
         "seed": 17, "selectivity": 0.4, "rand_max": 1024,
         "out_capacity_factor": 3.0}

    def oracle_matches():
        from distributed_join_tpu.service.server import (
            _tables_from_spec,
        )

        build, probe = _tables_from_spec(q)
        return len(build.to_pandas().merge(probe.to_pandas(),
                                           on="key"))

    try:
        expected = oracle_matches()
        cold = client.send(q)
        if not cold.get("ok"):
            raise RuntimeError(f"cold query failed: {cold}")
        warm = client.send(q)
        if not warm.get("ok"):
            raise RuntimeError(f"warm query failed: {warm}")
        if warm["fleet"]["replica"] != cold["fleet"]["replica"]:
            violations.append(
                "affinity broke: warm repeat routed to replica "
                f"{warm['fleet']['replica']}, cold ran on "
                f"{cold['fleet']['replica']}")
        if warm["new_traces"] != 0:
            violations.append(
                f"warm repeat traced {warm['new_traces']} new "
                "program(s)")
        for name, resp in (("cold", cold), ("warm", warm)):
            if resp["matches"] != expected:
                violations.append(
                    f"{name} matches {resp['matches']} != pandas "
                    f"oracle {expected}")

        # THE scripted kill: SIGKILL the affine replica mid-traffic.
        victim = router.replicas[cold["fleet"]["replica"]]
        victim_index = victim.index
        t_kill = time.monotonic()
        victim.backend.kill()
        failover = client.send(q)
        if not failover.get("ok"):
            violations.append(
                f"failover repeat was not served: {failover}")
        else:
            if failover["matches"] != expected:
                violations.append(
                    f"failover matches {failover['matches']} != "
                    f"oracle {expected}")
            if failover["fleet"]["replica"] == victim_index:
                violations.append(
                    "failover answered from the killed replica")
            if failover["fleet"]["attempts"] > cfg.retry_budget + 1:
                violations.append(
                    f"failover took {failover['fleet']['attempts']} "
                    f"attempts > budget {cfg.retry_budget + 1}")

        # Drain observed within one probe interval (+ scheduling
        # slack), replacement healthy at generation 1.
        replaced = router.wait_replaced(victim_index,
                                        timeout_s=cfg.spawn_timeout_s)
        if not replaced:
            violations.append(
                f"killed replica {victim_index} was not replaced "
                f"within {cfg.spawn_timeout_s}s")
        drained_after_s = ((victim.drained_at or time.monotonic())
                           - t_kill)
        if victim.drained_at is None or drained_after_s > \
                3 * cfg.probe_interval_s + 5.0:
            violations.append(
                f"kill -> drained took {drained_after_s:.2f}s "
                f"(> probe interval {cfg.probe_interval_s}s + slack)")

        # Post-replacement repeat: the replacement must serve the
        # pre-fault signature WARM (zero traces via the shared
        # persist dir). Route directly at the replacement to pin the
        # assertion on it — only when a replacement is actually up
        # (dialing the SIGKILLed backend's old port would crash the
        # harness instead of reporting the violation above).
        replay: dict = {}
        if replaced:
            try:
                direct = ServiceClient(
                    *router.replicas[victim_index].addr(),
                    timeout_s=120.0)
                try:
                    replay = direct.send(
                        {**q, "request_id": "smoke-replay"})
                finally:
                    direct.close()
            except (OSError, ValueError) as exc:
                violations.append(
                    "replacement replica unreachable for the "
                    f"replay: {type(exc).__name__}: {exc}")
            if replay and not replay.get("ok"):
                violations.append(
                    f"replacement replica refused the replay: "
                    f"{replay}")
            elif replay:
                if replay["matches"] != expected:
                    violations.append(
                        f"replacement matches {replay['matches']} "
                        f"!= oracle {expected}")
                if replay["new_traces"] != 0:
                    violations.append(
                        "replacement was not warm: "
                        f"{replay['new_traces']} new trace(s) — the "
                        "shared persist dir must hand it the "
                        "compiled program")

        # Synthetic overload: a concurrent burst at inflight bound 1
        # must shed with structured errors, never queue unboundedly.
        router.config.max_inflight_per_replica = 1
        burst_n = 8
        results = [None] * burst_n

        def fire(i):
            c = ServiceClient("127.0.0.1", port)
            try:
                results[i] = c.send(
                    {**q, "request_id": f"burst-{i}"})
            finally:
                c.close()

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(burst_n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300.0)
        shed = [r for r in results
                if r is not None and r.get("shed")]
        served = [r for r in results
                  if r is not None and r.get("ok")]
        if not shed:
            violations.append(
                f"burst of {burst_n} at inflight bound 1 shed "
                "nothing — admission is queueing unboundedly")
        for r in served:
            if r["matches"] != expected:
                violations.append(
                    f"burst answer {r['matches']} != oracle "
                    f"{expected}")
        if len(shed) + len(served) != burst_n:
            violations.append(
                f"burst lost requests: {len(served)} served + "
                f"{len(shed)} shed != {burst_n}")
        router.config.max_inflight_per_replica = args.max_inflight

        prom = router.prometheus_metrics()
        for gauge in ("djtpu_fleet_replicas", "djtpu_fleet_healthy",
                      "djtpu_fleet_drained",
                      "djtpu_fleet_failovers_total",
                      "djtpu_fleet_shed_total"):
            if gauge not in prom:
                violations.append(
                    f"prometheus exposition missing {gauge}")

        stats = router.stats()
        if stats["replaced_total"] < 1:
            violations.append("no replacement counted")
        if stats["failovers_total"] < 1:
            violations.append("no failover counted")
        if stats["healthy"] != 2:
            violations.append(
                f"fleet did not return to 2 healthy replicas: "
                f"{stats}")
    finally:
        client.close()
        server.shutdown()
        server.server_close()
        router.stop()

    record = {
        "kind": "fleet_smoke",
        "benchmark": "fleet_smoke",
        "n_ranks": cfg.replica_ranks,
        "replicas": cfg.n_replicas,
        "matches_expected": expected,
        "killed_replica": victim_index,
        "drained_after_s": round(drained_after_s, 3),
        "failover_attempts": (failover.get("fleet", {})
                              .get("attempts")),
        "burst_served": len(served),
        "burst_shed": len(shed),
        "stats": stats,
        "history_path": (router.history.path
                         if router.history is not None else None),
        "violations": violations,
        # The deterministic gate body (integer counters only; shed/
        # failover TIMINGS and counts beyond the gates above are
        # load-dependent and stay outside the signature).
        "counter_signature": {
            "signature_version": 1,
            "n_ranks": cfg.replica_ranks,
            "counters": {
                "replicas": cfg.n_replicas,
                "matches_cold": cold["matches"],
                "matches_warm": warm["matches"],
                "matches_failover": failover.get("matches", -1),
                "matches_replacement": replay.get("matches", -1),
                "warm_new_traces": warm["new_traces"],
                "replacement_new_traces": replay.get("new_traces",
                                                     -1),
                "requests_lost": burst_n - len(served) - len(shed),
            },
        },
    }
    if violations:
        # Keep the workdir (program blobs, history, flight dumps) —
        # it IS the postmortem of a failed smoke.
        record["workdir"] = workdir
        raise FleetSmokeError(
            "fleet smoke violations: " + "; ".join(violations),
            record)
    if workdir_owned:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    return record


class FleetSmokeError(RuntimeError):
    def __init__(self, msg, record):
        super().__init__(msg)
        self.record = record


# -- CLI ---------------------------------------------------------------


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="router TCP port (0 = ephemeral; printed on "
                        "the 'listening' line)")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--replica-ranks", type=int, default=2,
                   help="mesh size of EACH replica (disjoint hosts "
                        "on hardware; per-process virtual CPU meshes "
                        "in tests)")
    p.add_argument("--platform", default=None,
                   help="forwarded to each replica (--platform cpu "
                        "for the CPU-mesh fleet)")
    p.add_argument("--persist-dir", default=None, metavar="DIR",
                   help="SHARED compiled-program dir: the fleet's "
                        "distribution tier (replacements restart "
                        "warm)")
    p.add_argument("--history-dir", default=None, metavar="DIR",
                   help="fleet-level per-request history store "
                        "(entries stamped with the serving replica)")
    p.add_argument("--probe-interval-s", type=float, default=1.0)
    p.add_argument("--probe-timeout-s", type=float, default=5.0)
    p.add_argument("--spawn-timeout-s", type=float, default=180.0)
    p.add_argument("--suspect-strikes", type=int, default=2)
    p.add_argument("--retry-budget", type=int, default=2,
                   help="bounded failover attempts per request "
                        "beyond the first")
    p.add_argument("--request-deadline-s", type=float, default=300.0)
    p.add_argument("--max-inflight", type=int, default=4,
                   help="per-replica inflight admission bound "
                        "(beyond it the router sheds with a "
                        "structured AdmissionError)")
    p.add_argument("--shed-p95-s", type=float, default=None)
    p.add_argument("--shed-qps", type=float, default=None)
    p.add_argument("--no-respawn", action="store_true",
                   help="drain faulted replicas but do not replace "
                        "them (debugging)")
    p.add_argument("--replica-arg", action="append", default=[],
                   metavar="ARG",
                   help="extra argv token forwarded to every "
                        "replica (repeatable)")
    p.add_argument("--flight-records", type=int, default=256)
    p.add_argument("--flight-recorder-path", default=None)
    p.add_argument("--smoke", action="store_true",
                   help="run the CI acceptance protocol (2-replica "
                        "CPU-mesh fleet, scripted replica kill, "
                        "oracle/drain/replace/shed gates) instead of "
                        "serving; JSON record on stdout")
    p.add_argument("--json-output", default=None)
    return p.parse_args(argv)


def main(argv=None) -> int:
    from distributed_join_tpu.benchmarks import report

    args = parse_args(argv)
    if args.smoke:
        try:
            record = run_fleet_smoke(args)
        except FleetSmokeError as exc:
            report("fleet smoke FAILED", exc.record,
                   args.json_output)
            print(str(exc), file=sys.stderr)
            return 1
        report(
            f"fleet smoke: {record['replicas']} replicas, kill -> "
            f"drained in {record['drained_after_s']}s, failover in "
            f"{record['failover_attempts']} attempt(s), replacement "
            "warm with 0 traces, "
            f"{record['burst_shed']} shed under synthetic overload",
            record, args.json_output)
        return 0

    cfg = FleetConfig(
        n_replicas=args.replicas,
        replica_ranks=args.replica_ranks,
        persist_dir=args.persist_dir,
        history_dir=args.history_dir,
        probe_interval_s=args.probe_interval_s,
        probe_timeout_s=args.probe_timeout_s,
        spawn_timeout_s=args.spawn_timeout_s,
        suspect_strikes=args.suspect_strikes,
        retry_budget=args.retry_budget,
        request_deadline_s=args.request_deadline_s,
        max_inflight_per_replica=args.max_inflight,
        shed_p95_s=args.shed_p95_s,
        shed_qps=args.shed_qps,
        respawn=not args.no_respawn,
        flight_records=args.flight_records,
        flight_recorder_path=args.flight_recorder_path,
    )
    router = FleetRouter(
        process_fleet_factory(cfg, platform=args.platform or "cpu",
                              extra_args=args.replica_arg),
        cfg)
    router.start()
    server, port = start_router_daemon(router, args.host, args.port)
    print(f"join-fleet listening on {args.host}:{port} "
          f"({cfg.n_replicas} replicas x {cfg.replica_ranks} ranks)",
          flush=True)
    try:
        import signal

        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        while not stop.wait(0.5):
            if router.shutdown_requested.is_set():
                break
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        server.server_close()
        router.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
