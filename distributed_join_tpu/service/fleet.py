"""Fault-tolerant serving fleet: a signature-affinity router over N
``tpu-join-service`` replicas (docs/FLEET.md; ROADMAP item 6).

One daemon = one mesh = one SPMD program at a time, and the
deliberately conservative hang semantics of docs/FAILURE_SEMANTICS.md
mean a poisoned daemon refuses traffic until a human restarts it. The
fleet tier makes the failure domain ONE REPLICA instead of the
service:

- **Routing is signature-affine.** The router hashes the SAME
  canonical workload-signature digest the program cache and tuner key
  on (:func:`~..planning.tuner.workload_signature` over abstract
  tables built from the wire spec — zero traces, zero devices), so a
  repeat workload lands where its executable is already resident;
  table-management ops hash the table name, so ``register`` and the
  probe-only ``join`` of one handle co-locate. A shared AOT
  ``--persist-dir`` is the compiled-program distribution tier: any
  replica (including a cold replacement) loads a sibling's programs
  with zero traces.
- **Replica lifecycle.** Periodic ``stats`` health probes plus
  per-request outcome accounting drive a state machine
  (healthy -> suspect -> drained). A replica that reports
  ``poisoned``, times out, or drops its connection is DRAINED (no new
  requests; in-flight completes or deadlines out under the replica's
  own watchdog) and REPLACED (respawned on its device subset, warm
  via the shared persist dir) — never left refusing traffic forever.
- **Failover.** A request whose replica dies mid-flight retries on
  the next affine replica under a bounded
  :func:`~..parallel.faults.retry_with_backoff` budget with a
  per-request deadline — idempotent because the wire carries query
  SPECS, not table bytes. Duplicate dispatch is fenced by request id:
  a second arrival of an id still in flight is refused, and a
  superseded attempt's connection is abandoned (its late answer is
  never read).
- **Load shedding.** Admission at the router is driven by per-replica
  inflight counters plus the ``qps_60s``/p95 figures of the replicas'
  own :class:`~..telemetry.live.LiveMetrics` snapshots (taken by the
  health probe): when no replica is admittable the router answers a
  structured ``AdmissionError`` instead of queueing unboundedly.
- **Replicated resident state** (``table_replication`` = K > 1,
  docs/FLEET.md "Replication"): ``register`` writes a versioned TABLE
  MANIFEST (name, key, generation, the register spec, every append
  delta spec, a payload digest, prep knobs) to the shared coord dir,
  then fans the registration out to the K live replicas from the
  table's affine ring slot. ``append`` applies to every holder with
  GENERATION FENCING: a holder that misses the delta is marked stale
  in the router's table directory and the router injects
  ``min_generation`` into probe-only joins, so a stale image REFUSES
  (``StaleGenerationError``) instead of silently serving rows that
  exclude the delta — the router fails the attempt over to an
  up-to-date holder. A replacement replica REBUILDS its image from
  the manifest on its slot (``rebuilding -> serving`` holder
  lifecycle; warm probe-only programs reload from the AOT persist
  dir, so the rebuilt image serves repeat signatures with zero new
  traces). When NO live holder exists, table ops answer a structured
  ``NoHolderError`` — never a misroute.
- **Router HA** (:class:`RouterHA`, ROADMAP 5b): N router processes
  share the pure affinity function, the durable manifests, and a
  generation-fenced replica/table DIRECTORY file — no consensus. A
  fenced LEASE file elects the one serving primary; a standby polls
  it, and on primary death (lease stale past its TTL) acquires the
  lease, adopts the directory, binds the advertised endpoint, and
  serves. Clients ride the same reconnect+resend contract as replica
  failover: idempotent ops resend through their bounded backoff,
  mutating ops refuse client-side resend.
- **Observability.** The router keeps its own
  :class:`~..telemetry.live.LiveMetrics` /
  :class:`~..telemetry.live.FlightRecorder` /
  :class:`~..telemetry.history.WorkloadHistory` (entries stamped with
  the serving replica and, for resident traffic, the table's holder
  set + generation), and exposes fleet-level Prometheus gauges
  ``djtpu_fleet_{replicas,healthy,suspect,drained,failovers_total,
  shed_total,replaced_total,rebuilds_total}``,
  ``djtpu_fleet_resident_holders{table}``, ``djtpu_router_role`` and
  ``djtpu_router_takeovers_total`` next to the usual request
  counters.

``python -m distributed_join_tpu.service.fleet`` (``tpu-join-fleet``)
serves the same line-JSON wire protocol as one daemon — clients do not
change. ``--smoke`` runs the CI acceptance protocol (the ``fleet``
lane of ``scripts/run_tier1.sh``): a 2-replica CPU-mesh fleet, warm
affinity discipline, ONE SCRIPTED REPLICA KILL mid-traffic, and gates
on oracle equality, drain+replace observed, bounded retry count, and
a zero-trace warm repeat on the replacement. ``--ha-smoke`` runs the
replication/HA acceptance protocol (the ``fleet_ha`` lane): K=2
resident replication, a holder kill with manifest-driven rebuild, and
a router kill with standby takeover — warm, fenced, oracle-graded.
``--standby`` joins an existing coord dir as a standby router.

The chaos soak lives in ``parallel/chaos.py --fleet`` (kill / hang /
corrupt one replica mid-soak, every non-refused answer graded against
the pandas oracle; ``--fleet-fault resident-kill`` kills a registered
table's primary holder instead).
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import os
import subprocess
import sys
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

from distributed_join_tpu import telemetry
from distributed_join_tpu.service.programs import (
    atomic_write_json,
    spec_digest,
)
from distributed_join_tpu.service.server import (
    AdmissionError,
    ServiceClient,
    _join_opts_from_spec,
)
from distributed_join_tpu.telemetry import history as tel_history
from distributed_join_tpu.telemetry import live as tel_live
from distributed_join_tpu.telemetry import tracectx


class FleetError(RuntimeError):
    """A fleet-level structured failure (failover budget exhausted,
    duplicate in-flight request id) — answered on the wire, never an
    unstructured crash of the router."""


class NoHolderError(FleetError):
    """A table op or probe-only join found NO live holder for its
    table (replication on): answered as a structured refusal — never
    silently misrouted to a replica that would invent an 'unknown
    table' answer for state the fleet actually owns."""


class QuotaExceededError(FleetError):
    """A tenant crossed one of ITS OWN admission bounds (the
    ``tenants`` config map: QPS token bucket, per-tenant inflight
    cap, or per-tenant ``shed_p95_s``): answered as a structured
    ``shed`` refusal NAMING the bound — the over-quota tenant is shed
    while within-quota tenants keep being served
    (docs/FLEET.md "Multi-tenancy & autoscaling")."""


class ShedError(FleetError):
    """Priority-weighted overload shed: under fleet-wide pressure a
    LOW-PRIORITY tenant's per-replica inflight headroom (its
    priority's share of ``max_inflight_per_replica``) ran out while
    higher-priority traffic still fits — the low-priority request is
    shed FIRST, with a structured refusal naming the priority bound,
    so the quiet high-priority tenant is never the one refused."""


# Durable-state artifact versions (docs/FAILURE_SEMANTICS.md,
# "Replication & durability contract"). `analyze check` validates
# both kinds.
TABLE_MANIFEST_SCHEMA_VERSION = 1
ROUTER_DIRECTORY_SCHEMA_VERSION = 1
ROUTER_DIRECTORY_FILENAME = "router_directory.json"
ROUTER_LEASE_FILENAME = "router_lease.json"
MANIFEST_SUFFIX = ".manifest.json"


@dataclasses.dataclass
class FleetConfig:
    """Router policy knobs (docs/FLEET.md).

    ``replica_ranks`` is each replica's mesh size (the affinity hash
    binds it — the workload signature covers ``n_ranks``);
    ``persist_dir`` is the SHARED compiled-program distribution tier
    (a replacement replica cold-loads its predecessor's programs with
    zero traces). ``probe_interval_s`` paces the health prober;
    ``suspect_strikes`` is how many consecutive probe/request
    failures turn suspicion into a drain. ``retry_budget`` bounds
    failover attempts per request (total attempts = budget + 1) and
    ``request_deadline_s`` bounds the whole request across attempts.
    ``max_inflight_per_replica`` plus the optional ``shed_p95_s`` /
    ``shed_qps`` bounds (read from the replicas' probed LiveMetrics
    snapshots) drive admission — beyond them the router sheds with a
    structured ``AdmissionError``.

    ``table_replication`` (K) is the resident-table holder count:
    K=1 (default) is the exact pre-replication behavior — table ops
    route by affinity hash alone, no manifests, no directory. K>1
    turns on durable manifests, holder fan-out, and generation
    fencing. ``coord_dir`` holds the manifests + the router
    directory + the HA lease (defaults to ``persist_dir``);
    ``lease_ttl_s``/``lease_renew_s`` pace the HA lease,
    ``router_id`` names this router in the lease/directory files.
    """

    n_replicas: int = 2
    replica_ranks: int = 2
    persist_dir: Optional[str] = None
    probe_interval_s: float = 1.0
    probe_timeout_s: float = 5.0
    spawn_timeout_s: float = 180.0
    drain_settle_s: float = 10.0
    suspect_strikes: int = 2
    retry_budget: int = 2
    retry_backoff_s: float = 0.1
    request_deadline_s: float = 300.0
    max_inflight_per_replica: int = 4
    shed_p95_s: Optional[float] = None
    shed_qps: Optional[float] = None
    respawn: bool = True
    history_dir: Optional[str] = None
    flight_records: int = 256
    flight_recorder_path: Optional[str] = None
    table_replication: int = 1
    coord_dir: Optional[str] = None
    lease_ttl_s: float = 3.0
    lease_renew_s: float = 0.5
    router_id: Optional[str] = None
    # Multi-tenant admission (docs/FLEET.md "Multi-tenancy &
    # autoscaling"): ``tenants`` maps a tenant name to its bounds —
    # ``{"qps": float, "burst_s": float, "max_inflight": int,
    # "priority": int, "shed_p95_s": float}`` (every key optional).
    # Unconfigured tenants (including the implicit default tenant of
    # unstamped requests) keep the exact pre-tenant behavior: no
    # quota, full priority. A configured ``shed_p95_s`` is the
    # per-tenant replacement for the global knob above: the tenant is
    # shed when even the BEST live replica's probed p95 exceeds it.
    tenants: Optional[dict] = None
    # Signature-level autoscaler: a router-side control loop over the
    # probed per-replica LiveMetrics. Sustained (``autoscale_sustain``
    # consecutive ticks, ``autoscale_interval_s`` apart) fleet QPS
    # over ``autoscale_up_qps`` or worst probed p95 over
    # ``autoscale_up_p95_s`` spawns ONE replica (up to
    # ``autoscale_max_replicas``), pre-warm verified against the
    # hottest retained join spec (zero new traces via the shared
    # persist dir) BEFORE entering rotation; fleet QPS at or below
    # ``autoscale_down_qps`` sustained for ``autoscale_idle_s``
    # drains the highest-index scaled-up idle replica (never below
    # the configured base ``n_replicas``).
    autoscale: bool = False
    autoscale_max_replicas: int = 4
    autoscale_up_qps: Optional[float] = None
    autoscale_up_p95_s: Optional[float] = None
    autoscale_down_qps: float = 0.0
    autoscale_idle_s: float = 30.0
    autoscale_interval_s: float = 1.0
    autoscale_sustain: int = 3


# -- durable state: table manifests, router directory, HA lease --------


def _table_slug(name: str) -> str:
    """Filesystem-safe manifest stem for a table name (verbatim when
    it is already safe, content-hashed otherwise — two distinct names
    can never collide on disk)."""
    if name and all(c.isalnum() or c in "._-" for c in name):
        return name
    return hashlib.sha256(name.encode()).hexdigest()[:24]


def table_manifest_path(coord_dir: str, name: str) -> str:
    return os.path.join(coord_dir, "tables",
                        _table_slug(name) + MANIFEST_SUFFIX)


def table_manifest_doc(name: str, register_spec: dict, key: str,
                       generation: int, deltas: list,
                       prep: dict) -> dict:
    """The versioned table manifest (kind ``table_manifest``): enough
    to rebuild a holder's image byte-for-byte — the register spec,
    every append delta spec IN ORDER, the generation they sum to, and
    a payload digest over both (through the same canonicalizer the
    program-cache signatures use, so a rebuilt holder can prove it
    replayed the rows the original held)."""
    return {
        "kind": "table_manifest",
        "schema_version": TABLE_MANIFEST_SCHEMA_VERSION,
        "name": name,
        "key": key,
        "generation": int(generation),
        "register": register_spec,
        "deltas": list(deltas),
        "payload_digest": spec_digest(
            {"register": register_spec, "deltas": list(deltas)}),
        "prep": prep,
        "updated_unix_s": time.time(),
    }


def load_table_manifest(coord_dir: str, name: str) -> Optional[dict]:
    try:
        with open(table_manifest_path(coord_dir, name)) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def router_directory_path(coord_dir: str) -> str:
    return os.path.join(coord_dir, ROUTER_DIRECTORY_FILENAME)


def load_router_directory(coord_dir: str) -> Optional[dict]:
    try:
        with open(router_directory_path(coord_dir)) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


class RouterLease:
    """The fenced lease file behind router HA (ROADMAP 5b): ONE
    serving router at a time, no consensus — a shared filesystem
    plays coordinator. The file carries ``{owner, epoch,
    renewed_unix_s, addr}``. Acquisition is EPOCH-FENCED: a taker
    writes ``epoch + 1``, settles, and re-reads — if another
    contender's write landed last, the taker lost and stands down.
    Renewal re-reads before every write: an owner that finds a higher
    epoch (someone took over while it stalled) is FENCED OUT and must
    stop serving rather than split-brain the directory."""

    def __init__(self, path: str, owner: str, ttl_s: float = 3.0,
                 settle_s: float = 0.2):
        self.path = path
        self.owner = owner
        self.ttl_s = ttl_s
        self.settle_s = settle_s
        self.epoch = 0

    def read(self) -> Optional[dict]:
        try:
            with open(self.path) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def _write(self, epoch: int, addr=None) -> None:
        atomic_write_json(self.path, {
            "kind": "router_lease",
            "owner": self.owner,
            "epoch": int(epoch),
            "ttl_s": self.ttl_s,
            "renewed_unix_s": time.time(),
            "addr": addr,
        })

    def stale(self, doc: Optional[dict] = None) -> bool:
        doc = doc if doc is not None else self.read()
        if doc is None:
            return True
        age = time.time() - float(doc.get("renewed_unix_s") or 0.0)
        return age > self.ttl_s

    def acquire(self, addr=None) -> bool:
        doc = self.read()
        if doc is not None and not self.stale(doc) \
                and doc.get("owner") != self.owner:
            return False
        epoch = int((doc or {}).get("epoch") or 0) + 1
        self._write(epoch, addr=addr)
        time.sleep(self.settle_s)
        doc = self.read()
        if doc is None or doc.get("owner") != self.owner \
                or int(doc.get("epoch") or -1) != epoch:
            return False
        self.epoch = epoch
        return True

    def renew(self) -> bool:
        doc = self.read()
        if doc is None or doc.get("owner") != self.owner \
                or int(doc.get("epoch") or -1) != self.epoch:
            return False
        self._write(self.epoch, addr=doc.get("addr"))
        return True

    def release(self) -> None:
        """Hand off immediately: stamp the lease stale so a standby
        takes over without waiting out the TTL."""
        doc = self.read()
        if doc is not None and doc.get("owner") == self.owner \
                and int(doc.get("epoch") or -1) == self.epoch:
            atomic_write_json(self.path,
                              {**doc, "renewed_unix_s": 0.0})


# -- replica backends --------------------------------------------------


class ProcessReplica:
    """One ``tpu-join-service`` subprocess (the production backend:
    disjoint hosts on hardware, per-process virtual CPU meshes in
    tests). The constructor blocks until the daemon's ``listening``
    line names its port."""

    def __init__(self, argv: list, spawn_timeout_s: float = 180.0):
        self.argv = list(argv)
        self.proc = subprocess.Popen(
            self.argv, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        # One reader thread owns stdout for the process's lifetime:
        # it resolves the 'listening' line AND keeps the pipe from
        # filling afterwards (a bare readline-with-timeout would race
        # Python's stream buffering).
        self._listening = threading.Event()
        self._addr: Optional[tuple] = None
        self._reader = threading.Thread(target=self._read_stdout,
                                        daemon=True)
        self._reader.start()
        if not self._listening.wait(spawn_timeout_s) \
                or self._addr is None:
            rc = self.proc.poll()
            self.proc.kill()
            raise FleetError(
                f"replica did not report a listening port within "
                f"{spawn_timeout_s}s (rc={rc}): "
                f"{' '.join(self.argv)}")
        self.host, self.port = self._addr

    def _read_stdout(self):
        try:
            for line in self.proc.stdout:
                if self._addr is None and "listening on " in line:
                    addr = line.rsplit("listening on ",
                                       1)[1].strip()
                    host, port = addr.rsplit(":", 1)
                    self._addr = (host, int(port))
                    self._listening.set()
        except (OSError, ValueError):  # pragma: no cover - teardown
            pass
        finally:
            # EOF before the listening line = the replica died at
            # spawn; release the constructor immediately.
            self._listening.set()

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        """Hard stop (SIGKILL) — the chaos harness's scripted death."""
        if self.alive():
            self.proc.kill()
        self.proc.wait(timeout=30.0)

    def stop(self, timeout_s: float = 10.0) -> None:
        """Reap after a graceful drain attempt: SIGTERM (the daemon's
        handler drains and exits 0), bounded wait, then SIGKILL."""
        if self.alive():
            self.proc.terminate()
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=30.0)


class InProcessReplica:
    """One in-process :class:`~.server.JoinService` behind a real TCP
    daemon — the fleet TEST backend: replicas over DISJOINT device
    subsets of the one CPU mesh, spawn/kill in milliseconds, no
    subprocess bootstrap. ``kill`` closes the listening socket
    (connection-refused to the router, exactly what a dead process
    looks like on the wire)."""

    def __init__(self, service):
        from distributed_join_tpu.service.server import start_daemon

        self.service = service
        self.server, self.port = start_daemon(service)
        self.host = "127.0.0.1"
        self._dead = False

    def alive(self) -> bool:
        return not self._dead

    def kill(self) -> None:
        self._dead = True
        self.server.shutdown()
        self.server.server_close()

    def stop(self, timeout_s: float = 10.0) -> None:  # noqa: ARG002
        if not self._dead:
            self.kill()


class AttachedReplica:
    """A replica endpoint ADOPTED from the router directory at
    standby takeover: the daemon process belongs to the dead router's
    spawn tree, so this incarnation has no process handle — liveness
    is judged on the wire (health probes + request strikes), and
    ``stop`` deliberately leaves the process running (a router
    takeover must not reap the serving fleet)."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = int(port)
        self._dead = False

    def alive(self) -> bool:
        return not self._dead

    def kill(self) -> None:
        # No process handle: mark it dead so the state machine treats
        # the slot as gone (the wire is already refusing).
        self._dead = True

    def stop(self, timeout_s: float = 10.0) -> None:  # noqa: ARG002
        pass


def in_process_fleet_factory(n_replicas: int, ranks_per_replica: int,
                             service_config=None,
                             comm_wrap: Optional[Callable] = None,
                             persist_dir: Optional[str] = None):
    """A replica factory over DISJOINT CPU-mesh device subsets:
    replica ``i`` serves devices ``[i*k, (i+1)*k)`` — the test-side
    realization of 'the failure domain is one replica'. ``comm_wrap``
    (index, generation, comm) -> comm lets tests arm one replica with
    a :class:`~..parallel.faults.FaultInjectingCommunicator`.

    ``persist_dir`` arms PER-SLOT persist subdirs (``r0/``, ``r1/``,
    ...): an AOT blob binds its device assignment, so two in-process
    replicas on DIFFERENT subsets of one runtime cannot share blobs —
    but a replacement respawned on the SAME subset restarts warm,
    which is what the in-process tests lock. Cross-replica sharing is
    real only across processes (each subprocess sees its own
    ``cpu:0..k-1``) and is locked by the subprocess smoke."""
    import jax

    from distributed_join_tpu.parallel.communicator import (
        TpuCommunicator,
    )
    from distributed_join_tpu.parallel.mesh import make_mesh
    from distributed_join_tpu.service.server import (
        JoinService,
        ServiceConfig,
    )

    devices = jax.devices()
    need = n_replicas * ranks_per_replica
    if need > len(devices):
        raise ValueError(
            f"{n_replicas} replicas x {ranks_per_replica} ranks needs "
            f"{need} devices, have {len(devices)}")

    def factory(index: int, generation: int) -> InProcessReplica:
        subset = devices[index * ranks_per_replica:
                         (index + 1) * ranks_per_replica]
        comm = TpuCommunicator(mesh=make_mesh(devices=subset))
        if comm_wrap is not None:
            comm = comm_wrap(index, generation, comm)
        cfg = service_config or ServiceConfig()
        if persist_dir is not None:
            cfg = dataclasses.replace(
                cfg, persist_dir=os.path.join(persist_dir,
                                              f"r{index}"))
        return InProcessReplica(JoinService(comm, cfg))

    return factory


def process_fleet_factory(config: FleetConfig,
                          platform: str = "cpu",
                          extra_args: Optional[list] = None,
                          replica_overrides: Optional[dict] = None):
    """The default replica factory: one ``tpu-join-service``
    subprocess per replica, sharing ``config.persist_dir``.

    ``replica_overrides`` maps a replica index to a dict applied ONLY
    on generation 0 (the chaos harness's scripted outage; every
    replacement respawns clean): ``"fault_plan"`` (a FaultPlan JSON
    record, forwarded as ``--fault-plan``), ``"extra_args"`` (extra
    argv tokens — e.g. the hang scenario's ``--guard-deadline-s``),
    and ``"persist": False`` (exclude the victim from the shared
    persist dir, so a corruption-armed replica must TRACE — a
    corrupted trace must never enter the fleet's distribution
    tier)."""

    def factory(index: int, generation: int) -> ProcessReplica:
        override = ((replica_overrides or {}).get(index) or {}
                    if generation == 0 else {})
        argv = [
            sys.executable, "-m",
            "distributed_join_tpu.service.server",
            "--host", "127.0.0.1", "--port", "0",
            "--platform", platform,
            "--n-ranks", str(config.replica_ranks),
        ]
        if config.persist_dir and override.get("persist", True):
            argv += ["--persist-dir", config.persist_dir]
        plan = override.get("fault_plan")
        if plan is not None:
            argv += ["--fault-plan", json.dumps(plan)]
        argv += list(extra_args or [])
        argv += list(override.get("extra_args") or [])
        return ProcessReplica(argv,
                              spawn_timeout_s=config.spawn_timeout_s)

    return factory


# -- the router --------------------------------------------------------


@dataclasses.dataclass
class _Replica:
    """Router-side replica bookkeeping (the state machine's subject).
    ``state``: starting -> healthy -> suspect -> drained (-> healthy
    again after replacement; ``failed`` when a respawn itself died)."""

    index: int
    backend: object
    generation: int = 0
    state: str = "healthy"
    strikes: int = 0
    inflight: int = 0
    last_stats: Optional[dict] = None
    drained_reason: Optional[str] = None
    drained_at: Optional[float] = None
    replaced_at: Optional[float] = None

    def addr(self):
        return self.backend.host, self.backend.port


class _AffinityStub:
    """The n_ranks/n_slices view :func:`workload_signature` needs —
    the router hashes signatures without a mesh or devices."""

    def __init__(self, n_ranks: int, n_slices: int = 1):
        self.n_ranks = n_ranks
        self.n_slices = n_slices


def affinity_key(req: dict, replica_ranks: int) -> str:
    """THE canonical routing digest of one wire request (module-level
    so harnesses can predict routing before a fleet exists). Join and
    explain specs hash through the workload-signature function the
    program cache and tuner key on (abstract tables from the spec's
    shapes — no data, no devices); table-management ops hash the
    table name so one handle's traffic co-locates; anything else
    hashes its canonical JSON."""
    op = req.get("op")
    table = req.get("table") or (
        req.get("name") if op in ("register", "append", "drop")
        else None)
    if table is not None:
        return hashlib.sha256(
            f"table:{table}".encode()).hexdigest()[:16]
    if op == "query":
        # Multi-operator plans route by the PLAN DIGEST — the same
        # key the replicas' program caches hold the compiled
        # whole-plan program under, so a repeated query lands warm
        # on the same replica (docs/QUERY.md).
        try:
            from distributed_join_tpu.planning.query import (
                tpch_query_plan,
            )

            digest = tpch_query_plan(
                str(req.get("query", "q3"))).digest()
            return hashlib.sha256(
                f"queryplan:{digest}".encode()).hexdigest()[:16]
        except Exception as exc:  # noqa: BLE001 - fall to JSON hash
            telemetry.event(
                "fleet_affinity_fallback", op=op,
                error=f"{type(exc).__name__}: {exc}")
    if op in ("join", "explain") and req.get("build_nrows"):
        try:
            from distributed_join_tpu.planning import abstract_tables
            from distributed_join_tpu.planning.tuner import (
                workload_signature,
            )

            build, probe = abstract_tables(
                int(req["build_nrows"]), int(req["probe_nrows"]))
            stub = _AffinityStub(replica_ranks)
            return workload_signature(
                stub, build, probe, with_metrics=False,
                **_join_opts_from_spec(req))
        except Exception as exc:  # noqa: BLE001 - fall to JSON hash
            # Loud: routing by the JSON fallback still works, but it
            # no longer matches the replicas' program-cache digests —
            # warm repeats would scatter. A silent fallback here made
            # that near-undiagnosable.
            telemetry.event(
                "fleet_affinity_fallback", op=op,
                error=f"{type(exc).__name__}: {exc}")
    basis = json.dumps(
        {k: repr(v) for k, v in req.items()
         if k not in ("request_id",)}, sort_keys=True)
    return hashlib.sha256(basis.encode()).hexdigest()[:16]


def affine_replica(req: dict, replica_ranks: int,
                   n_replicas: int) -> int:
    """The ring-walk START slot for ``req`` — the replica a healthy
    fleet serves it from (the chaos harness arms its victim here)."""
    key = affinity_key(req, replica_ranks)
    return int(key[:8], 16) % max(n_replicas, 1)


# Per-tenant admission state is bounded: unconfigured tenant names
# seen on the wire get counters up to this cap (configured tenants
# are never evicted — their quota buckets must not reset under churn).
MAX_TENANT_STATES = 64
# Retained warm join specs (newest = hottest) for the autoscaler's
# pre-warm rotation gate.
WARM_SPECS_MAX = 32
AUTOSCALE_EVENTS_MAX = 256
AUTOSCALE_SCHEMA_VERSION = 1


class _TenantState:
    """Router-side per-tenant admission state: a QPS token bucket,
    an inflight counter, and shed/served tallies. Mutated only under
    the router lock."""

    __slots__ = ("name", "quota", "priority", "tokens",
                 "last_refill_monotonic", "inflight", "served",
                 "quota_sheds", "priority_sheds")

    def __init__(self, name: str, quota: Optional[dict]):
        self.name = name
        self.quota = dict(quota) if quota else {}
        self.priority = int(self.quota.get("priority", 1) or 1)
        qps = self.quota.get("qps")
        burst = float(self.quota.get("burst_s", 1.0) or 1.0)
        # The bucket starts FULL (one burst window's worth, never
        # below one token) so a tenant's first requests are not shed
        # by an empty bucket it never filled.
        self.tokens = max(float(qps) * burst, 1.0) if qps else 0.0
        self.last_refill_monotonic = time.monotonic()
        self.inflight = 0
        self.served = 0
        self.quota_sheds = 0
        self.priority_sheds = 0

    def take_token(self, now: float) -> bool:
        """Refill-then-take; False = over the QPS quota. Only called
        when the quota configures ``qps``. Caller holds the lock."""
        qps = float(self.quota["qps"])
        cap = max(qps * float(self.quota.get("burst_s", 1.0) or 1.0),
                  1.0)
        # now is sampled before the router lock — on the admission
        # that CREATES this state it can precede the constructor's
        # refill stamp, and a negative delta must not drain the
        # fresh bucket below its first token.
        self.tokens = min(
            cap,
            self.tokens
            + max(now - self.last_refill_monotonic, 0.0) * qps)
        self.last_refill_monotonic = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class FleetRouter:
    """The thin line-JSON TCP router fronting N replicas. Owns the
    replica set (spawn, probe, drain, replace), the affinity routing,
    the bounded failover loop, admission/shedding (global AND
    per-tenant), the signature-level autoscaler, and the fleet-level
    observability surfaces."""

    def __init__(self, replica_factory: Callable,
                 config: Optional[FleetConfig] = None):
        self.config = config or FleetConfig()
        self.factory = replica_factory
        self._lock = threading.Lock()
        self.replicas: list = []
        self.live = tel_live.LiveMetrics()
        self.recorder = tel_live.FlightRecorder(
            self.config.flight_records)
        self.history = (tel_history.WorkloadHistory(os.path.join(
            self.config.history_dir, tel_history.HISTORY_FILENAME))
            if self.config.history_dir else None)
        self.failovers_total = 0
        self.shed_total = 0
        self.replaced_total = 0
        self.drains_total = 0
        self.rebuilds_total = 0
        self.takeovers_total = 0
        self.served = 0
        self.failed = 0
        self.rejected = 0
        # Multi-tenant admission (config.tenants): per-tenant token
        # buckets / inflight counters / shed tallies, created lazily
        # per observed tenant name (bounded; configured tenants are
        # never evicted).
        self._tenant_states: OrderedDict = OrderedDict()
        # Signature-level autoscaler (config.autoscale): decision
        # counters, the bounded event log behind the fleet_autoscale
        # artifact, and the retained hot join specs its pre-warm
        # rotation gate replays.
        self.autoscale_spawns_total = 0
        self.autoscale_drains_total = 0
        self._autoscale_events: list = []
        self._autoscaler: Optional[threading.Thread] = None
        self._warm_specs: OrderedDict = OrderedDict()
        # Replicated-state tier (table_replication > 1): the in-memory
        # table directory (name -> generation/key/holder set) the
        # durable router_directory.json mirrors; `role` is the HA
        # role ("single" outside RouterHA pairing).
        self.role = "single"
        self._tables: dict = {}
        self._lease: Optional[RouterLease] = None
        self._directory_fence = 0
        self._request_seq = 0
        self._id_stamp = os.urandom(3).hex()
        self._inflight_ids: set = set()
        self._stop = threading.Event()
        self._prober: Optional[threading.Thread] = None
        self._replace_threads: list = []
        # Set by the wire `shutdown` op; the serving loop (main) and
        # embedding harnesses watch it to tear the fleet down.
        self.shutdown_requested = threading.Event()

    @property
    def _coord_dir(self) -> Optional[str]:
        return self.config.coord_dir or self.config.persist_dir

    @property
    def _replicated(self) -> bool:
        return self.config.table_replication > 1

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        """Spawn the replica set and start the health prober."""
        for i in range(self.config.n_replicas):
            self.replicas.append(
                _Replica(index=i, backend=self.factory(i, 0)))
        telemetry.event("fleet_started",
                        replicas=len(self.replicas))
        self._prober = threading.Thread(target=self._probe_loop,
                                        daemon=True,
                                        name="fleet-prober")
        self._prober.start()
        if self.config.autoscale:
            self._autoscaler = threading.Thread(
                target=self._autoscale_loop, daemon=True,
                name="fleet-autoscaler")
            self._autoscaler.start()

    def stop(self, drain: bool = True) -> None:
        """Stop probing, settle any in-flight replacement, and reap
        every replica (graceful drain op first when ``drain``)."""
        self._stop.set()
        if self._prober is not None:
            self._prober.join(timeout=self.config.probe_interval_s
                              + self.config.probe_timeout_s + 5.0)
        if self._autoscaler is not None:
            # A tick mid-spawn holds the thread up to the spawn
            # timeout; its own _stop checks keep a late backend from
            # leaking past this reap loop.
            self._autoscaler.join(
                timeout=self.config.autoscale_interval_s + 5.0)
        # A _replace thread may be mid-spawn: join it (bounded) so
        # the freshly spawned backend lands in self.replicas and is
        # reaped below instead of leaking past shutdown.
        for t in self._replace_threads:
            t.join(timeout=self.config.spawn_timeout_s
                   + self.config.drain_settle_s + 10.0)
        for rep in self.replicas:
            if drain and rep.backend.alive():
                self._send_drain(rep)
            rep.backend.stop()
        if self.history is not None:
            self.history.close()
        if self.config.flight_recorder_path:
            self.dump_flight_recorder("fleet stopped")

    # -- affinity -----------------------------------------------------

    def affinity_key(self, req: dict) -> str:
        """The canonical routing digest of one wire request — see the
        module-level :func:`affinity_key`."""
        return affinity_key(req, self.config.replica_ranks)

    def _admittable(self, rep: _Replica,
                    inflight_bound: Optional[int] = None) -> bool:
        """Admission policy: state + inflight bound + the optional
        p95/QPS bounds read from the replica's probed LiveMetrics
        snapshot (stale by at most one probe interval — shedding is a
        pressure valve, not an exact gate). ``inflight_bound`` is a
        TIGHTER per-tenant priority cap (never looser than the fleet
        bound) — how low-priority tenants shed first under
        pressure."""
        if rep.state not in ("healthy", "suspect"):
            return False
        bound = self.config.max_inflight_per_replica
        if inflight_bound is not None:
            bound = min(bound, inflight_bound)
        if rep.inflight >= bound:
            return False
        st = rep.last_stats or {}
        if self.config.shed_p95_s is not None:
            p95 = (st.get("latency") or {}).get("p95_s")
            if p95 is not None and p95 > self.config.shed_p95_s:
                return False
        if self.config.shed_qps is not None:
            qps = st.get("qps_60s")
            if qps is not None and qps > self.config.shed_qps:
                return False
        return True

    # -- the health prober + state machine ----------------------------

    def _probe_loop(self):
        while not self._stop.wait(self.config.probe_interval_s):
            for rep in list(self.replicas):
                if self._stop.is_set():
                    return
                # drained = replacement in progress; failed = respawn
                # itself died and the fleet serves on with n-1 — the
                # prober leaves both alone (re-striking a failed slot
                # would churn doomed respawns forever).
                if rep.state in ("drained", "failed"):
                    continue
                self._probe_one(rep)

    def _probe_one(self, rep: _Replica):
        try:
            client = ServiceClient(
                *rep.addr(), timeout_s=self.config.probe_timeout_s)
            try:
                # Probes root their own (tiny) trace so a probe-
                # triggered drain is causally linkable to the probe.
                st = client.send(tracectx.attach(
                    {"op": "stats"}, tracectx.mint()))
            finally:
                client.close()
        except (OSError, ValueError) as exc:
            self._strike(rep, f"probe: {type(exc).__name__}: {exc}")
            return
        rep.last_stats = st
        if st.get("poisoned"):
            self._drain(rep, f"probe saw poisoned: {st['poisoned']}")
        elif st.get("draining"):
            # Someone (an operator, SIGTERM) is draining it out from
            # under the fleet: stop routing to it and replace.
            self._drain(rep, "probe saw draining")
        else:
            with self._lock:
                rep.strikes = 0
                if rep.state == "suspect":
                    rep.state = "healthy"
                    telemetry.event("fleet_replica_recovered",
                                    replica=rep.index)

    def _strike(self, rep: _Replica, reason: str):
        """One probe/request failure: healthy -> suspect; strikes
        beyond the bound (or a dead process) -> drained."""
        with self._lock:
            rep.strikes += 1
            strikes = rep.strikes
            if rep.state == "healthy":
                rep.state = "suspect"
                telemetry.event("fleet_replica_suspect",
                                replica=rep.index, reason=reason)
        if strikes >= self.config.suspect_strikes \
                or not rep.backend.alive():
            self._drain(rep, reason)

    def _drain(self, rep: _Replica, reason: str):
        """drained + replacement kick-off; idempotent per incident
        (a failed slot is terminal until an operator intervenes)."""
        with self._lock:
            if rep.state in ("drained", "failed"):
                return
            rep.state = "drained"
            rep.drained_reason = reason
            rep.drained_at = time.monotonic()
            self.drains_total += 1
        telemetry.event("fleet_replica_drained", replica=rep.index,
                        reason=reason)
        self.recorder.record(
            request_id=f"fleet-replica-{rep.index}",
            op="drain_replica", signature=None, outcome="drained",
            reason=reason,
            replica={"index": rep.index,
                     "generation": rep.generation})
        if self.config.respawn and not self._stop.is_set():
            t = threading.Thread(target=self._replace, args=(rep,),
                                 daemon=True,
                                 name=f"fleet-replace-{rep.index}")
            with self._lock:
                self._replace_threads = [x for x in
                                         self._replace_threads
                                         if x.is_alive()] + [t]
            t.start()

    def _send_drain(self, rep: _Replica):
        """Best-effort graceful drain wire op (in-flight completes or
        deadlines out replica-side before the reap)."""
        try:
            client = ServiceClient(
                *rep.addr(), timeout_s=self.config.drain_settle_s
                + self.config.probe_timeout_s)
            try:
                client.send({"op": "drain",
                             "reason": "fleet drain",
                             "settle_timeout_s":
                                 self.config.drain_settle_s})
            finally:
                client.close()
        except (OSError, ValueError):
            pass

    def _replace(self, rep: _Replica):
        """Reap the drained replica and respawn its slot (same index,
        same device subset, next generation) — warm via the shared
        persist dir. A replacement failure marks the slot ``failed``
        (the fleet serves on with n-1; the prober leaves it alone)."""
        if rep.backend.alive():
            self._send_drain(rep)
        try:
            rep.backend.stop()
        except Exception as exc:  # noqa: BLE001 - reap boundary
            telemetry.event("fleet_replica_reap_error",
                            replica=rep.index, error=str(exc))
        if self._stop.is_set():
            # Teardown in progress: the old backend is reaped, but a
            # fresh spawn would only leak past stop() — leave the
            # slot drained.
            return
        try:
            backend = self.factory(rep.index, rep.generation + 1)
        except Exception as exc:  # noqa: BLE001 - respawn boundary
            with self._lock:
                rep.state = "failed"
            telemetry.event("fleet_replica_respawn_failed",
                            replica=rep.index,
                            error=f"{type(exc).__name__}: {exc}")
            return
        with self._lock:
            rep.backend = backend
            rep.generation += 1
            rep.state = "healthy"
            rep.strikes = 0
            rep.inflight = 0
            rep.last_stats = None
            rep.replaced_at = time.monotonic()
            self.replaced_total += 1
        telemetry.event("fleet_replica_replaced", replica=rep.index,
                        generation=rep.generation)
        if self._replicated:
            try:
                self._rebuild_holder_tables(rep)
            except Exception as exc:  # noqa: BLE001 - rebuild boundary
                telemetry.event("fleet_rebuild_error",
                                replica=rep.index,
                                error=f"{type(exc).__name__}: {exc}")
            self._save_directory()

    # -- multi-tenant admission (config.tenants) ----------------------

    def _tenant_state_locked(self, name: str) -> _TenantState:
        """Lazily created per-tenant state (caller holds the lock).
        Bounded: when over the cap, evict one UNCONFIGURED tenant's
        counters — configured quota buckets never reset under name
        churn."""
        st = self._tenant_states.get(name)
        if st is None:
            st = _TenantState(name,
                              (self.config.tenants or {}).get(name))
            self._tenant_states[name] = st
            if len(self._tenant_states) > MAX_TENANT_STATES:
                cfg = self.config.tenants or {}
                for k in list(self._tenant_states):
                    if k not in cfg and k != name:
                        del self._tenant_states[k]
                        break
        return st

    def _tenant_admit(self, tenant: Optional[str],
                      op: str) -> Optional[_TenantState]:
        """Per-tenant admission (docs/FLEET.md "Multi-tenancy &
        autoscaling"): the inflight cap, the QPS token bucket, and
        the per-tenant p95 bound, each refusing with a
        QuotaExceededError NAMING the bound it enforces. Returns the
        tenant's state with its inflight slot RESERVED (the dispatch
        finally releases it); None when no tenant accounting applies
        (control-plane op, or an unstamped request with no quota
        configured for the default tenant)."""
        if op in ("ping", "stats", "metrics"):
            return None
        name = (tenant if tenant is not None
                else tel_history.DEFAULT_TENANT)
        if tenant is None \
                and name not in (self.config.tenants or {}):
            return None
        now = time.monotonic()
        with self._lock:
            st = self._tenant_state_locked(name)
            q = st.quota
            if q.get("max_inflight") is not None \
                    and st.inflight >= int(q["max_inflight"]):
                st.quota_sheds += 1
                raise QuotaExceededError(
                    f"tenant {name!r} over its inflight quota "
                    f"(max_inflight={int(q['max_inflight'])}, "
                    f"inflight={st.inflight}); shed — retry with "
                    "backoff")
            if q.get("qps") is not None \
                    and not st.take_token(now):
                st.quota_sheds += 1
                raise QuotaExceededError(
                    f"tenant {name!r} over its QPS quota "
                    f"(qps={float(q['qps'])}/s, "
                    f"burst_s={float(q.get('burst_s', 1.0) or 1.0)})"
                    "; shed — retry with backoff")
            st.inflight += 1
        bound = st.quota.get("shed_p95_s")
        if bound is not None:
            best = self._best_live_p95()
            if best is not None and best > float(bound):
                with self._lock:
                    st.inflight = max(st.inflight - 1, 0)
                    st.quota_sheds += 1
                raise QuotaExceededError(
                    f"tenant {name!r} shed on its p95 bound "
                    f"(shed_p95_s={float(bound)}, best live replica "
                    f"p95={best:.3f}s); retry with backoff")
        return st

    def _best_live_p95(self) -> Optional[float]:
        """The BEST probed p95 across live replicas — the latency the
        fleet could serve a request at right now. A tenant's
        ``shed_p95_s`` sheds only when even this exceeds its bound
        (one slow replica must not shed a tenant the others can
        serve in time)."""
        with self._lock:
            reps = list(self.replicas)
        vals = []
        for rep in reps:
            if rep.state not in ("healthy", "suspect"):
                continue
            p95 = ((rep.last_stats or {}).get("latency")
                   or {}).get("p95_s")
            if p95 is not None:
                vals.append(float(p95))
        return min(vals) if vals else None

    def _priority_fraction(
            self, tstate: Optional[_TenantState]) -> float:
        """A configured tenant's share of the per-replica inflight
        bound: its priority over the MAX configured priority.
        Unconfigured (and default) tenants keep the full bound — the
        exact pre-tenant admission behavior."""
        if tstate is None or not tstate.quota:
            return 1.0
        cfg = self.config.tenants or {}
        max_p = max([int((q or {}).get("priority", 1) or 1)
                     for q in cfg.values()] + [1])
        if max_p <= 0:
            return 1.0
        return min(max(tstate.priority / max_p, 0.0), 1.0)

    # -- signature-level autoscaler (config.autoscale) ----------------

    def _retain_warm_spec(self, key: str, req: dict) -> None:
        """Retain the wire spec of a served generic join keyed by its
        affinity signature (LRU, newest = hottest): the autoscaler's
        pre-warm gate replays the hottest one against a fresh replica
        before it enters rotation."""
        spec = {k: v for k, v in req.items()
                if k not in ("request_id", "tenant",
                             tracectx.TRACE_FIELD)}
        with self._lock:
            self._warm_specs.pop(key, None)
            self._warm_specs[key] = spec
            while len(self._warm_specs) > WARM_SPECS_MAX:
                self._warm_specs.popitem(last=False)

    def _autoscale_loop(self):
        over = 0
        idle_since = None
        while not self._stop.wait(self.config.autoscale_interval_s):
            try:
                over, idle_since = self._autoscale_tick(over,
                                                        idle_since)
            except Exception as exc:  # noqa: BLE001 - control loop
                telemetry.event(
                    "fleet_autoscale_error",
                    error=f"{type(exc).__name__}: {exc}")

    def _autoscale_tick(self, over, idle_since):
        """One control-loop decision over the probed per-replica
        LiveMetrics: sustained fleet QPS / worst-p95 over the up
        bounds spawns a replica; fleet QPS at/below the down bound
        with nothing in flight, sustained for ``autoscale_idle_s``,
        drains one scaled-up replica."""
        cfg = self.config
        with self._lock:
            live = [r for r in self.replicas
                    if r.state in ("healthy", "suspect")]
            n_live = len(live)
            any_inflight = any(r.inflight > 0 for r in live)
            total_qps = 0.0
            worst_p95 = None
            for r in live:
                st = r.last_stats or {}
                q = st.get("qps_60s")
                if q:
                    total_qps += float(q)
                p95 = (st.get("latency") or {}).get("p95_s")
                if p95 is not None and (worst_p95 is None
                                        or float(p95) > worst_p95):
                    worst_p95 = float(p95)
        hot = ((cfg.autoscale_up_qps is not None
                and total_qps > cfg.autoscale_up_qps)
               or (cfg.autoscale_up_p95_s is not None
                   and worst_p95 is not None
                   and worst_p95 > cfg.autoscale_up_p95_s))
        now = time.monotonic()
        if hot:
            over += 1
            idle_since = None
            if over >= max(cfg.autoscale_sustain, 1) \
                    and n_live < cfg.autoscale_max_replicas:
                self._autoscale_spawn(total_qps, worst_p95)
                over = 0
            return over, idle_since
        over = 0
        if total_qps <= cfg.autoscale_down_qps and not any_inflight:
            if idle_since is None:
                idle_since = now
            elif now - idle_since >= cfg.autoscale_idle_s:
                if self._autoscale_drain_one(total_qps):
                    idle_since = now
        else:
            idle_since = None
        return over, idle_since

    def _autoscale_spawn(self, qps, p95):
        """Spawn one scaled-up replica on a fresh index and PRE-WARM
        VERIFY it BEFORE it enters rotation: the hottest retained
        join spec replayed directly (it is not routable yet) must be
        SERVED, and is warm-verified when it cost zero new traces
        (the shared AOT persist dir did its job). A replica that
        cannot serve the probe never rotates in."""
        with self._lock:
            index = max((r.index for r in self.replicas),
                        default=-1) + 1
        reason = (f"sustained load (fleet qps_60s={qps:.2f}, "
                  f"worst p95={p95})")
        try:
            backend = self.factory(index, 0)
        except Exception as exc:  # noqa: BLE001 - spawn boundary
            self._autoscale_event(
                "spawn_failed", index,
                reason=f"{type(exc).__name__}: {exc}",
                qps=qps, p95_s=p95)
            return
        rep = _Replica(index=index, backend=backend)
        warm = self._prewarm(rep)
        if self._stop.is_set() or not warm.get("served"):
            try:
                backend.stop()
            except Exception:  # noqa: BLE001 - reap boundary
                pass
            if not self._stop.is_set():
                self._autoscale_event(
                    "spawn_failed", index,
                    reason="pre-warm probe failed: "
                           f"{warm.get('error')}",
                    qps=qps, p95_s=p95)
            return
        with self._lock:
            self.replicas.append(rep)
            self.autoscale_spawns_total += 1
        self._autoscale_event(
            "spawn", index, reason=reason, qps=qps, p95_s=p95,
            warm_verified=warm.get("verified"),
            new_traces=warm.get("new_traces"),
            signature=warm.get("signature"))

    def _prewarm(self, rep: _Replica) -> dict:
        """The rotation gate probe: replay the hottest retained join
        spec against the fresh replica; fall back to a ping when no
        spec has been retained yet (served, but not warm-verified)."""
        with self._lock:
            sig, spec = (next(reversed(self._warm_specs.items()))
                         if self._warm_specs else (None, None))
        probe = dict(spec) if spec else {"op": "ping"}
        probe["request_id"] = f"autoscale-warm-{rep.index}"
        try:
            client = ServiceClient(
                *rep.addr(), timeout_s=self.config.spawn_timeout_s)
            try:
                resp = client.send(tracectx.attach(
                    probe, tracectx.mint()))
            finally:
                client.close()
        except (OSError, ValueError) as exc:
            return {"served": False,
                    "error": f"{type(exc).__name__}: {exc}"}
        if not resp.get("ok"):
            return {"served": False,
                    "error": str(resp.get("message")
                                 or resp.get("error"))}
        new_traces = int(resp.get("new_traces") or 0)
        return {"served": True,
                "verified": spec is not None and new_traces == 0,
                "new_traces": (new_traces if spec is not None
                               else None),
                "signature": sig}

    def _autoscale_drain_one(self, qps) -> bool:
        """Scale down: drain the HIGHEST-INDEX idle scaled-up
        replica — never below the configured base ``n_replicas`` —
        and reap it with NO respawn (this drain is the point)."""
        with self._lock:
            live = [r for r in self.replicas
                    if r.state in ("healthy", "suspect")]
            candidates = [r for r in live
                          if r.index >= self.config.n_replicas
                          and r.inflight == 0]
            if len(live) <= self.config.n_replicas \
                    or not candidates:
                return False
            rep = max(candidates, key=lambda r: r.index)
            rep.state = "drained"
            rep.drained_reason = "autoscale: idle"
            rep.drained_at = time.monotonic()
            self.drains_total += 1
            self.autoscale_drains_total += 1
        self._send_drain(rep)
        try:
            rep.backend.stop()
        except Exception as exc:  # noqa: BLE001 - reap boundary
            telemetry.event("fleet_replica_reap_error",
                            replica=rep.index, error=str(exc))
        self._autoscale_event("drain", rep.index,
                              reason="idle past autoscale_idle_s",
                              qps=qps)
        return True

    def _autoscale_event(self, action, replica, *, reason,
                         qps=None, p95_s=None, warm_verified=None,
                         new_traces=None, signature=None):
        event = {"action": action, "replica": int(replica),
                 "reason": reason, "unix_s": time.time()}
        if qps is not None:
            event["qps_60s"] = round(float(qps), 4)
        if p95_s is not None:
            event["p95_s"] = round(float(p95_s), 6)
        if warm_verified is not None:
            event["warm_verified"] = bool(warm_verified)
        if new_traces is not None:
            event["new_traces"] = int(new_traces)
        if signature is not None:
            event["signature"] = signature
        with self._lock:
            self._autoscale_events.append(event)
            del self._autoscale_events[:-AUTOSCALE_EVENTS_MAX]
        telemetry.event("fleet_autoscale_" + action,
                        replica=int(replica), reason=reason)
        self.recorder.record(
            request_id=f"fleet-autoscale-{replica}",
            op="autoscale", signature=signature,
            outcome=action, reason=reason)

    def autoscale_record(self) -> dict:
        """The ``fleet_autoscale`` artifact (``analyze check``
        validates it): the autoscaler's decision log plus its
        counters."""
        with self._lock:
            return {
                "kind": "fleet_autoscale",
                "schema_version": AUTOSCALE_SCHEMA_VERSION,
                "enabled": bool(self.config.autoscale),
                "spawns_total": int(self.autoscale_spawns_total),
                "drains_total": int(self.autoscale_drains_total),
                "replicas": len(self.replicas),
                "events": [dict(e)
                           for e in self._autoscale_events],
            }

    # -- dispatch -----------------------------------------------------

    def _mint_request_id(self, request_id) -> str:
        with self._lock:
            self._request_seq += 1
            seq = self._request_seq
        if request_id:
            rid = str(request_id)
            if len(rid) > 64:
                # Cap length WITHOUT aliasing (the JoinService
                # scheme): two long ids sharing a 64-char prefix must
                # stay distinct — the duplicate-dispatch fence keys
                # on rid.
                rid = (rid[:48] + "-"
                       + hashlib.sha256(
                           rid.encode()).hexdigest()[:15])
            return rid
        return f"flt-{self._id_stamp}-{seq:06d}"

    def dispatch(self, req: dict) -> dict:
        """Route one wire request: affinity ring walk, admission,
        bounded failover, duplicate-id fencing, and the observability
        fan-out. Always returns a response dict (structured errors
        included) — the router never crashes a client."""
        from distributed_join_tpu.parallel.faults import (
            retry_with_backoff,
        )

        op = req.get("op", "?")
        # The optional wire tenant (default tenant = absent field —
        # every pre-tenant wire contract preserved byte-for-byte):
        # threaded like request_id through admission, history,
        # flight records, and Prometheus.
        tenant = req.get("tenant")
        if tenant is not None:
            tenant = str(tenant)
        rid = self._mint_request_id(req.get("request_id"))
        key = self.affinity_key(req)
        # The router is the trace ROOT when the client sent no
        # context; a client-minted context makes this dispatch a
        # child hop, so the whole fleet hop chain shares the
        # client's trace_id (docs/OBSERVABILITY.md "Distributed
        # tracing").
        ctx = tracectx.child_of_wire(req) or tracectx.mint()
        t0 = time.perf_counter()
        # The duplicate-dispatch fence: one id, one in-flight dispatch
        # at a time. A resend that arrives while the original is still
        # running PARKS until the original settles (the documented
        # reconnect-and-resend client pattern — its first answer was
        # lost with the torn connection, so the resend must be served,
        # idempotently, not refused), bounded by the request deadline.
        fence_deadline = time.monotonic() \
            + self.config.request_deadline_s
        while True:
            with self._lock:
                if rid not in self._inflight_ids:
                    self._inflight_ids.add(rid)
                    break
            if time.monotonic() >= fence_deadline:
                with self._lock:
                    self.rejected += 1
                self.live.record_request(op, "rejected",
                                         tenant=tenant)
                # Every refusal lands in the postmortem ring — this
                # is the one path that bypasses _observe's fan-out.
                self.recorder.record(
                    request_id=rid, op=op, signature=key,
                    outcome="rejected", reason="duplicate_fence",
                    trace=tracectx.stamp(ctx) or None)
                return {"ok": False, "error": "FleetError",
                        "message": f"request id {rid!r} still in "
                                   "flight past the request deadline "
                                   "(duplicate fenced)",
                        "request_id": rid,
                        tracectx.TRACE_FIELD: tracectx.to_wire(ctx)}
            time.sleep(0.05)
        state = {"attempts": 0, "failovers": 0, "replica": None,
                 "trace": ctx}
        outcome = "failed"
        resp = None
        tstate = None
        scope = telemetry.request_scope(None, trace=tracectx.stamp(ctx)
                                        or None)
        scope.__enter__()
        try:
            # Per-tenant admission FIRST: an over-quota tenant is
            # shed before it can touch a replica slot or the holder
            # fan-out — the quiet tenant's capacity is never burned
            # probing on the noisy tenant's behalf.
            tstate = self._tenant_admit(tenant, op)
            tenant_frac = self._priority_fraction(tstate)
            if self._replicated and op in ("register", "append",
                                           "drop"):
                # Replicated table ops never ride the single-replica
                # path: they fan out to the holder set (register
                # picks it, append/drop route BY it).
                resp = self._table_fanout(req, rid, key, state)
                outcome = "served" if resp.get("ok") else "failed"
                return resp
            allowed = None
            if op == "join" and req.get("table"):
                with self._lock:
                    entry = self._tables.get(str(req["table"]))
                if entry is not None:
                    # Probe-only joins route by HOLDER SET, fenced at
                    # the directory generation: a stale holder must
                    # refuse, not serve rows missing a delta. Only
                    # SERVING holders are routable — a slot mid-
                    # rebuild has no image yet (its replica would
                    # answer ResidentError), and a stale slot would
                    # only burn an attempt on a guaranteed fence
                    # refusal.
                    with self._lock:
                        allowed = {
                            idx for idx, h
                            in entry["holders"].items()
                            if h["state"] == "serving"}
                        states = {idx: h["state"] for idx, h
                                  in entry["holders"].items()}
                    if not allowed:
                        raise NoHolderError(
                            f"no serving holder for table "
                            f"{req['table']!r} (holder states "
                            f"{states}); rebuilds in flight heal "
                            "this — retry with backoff")
                    req = {**req,
                           "min_generation": entry["generation"]}
            resp = self._dispatch_attempts(
                req, rid, key, state, retry_with_backoff,
                allowed=allowed, tenant=tenant,
                tenant_frac=tenant_frac)
            outcome = "served" if resp.get("ok") else "failed"
            if outcome == "served" and op == "join" \
                    and not req.get("table"):
                self._retain_warm_spec(key, req)
            return resp
        except AdmissionError as exc:
            outcome = "rejected"
            with self._lock:
                self.shed_total += 1
                self.rejected += 1
            resp = {"ok": False, "error": "AdmissionError",
                    "message": str(exc), "shed": True,
                    "request_id": rid,
                    "fleet": {"attempts": state["attempts"]}}
            return resp
        except QuotaExceededError as exc:
            outcome = "rejected"
            with self._lock:
                self.shed_total += 1
                self.rejected += 1
            resp = {"ok": False, "error": "QuotaExceededError",
                    "message": str(exc), "shed": True,
                    "tenant": tenant, "request_id": rid,
                    "fleet": {"attempts": state["attempts"]}}
            return resp
        except ShedError as exc:
            outcome = "rejected"
            with self._lock:
                self.shed_total += 1
                self.rejected += 1
                if tstate is not None:
                    tstate.priority_sheds += 1
            resp = {"ok": False, "error": "ShedError",
                    "message": str(exc), "shed": True,
                    "tenant": tenant, "request_id": rid,
                    "fleet": {"attempts": state["attempts"]}}
            return resp
        except NoHolderError as exc:
            resp = {"ok": False, "error": "NoHolderError",
                    "message": str(exc), "request_id": rid,
                    "table": req.get("table") or req.get("name"),
                    "fleet": {"attempts": state["attempts"],
                              "failovers": state["failovers"]}}
            return resp
        except FleetError as exc:
            resp = {"ok": False, "error": "FleetError",
                    "message": str(exc), "request_id": rid,
                    "fleet": {"attempts": state["attempts"],
                              "failovers": state["failovers"]}}
            return resp
        finally:
            with self._lock:
                self._inflight_ids.discard(rid)
                if tstate is not None:
                    tstate.inflight = max(tstate.inflight - 1, 0)
                    if outcome == "served":
                        tstate.served += 1
            self._observe(rid, op, key, outcome, state,
                          time.perf_counter() - t0, resp,
                          tenant=tenant)
            scope.__exit__(None, None, None)
            if isinstance(resp, dict):
                # Echo the router's span on the wire so the client
                # can parent its own follow-up spans on this hop.
                resp.setdefault(tracectx.TRACE_FIELD,
                                tracectx.to_wire(ctx))

    def _dispatch_attempts(self, req, rid, key, state,
                           retry_with_backoff, allowed=None,
                           tenant=None, tenant_frac=1.0):
        deadline = time.monotonic() + self.config.request_deadline_s
        # index -> generation at HARD-failure time (dead connection,
        # hang, poison): a later attempt may return to the slot only
        # once it has been REPLACED. Transient busy/draining refusals
        # go in `soft_failed` instead — skipped on the first ring
        # pass but re-eligible on the fallback pass (the replica-side
        # pending bound drains between backoffs; fencing it on
        # generation would starve a small fleet into shedding).
        last_failed: dict = {}
        soft_failed: set = set()

        def attempt_once():
            state["attempts"] += 1
            # Priority-weighted headroom: a low-priority tenant sees
            # only its priority's share of the per-replica inflight
            # bound (recomputed per attempt — the bound is a live
            # knob). Full-priority and unconfigured tenants keep the
            # exact pre-tenant bound.
            bound = None
            if tenant_frac < 1.0:
                bound = max(
                    1, int(self.config.max_inflight_per_replica
                           * tenant_frac))
                if bound >= self.config.max_inflight_per_replica:
                    bound = None
            rep = self._pick(key, last_failed, soft_failed,
                             allowed=allowed, inflight_bound=bound)
            if rep is None:
                if allowed is not None:
                    with self._lock:
                        live = [r for r in self.replicas
                                if r.index in allowed
                                and r.state in ("healthy",
                                                "suspect")]
                    if not live:
                        raise NoHolderError(
                            f"no live holder for table "
                            f"{req.get('table')!r} (holder set "
                            f"{sorted(allowed)} all dead/drained); "
                            "refusing rather than misrouting to a "
                            "replica without the image")
                if bound is not None and self._pick(
                        key, last_failed, soft_failed,
                        allowed=allowed, reserve=False) is not None:
                    # A replica WOULD admit at the full fleet bound:
                    # this is a priority shed, not fleet-wide
                    # overload — the low-priority tenant yields its
                    # headroom first, named as such.
                    raise ShedError(
                        f"tenant {tenant!r} shed under fleet "
                        f"pressure: priority weight "
                        f"{tenant_frac:.2f} caps its per-replica "
                        f"inflight at {bound} (fleet bound "
                        f"{self.config.max_inflight_per_replica}); "
                        "higher-priority tenants keep the remaining "
                        "headroom")
                raise AdmissionError(
                    "fleet admission: no admittable replica "
                    f"(inflight bound "
                    f"{self.config.max_inflight_per_replica}"
                    " or p95/QPS shed policy); retry with backoff")
            state["replica"] = rep
            # Every attempt — including the ones that fail and fail
            # over — is its own child span of the dispatch, carried
            # on the wire to the replica, so one trace shows the
            # whole causal chain victim-attempt included.
            attempt_ctx = tracectx.child(state.get("trace"))
            telemetry.event(
                "fleet_attempt", request_id=rid,
                op=req.get("op"), replica=rep.index,
                attempt=state["attempts"],
                **tracectx.stamp(attempt_ctx))
            gen0 = rep.generation
            try:
                remaining = max(deadline - time.monotonic(), 0.1)
                client = ServiceClient(*rep.addr(),
                                       timeout_s=remaining)
                try:
                    resp = client.send(tracectx.attach(
                        {**req, "request_id": rid}, attempt_ctx))
                finally:
                    # Superseded attempts are abandoned with their
                    # connection — a late answer is never read.
                    client.close()
            except (OSError, ValueError) as exc:
                self._strike(
                    rep, f"request {rid}: "
                         f"{type(exc).__name__}: {exc}")
                last_failed[rep.index] = gen0
                self._record_attempt_failed(
                    rid, req, key, rep, gen0, state["attempts"],
                    attempt_ctx,
                    f"{type(exc).__name__}: {exc}")
                raise _AttemptFailed(
                    f"replica {rep.index} connection failed: "
                    f"{type(exc).__name__}: {exc}") from exc
            finally:
                with self._lock:
                    if rep.generation == gen0:
                        rep.inflight = max(rep.inflight - 1, 0)
            fault = self._replica_fault(resp)
            if fault is None and allowed is not None \
                    and not resp.get("ok") \
                    and resp.get("error") == "ResidentError":
                # Holder-routed probe-only join answered "no resident
                # table": the directory says this slot holds the
                # image, the replica says it does not (a replacement
                # whose rebuild has not started yet, or an image lost
                # with a prior incarnation). That inconsistency is
                # the FLEET's, not the client's answer — park the
                # slot stale (the rebuild's completion overwrites
                # this) and fail over to another holder.
                fault = "stale"
            if fault is not None:
                if fault in ("hang", "poisoned"):
                    self._drain(rep, f"request {rid}: {fault}")
                    last_failed[rep.index] = gen0
                elif fault == "stale":
                    # Generation fence fired: this holder missed an
                    # append. Hard-exclude the incarnation (it can
                    # never catch up short of a rebuild) and record
                    # the staleness in the table directory — the
                    # retry lands on an up-to-date holder.
                    last_failed[rep.index] = gen0
                    self._mark_holder_stale(req.get("table"),
                                            rep.index)
                else:
                    # busy/draining: transient — steer the next
                    # attempt elsewhere, but stay re-eligible on the
                    # fallback pass.
                    soft_failed.add(rep.index)
                self._record_attempt_failed(
                    rid, req, key, rep, gen0, state["attempts"],
                    attempt_ctx,
                    f"{fault}: {resp.get('message') or resp.get('error')}")
                raise _AttemptFailed(
                    f"replica {rep.index} {fault}: "
                    f"{resp.get('message') or resp.get('error')}")
            return self._augment(resp, rep, state)

        try:
            resp, attempts = retry_with_backoff(
                attempt_once,
                max_attempts=self.config.retry_budget + 1,
                backoff_s=self.config.retry_backoff_s,
                deadline_s=self.config.request_deadline_s,
                retry_on=(_AttemptFailed,),
            )
            state["failovers"] = len(attempts) - 1
            with self._lock:
                self.failovers_total += state["failovers"]
            return resp
        except _AttemptFailed as exc:
            trail = getattr(exc, "_retry_attempts", [])
            state["failovers"] = max(len(trail) - 1, 0)
            with self._lock:
                self.failovers_total += state["failovers"]
            raise FleetError(
                f"request {rid} failed after {len(trail)} attempt(s) "
                f"(retry_budget={self.config.retry_budget}): "
                f"{exc}") from exc

    def _pick(self, key: str, exclude: dict,
              soft: Optional[set] = None,
              allowed: Optional[set] = None,
              inflight_bound: Optional[int] = None,
              reserve: bool = True) -> Optional[_Replica]:
        """Pick AND reserve (inflight slot taken under the one lock,
        so two concurrent dispatches can never both pass the
        admission bound). The caller releases the slot in its
        dispatch finally. ``exclude`` maps a HARD-failed replica's
        index to its generation AT failure: that slot becomes
        eligible again only once REPLACED (generation moved) — never
        handed the same request back while still the known-bad
        incarnation. ``soft`` holds transiently-refusing (busy/
        draining) indices: preferred-against on the first pass,
        re-eligible on the fallback pass. ``allowed`` (replicated
        resident traffic) restricts the walk to the table's holder
        set — a non-holder never sees the request.
        ``inflight_bound`` tightens the per-replica inflight cap for
        low-priority tenants; ``reserve=False`` is a dry-run probe
        (no slot taken) used to tell a priority shed apart from a
        fleet-wide one."""
        with self._lock:
            n = len(self.replicas)
            if not n:
                return None
            start = int(key[:8], 16) % n
            order = [self.replicas[(start + k) % n]
                     for k in range(n)]
            for second_pass in (False, True):
                for rep in order:
                    if allowed is not None \
                            and rep.index not in allowed:
                        continue
                    if rep.index in exclude:
                        if not second_pass:
                            continue
                        if rep.generation <= exclude[rep.index]:
                            continue
                    if not second_pass and soft \
                            and rep.index in soft:
                        continue
                    if self._admittable(rep, inflight_bound):
                        if reserve:
                            rep.inflight += 1
                        return rep
        return None

    @staticmethod
    def _replica_fault(resp: dict) -> Optional[str]:
        """Classify an error response: replica-fatal (failover-able)
        vs a client error passed through untouched. HangError and
        poisoned refusals mean the REPLICA is gone for serving
        purposes; draining/pending refusals mean try a sibling; any
        other error (ValueError, IntegrityError, ...) is the CLIENT's
        answer — a refusal the fleet must not mask."""
        if resp.get("ok"):
            return None
        err = resp.get("error")
        msg = str(resp.get("message", ""))
        if err == "HangError":
            return "hang"
        if err == "StaleGenerationError":
            # The holder-side generation fence: failover-able (an
            # up-to-date holder can serve), never the client's
            # answer.
            return "stale"
        if err in ("AdmissionError", "DrainingError"):
            if "poisoned" in msg:
                return "poisoned"
            if "draining" in msg or err == "DrainingError":
                return "draining"
            return "busy"
        return None

    def _augment(self, resp: dict, rep: _Replica, state) -> dict:
        resp = dict(resp)
        resp["fleet"] = {
            "replica": rep.index,
            "generation": rep.generation,
            "attempts": state["attempts"],
            "failovers": state["attempts"] - 1,
        }
        return resp

    def _record_attempt_failed(self, rid, req, key, rep, gen0,
                               attempt, attempt_ctx, error) -> None:
        """A failed dispatch attempt lands in the flight ring WITH
        its replica/trace pair — before this, only the final attempt
        was visible postmortem, so a failover's victim hop could not
        be tied to the retry that served. Never fails the dispatch."""
        try:
            telemetry.event(
                "fleet_attempt_failed", request_id=rid,
                op=req.get("op"), replica=rep.index,
                attempt=attempt, error=error,
                **tracectx.stamp(attempt_ctx))
            self.recorder.record(
                request_id=rid, op=req.get("op", "?"),
                signature=key, outcome="attempt_failed",
                attempt=attempt, error=error,
                replica={"index": rep.index, "generation": gen0},
                trace=tracectx.stamp(attempt_ctx) or None)
        except Exception as exc:  # noqa: BLE001 - bookkeeping boundary
            telemetry.event("fleet_observability_error",
                            request_id=rid,
                            error=f"{type(exc).__name__}: {exc}")

    def _observe(self, rid, op, key, outcome, state, elapsed_s,
                 resp, tenant=None):
        """Fleet-side accounting fan-out (live metrics, flight ring,
        history line stamped with the serving replica and, when the
        wire named one, the tenant). Never fails a request."""
        try:
            rep = state.get("replica")
            stamp = ({"index": rep.index,
                      "generation": rep.generation,
                      "port": rep.backend.port}
                     if rep is not None else None)
            resident = self._resident_stamp(resp)
            trace = tracectx.stamp(state.get("trace")) or None
            with self._lock:
                if outcome == "served":
                    self.served += 1
                elif outcome == "failed":
                    self.failed += 1
            # The router's own dispatch span — the hop the fleet
            # timeline hangs admission/route/failover walls on.
            telemetry.span_complete(
                "fleet_dispatch", time.perf_counter() - elapsed_s,
                elapsed_s, request_id=rid, op=op, outcome=outcome,
                attempts=state.get("attempts", 0),
                failovers=state.get("failovers", 0),
                replica=(state["replica"].index
                         if state.get("replica") is not None
                         else None),
                **(trace or {}))
            tstamp = ({"tenant": tenant} if tenant is not None
                      else {})
            self.live.record_request(
                op, outcome,
                latency_s=elapsed_s if outcome == "served" else None,
                signature=key,
                new_traces=int((resp or {}).get("new_traces") or 0),
                tenant=tenant,
                shed=bool((resp or {}).get("shed")))
            self.recorder.record(
                request_id=rid, op=op, signature=key,
                outcome=outcome, elapsed_s=round(elapsed_s, 6),
                matches=(resp or {}).get("matches"),
                new_traces=(resp or {}).get("new_traces"),
                failovers=state.get("failovers", 0),
                replica=stamp,
                resident=resident,
                trace=trace,
                error=(None if (resp or {}).get("ok")
                       else (resp or {}).get("message")),
                **tstamp)
            if self.history is not None and op not in ("ping",
                                                       "stats",
                                                       "metrics"):
                self.history.append(tel_history.request_entry(
                    request_id=rid, op=op, signature=key,
                    outcome=outcome, wall_s=elapsed_s,
                    new_traces=int((resp or {}).get("new_traces")
                                   or 0),
                    matches=(resp or {}).get("matches"),
                    error=(None if (resp or {}).get("ok")
                           else str((resp or {}).get("message"))),
                    resident=resident,
                    replica=stamp, trace=trace,
                    tenant=tenant))
        except Exception as exc:  # noqa: BLE001 - bookkeeping boundary
            telemetry.event("fleet_observability_error",
                            request_id=rid,
                            error=f"{type(exc).__name__}: {exc}")

    def _resident_stamp(self, resp) -> Optional[dict]:
        """The holder/generation stamp for history + flight records:
        the replica's own resident stamp when present, else the table
        info of a fan-out response; holder set attached from the
        directory so `analyze history` can attribute a latency step
        to a rebuild."""
        r = (resp or {}).get("resident")
        fl = (resp or {}).get("fleet") or {}
        stamp = None
        if isinstance(r, dict) and r.get("table") is not None \
                and r.get("generation") is not None:
            stamp = dict(r)
        elif fl.get("table") is not None \
                and fl.get("table_generation") is not None:
            stamp = {"table": fl["table"],
                     "generation": fl["table_generation"]}
        if stamp is not None:
            with self._lock:
                entry = self._tables.get(stamp["table"])
                if entry is not None:
                    stamp["holders"] = sorted(entry["holders"])
        return stamp

    # -- replicated resident state (table_replication > 1) ------------

    def _holder_slots(self, key: str) -> list:
        """Ring order for a table key — registration picks the first
        K LIVE slots from here, so the primary holder is exactly the
        slot probe-only joins ring-start on."""
        n = len(self.replicas)
        start = int(key[:8], 16) % max(n, 1)
        return [(start + k) % n for k in range(n)]

    def _send_table_op(self, rep: _Replica, req: dict,
                       rid: str,
                       trace: Optional[dict] = None
                       ) -> Optional[dict]:
        """One table-op leg of a fan-out: direct wire send to one
        holder. ``None`` = connection-dead (struck, failover-able);
        a dict is the holder's answer, structured refusals
        included. ``trace`` (a per-leg child context) rides the
        wire so the holder's spans join the fan-out's trace."""
        with self._lock:
            rep.inflight += 1
        gen0 = rep.generation
        try:
            client = ServiceClient(
                *rep.addr(),
                timeout_s=self.config.request_deadline_s)
            try:
                return client.send(tracectx.attach(
                    {**req, "request_id": rid}, trace))
            finally:
                client.close()
        except (OSError, ValueError) as exc:
            self._strike(rep, f"table op {req.get('op')} {rid}: "
                              f"{type(exc).__name__}: {exc}")
            return None
        finally:
            with self._lock:
                if rep.generation == gen0:
                    rep.inflight = max(rep.inflight - 1, 0)

    def _table_fanout(self, req: dict, rid: str, key: str,
                      state: dict) -> dict:
        op = req["op"]
        name = str(req["name"])
        if op == "register":
            return self._register_fanout(req, rid, name, key, state)
        if op == "append":
            return self._append_fanout(req, rid, name, key, state)
        return self._drop_fanout(req, rid, name, key, state)

    def _register_fanout(self, req, rid, name, key, state) -> dict:
        """Register on the first K live ring slots; write the durable
        manifest; record the holder set in the directory. A
        structured refusal from any holder (duplicate name, schema)
        aborts the fan-out, rolls back the holders already
        registered, and passes the refusal through — registration is
        all-or-nothing."""
        want = min(self.config.table_replication,
                   len(self.replicas))
        results: list = []
        for idx in self._holder_slots(key):
            if len(results) >= want:
                break
            rep = self.replicas[idx]
            with self._lock:
                if rep.state not in ("healthy", "suspect"):
                    continue
            state["attempts"] += 1
            state["replica"] = rep
            leg = tracectx.child(state.get("trace"))
            telemetry.event("fleet_fanout_leg", op="register",
                            table=name, replica=rep.index,
                            request_id=rid, **tracectx.stamp(leg))
            resp = self._send_table_op(rep, req, rid, trace=leg)
            if resp is None:
                continue
            if not resp.get("ok"):
                for prep, _ in results:
                    self._send_table_op(
                        prep, {"op": "drop", "name": name},
                        f"{rid}-rollback",
                        trace=tracectx.child(state.get("trace")))
                return {**resp, "request_id": rid}
            results.append((rep, resp))
        if not results:
            raise NoHolderError(
                f"register {name!r}: no live replica accepted the "
                f"registration (wanted {want} holder(s) of "
                f"{len(self.replicas)} slots)")
        holders = {rep.index: {"state": "serving",
                               "generation":
                                   int(r.get("generation", 1))}
                   for rep, r in results}
        gen = max(h["generation"] for h in holders.values())
        primary = results[0][1]
        with self._lock:
            self._tables[name] = {
                "generation": gen,
                "key": primary.get("key", "key"),
                "holders": holders,
            }
        self._write_manifest_register(name, req, primary)
        self._save_directory()
        telemetry.event("fleet_table_registered", table=name,
                        holders=sorted(holders), generation=gen)
        resp = dict(primary)
        resp["fleet"] = {
            "table": name,
            "holders": sorted(holders),
            "table_generation": gen,
            "attempts": state["attempts"],
            "failovers": 0,
        }
        return resp

    def _append_fanout(self, req, rid, name, key, state) -> dict:
        """Apply one delta to EVERY holder. A holder the delta does
        not reach (dead, injected fault, mid-rebuild) is fenced
        STALE — it can never catch up by later appends, so it stops
        serving probe-only work until a rebuild replays the manifest.
        The delta also lands in the manifest, so rebuilds and
        late-joining holders replay it."""
        with self._lock:
            entry = self._tables.get(name)
        if entry is None:
            raise NoHolderError(
                f"append to {name!r}: table is not in the fleet "
                "directory (never registered through this router, "
                "or already dropped)")
        outcomes: dict = {}
        for idx in sorted(entry["holders"]):
            rep = self.replicas[idx]
            hstate = entry["holders"][idx]
            with self._lock:
                live = rep.state in ("healthy", "suspect")
            if not live or hstate["state"] == "rebuilding":
                # Unreachable or mid-rebuild: the manifest carries
                # the delta to it (rebuild replays; a dead slot's
                # replacement rebuilds on arrival).
                continue
            state["attempts"] += 1
            state["replica"] = rep
            leg = tracectx.child(state.get("trace"))
            telemetry.event("fleet_fanout_leg", op="append",
                            table=name, replica=rep.index,
                            request_id=rid, **tracectx.stamp(leg))
            outcomes[idx] = self._send_table_op(rep, req, rid,
                                                trace=leg)
        ok_items = {i: r for i, r in outcomes.items()
                    if r is not None and r.get("ok")}
        if not ok_items:
            refusals = [r for r in outcomes.values()
                        if r is not None]
            if refusals:
                # Deterministic client refusal (schema mismatch,
                # unknown table): every holder answered the same —
                # pass it through, fence nothing.
                return {**refusals[0], "request_id": rid}
            raise NoHolderError(
                f"append to {name!r}: no live holder reachable "
                f"(holder set {sorted(entry['holders'])})")
        gen = max(int(r.get("generation", 0))
                  for r in ok_items.values())
        for idx, hstate in entry["holders"].items():
            if idx in ok_items:
                hstate["state"] = "serving"
                hstate["generation"] = \
                    int(ok_items[idx]["generation"])
            elif hstate["state"] not in ("rebuilding", "stale"):
                hstate["state"] = "stale"
                telemetry.event("fleet_holder_stale", table=name,
                                replica=idx,
                                holder_generation=
                                hstate["generation"],
                                required_generation=gen)
                self.recorder.record(
                    request_id=rid, op="append", signature=key,
                    outcome="holder_stale",
                    replica={"index": idx,
                             "generation":
                                 self.replicas[idx].generation},
                    resident={"table": name,
                              "generation": hstate["generation"]})
        entry["generation"] = gen
        self._append_manifest_delta(name, req, gen)
        self._save_directory()
        if len(ok_items) < len(entry["holders"]):
            telemetry.event("fleet_append_partial", table=name,
                            applied=sorted(ok_items),
                            holders=sorted(entry["holders"]),
                            generation=gen)
        primary = ok_items[min(ok_items)]
        resp = dict(primary)
        resp["fleet"] = {
            "table": name,
            "holders": sorted(entry["holders"]),
            "applied": sorted(ok_items),
            "table_generation": gen,
            "attempts": state["attempts"],
            "failovers": 0,
        }
        return resp

    def _drop_fanout(self, req, rid, name, key, state) -> dict:
        with self._lock:
            entry = self._tables.pop(name, None)
        if entry is None:
            raise NoHolderError(
                f"drop {name!r}: table is not in the fleet "
                "directory")
        dropped = []
        for idx in sorted(entry["holders"]):
            rep = self.replicas[idx]
            with self._lock:
                live = rep.state in ("healthy", "suspect")
            if not live:
                continue
            state["attempts"] += 1
            state["replica"] = rep
            leg = tracectx.child(state.get("trace"))
            telemetry.event("fleet_fanout_leg", op="drop",
                            table=name, replica=rep.index,
                            request_id=rid, **tracectx.stamp(leg))
            resp = self._send_table_op(rep, req, rid, trace=leg)
            if resp is not None and resp.get("ok"):
                dropped.append(idx)
        self._drop_manifest(name)
        self._save_directory()
        telemetry.event("fleet_table_dropped", table=name,
                        holders=sorted(entry["holders"]),
                        reached=dropped)
        # Idempotent by intent: the directory entry and manifest are
        # gone even if a dead holder could not be reached — its
        # replacement rebuilds from the manifest set, which no
        # longer includes this table.
        return {"ok": True, "op": "drop", "table": name,
                "dropped": True, "request_id": rid,
                "fleet": {"table": name,
                          "holders": sorted(entry["holders"]),
                          "applied": dropped,
                          "attempts": state["attempts"],
                          "failovers": 0}}

    def _mark_holder_stale(self, table, index: int) -> None:
        if table is None:
            return
        with self._lock:
            entry = self._tables.get(str(table))
            if entry is None:
                return
            hstate = entry["holders"].get(index)
            if hstate is None or hstate["state"] == "stale":
                return
            hstate["state"] = "stale"
        telemetry.event("fleet_holder_stale", table=str(table),
                        replica=index,
                        holder_generation=hstate["generation"],
                        required_generation=entry["generation"])
        self._save_directory()

    def _rebuild_holder_tables(self, rep: _Replica) -> None:
        """Replacement arrived on a holder slot: replay every table
        the slot holds from its durable manifest (rebuilding ->
        serving lifecycle). Warm probe-only programs reload from the
        AOT persist dir, so the rebuilt image serves repeat
        signatures with zero new traces."""
        with self._lock:
            todo = [name for name, e in self._tables.items()
                    if rep.index in e["holders"]]
        for name in todo:
            self._rebuild_one(rep, name)

    def _rebuild_one(self, rep: _Replica, name: str) -> None:
        with self._lock:
            entry = self._tables.get(name)
            holder = (entry or {}).get("holders",
                                       {}).get(rep.index)
            if holder is None:
                return
            holder["state"] = "rebuilding"
        self._save_directory()
        # Rebuilds have no client: the router roots a fresh trace so
        # the manifest replay's spans (router legs + holder-side
        # register/append spans) assemble into one causal chain in
        # the fleet timeline.
        ctx = tracectx.mint()
        telemetry.event("fleet_holder_rebuilding", table=name,
                        replica=rep.index,
                        generation_target=entry["generation"],
                        **tracectx.stamp(ctx))
        manifest = (load_table_manifest(self._coord_dir, name)
                    if self._coord_dir else None)
        if manifest is None:
            with self._lock:
                holder["state"] = "stale"
            telemetry.event("fleet_rebuild_no_manifest",
                            table=name, replica=rep.index)
            self._save_directory()
            return
        t0 = time.perf_counter()
        # Deltas replay with maintain=True: the LSM merge runs INSIDE
        # the rebuild, so the rebuilt image is the same merged shape
        # the surviving holders serve (merge programs are trace-only —
        # no AOT blob — and a merge deferred to the first probe-only
        # join would cost that join its zero-trace warm gate).
        ops = ([dict(manifest["register"], replace=True)]
               + [dict(d, maintain=True)
                  for d in manifest.get("deltas", [])])
        gen = 0
        step = 0
        for _catchup_round in range(3):
            for op_req in ops:
                rid = (f"rebuild-{_table_slug(name)}-r{rep.index}"
                       f"g{rep.generation}-{step}")
                step += 1
                resp = self._send_table_op(
                    rep, op_req, rid, trace=tracectx.child(ctx))
                if resp is None or not resp.get("ok"):
                    with self._lock:
                        holder["state"] = "stale"
                    telemetry.event(
                        "fleet_rebuild_failed", table=name,
                        replica=rep.index, step=step - 1,
                        error=(resp or {}).get("message")
                        or (resp or {}).get("error")
                        or "connection failed")
                    self._save_directory()
                    return
                gen = int(resp.get("generation", 0))
            with self._lock:
                target = entry["generation"]
            if gen >= target:
                break
            # An append fanned out WHILE we replayed: it skipped this
            # rebuilding slot (the fan-out never waits on a rebuild)
            # but landed in the manifest. Reload and replay the tail
            # instead of parking the fresh image stale — stale here
            # would silently degrade the table to K-1 durability for
            # the rest of this incarnation. deltas[k] produces
            # generation k+2 (register is 1), so after reaching
            # ``gen`` the unapplied tail starts at deltas[gen-1].
            manifest = (load_table_manifest(self._coord_dir, name)
                        if self._coord_dir else None)
            tail = (manifest or {}).get("deltas",
                                        [])[max(gen - 1, 0):]
            if not tail:
                break
            ops = [dict(d, maintain=True) for d in tail]
        elapsed = time.perf_counter() - t0
        with self._lock:
            holder["generation"] = gen
            if gen >= entry["generation"]:
                holder["state"] = "serving"
            else:
                # The manifest was behind the directory (a lost
                # write): refuse to serve a silently-short image.
                holder["state"] = "stale"
            self.rebuilds_total += 1
        telemetry.event("fleet_holder_rebuilt", table=name,
                        replica=rep.index, generation=gen,
                        state=holder["state"],
                        elapsed_s=round(elapsed, 3),
                        **tracectx.stamp(ctx))
        self.recorder.record(
            request_id=f"rebuild-{_table_slug(name)}-r{rep.index}",
            trace=tracectx.stamp(ctx) or None,
            op="rebuild",
            signature=self.affinity_key({"op": "register",
                                         "name": name}),
            outcome="rebuilt", elapsed_s=round(elapsed, 6),
            resident={"table": name, "generation": gen,
                      "holders": sorted(entry["holders"])},
            replica={"index": rep.index,
                     "generation": rep.generation})
        if self.history is not None:
            self.history.append(tel_history.request_entry(
                request_id=(f"rebuild-{_table_slug(name)}"
                            f"-r{rep.index}"),
                op="rebuild",
                signature=self.affinity_key({"op": "register",
                                             "name": name}),
                outcome="rebuilt", wall_s=elapsed,
                resident={"table": name, "generation": gen,
                          "holders": sorted(entry["holders"])},
                replica={"index": rep.index,
                         "generation": rep.generation,
                         "port": getattr(rep.backend, "port",
                                         None)},
                trace=tracectx.stamp(ctx) or None))
        self._save_directory()

    # -- the durable router directory + HA adoption -------------------

    def _save_directory(self) -> None:
        """Mirror the in-memory replica/table directory to the coord
        dir (kind ``router_directory``), fence-counted and stamped
        with the lease epoch. Only an actively-serving router writes
        — a standby or fenced-out incarnation never clobbers the
        primary's view."""
        coord = self._coord_dir
        if coord is None:
            return
        if not (self._replicated or self._lease is not None):
            return
        if self.role in ("standby", "fenced"):
            return
        if self._lease is not None:
            # Write-time fence: re-read the lease file. A crashed or
            # stalled ex-primary whose renewer hasn't (or can never)
            # flip its role must not clobber the directory the NEW
            # primary is writing — ownership at the moment of the
            # write is what authorizes the write.
            doc0 = self._lease.read()
            if doc0 is not None and (
                    doc0.get("owner") != self._lease.owner
                    or int(doc0.get("epoch") or 0)
                    != self._lease.epoch):
                self.role = "fenced"
                telemetry.event(
                    "fleet_directory_write_fenced",
                    owner=self._lease.owner,
                    lease_owner=doc0.get("owner"),
                    lease_epoch=doc0.get("epoch"))
                return
        with self._lock:
            self._directory_fence += 1
            doc = {
                "kind": "router_directory",
                "schema_version": ROUTER_DIRECTORY_SCHEMA_VERSION,
                "fence": self._directory_fence,
                "lease_epoch": (self._lease.epoch
                                if self._lease is not None else 0),
                "written_by": self.config.router_id or "router",
                "table_replication":
                    self.config.table_replication,
                "updated_unix_s": time.time(),
                "tables": {
                    name: {
                        "generation": e["generation"],
                        "key": e.get("key", "key"),
                        "holders": {str(i): dict(h) for i, h
                                    in e["holders"].items()},
                    } for name, e in self._tables.items()
                },
                "replicas": [
                    {"index": r.index,
                     "host": getattr(r.backend, "host", None),
                     "port": getattr(r.backend, "port", None),
                     "generation": r.generation,
                     "state": r.state}
                    for r in self.replicas
                ],
            }
        try:
            atomic_write_json(router_directory_path(coord), doc)
        except OSError as exc:
            telemetry.event("fleet_directory_write_failed",
                            error=f"{type(exc).__name__}: {exc}")

    def adopt_from_directory(self) -> bool:
        """Standby takeover: rebuild the replica set and table
        directory from the durable ``router_directory.json`` written
        by the dead primary. Replica endpoints attach WITHOUT process
        handles (:class:`AttachedReplica`) — liveness is re-judged on
        the wire by the prober, and a dead slot drains and respawns
        through this router's own factory."""
        coord = self._coord_dir
        doc = load_router_directory(coord) if coord else None
        if doc is None:
            return False
        with self._lock:
            self.replicas = [
                _Replica(
                    index=int(spec["index"]),
                    backend=AttachedReplica(
                        spec.get("host") or "127.0.0.1",
                        int(spec["port"])),
                    generation=int(spec.get("generation") or 0),
                    state=str(spec.get("state") or "healthy"))
                for spec in doc.get("replicas", [])
                if spec.get("port") is not None
            ]
            self._tables = {
                name: {
                    "generation": int(e["generation"]),
                    "key": e.get("key", "key"),
                    "holders": {int(i): dict(h) for i, h
                                in (e.get("holders") or {}).items()},
                } for name, e in (doc.get("tables") or {}).items()
            }
            self._directory_fence = int(doc.get("fence") or 0)
        telemetry.event("fleet_directory_adopted",
                        replicas=len(self.replicas),
                        tables=sorted(self._tables),
                        fence=self._directory_fence)
        self._prober = threading.Thread(target=self._probe_loop,
                                        daemon=True,
                                        name="fleet-prober")
        self._prober.start()
        return True

    def _write_manifest_register(self, name, req, resp) -> None:
        if not self._coord_dir:
            return
        spec = {k: v for k, v in req.items() if k != "request_id"}
        doc = table_manifest_doc(
            name, spec, resp.get("key", "key"),
            int(resp.get("generation", 1)), [],
            {"replica_ranks": self.config.replica_ranks,
             "table_replication": self.config.table_replication})
        try:
            atomic_write_json(
                table_manifest_path(self._coord_dir, name), doc)
        except OSError as exc:
            telemetry.event("fleet_manifest_write_failed",
                            table=name,
                            error=f"{type(exc).__name__}: {exc}")

    def _append_manifest_delta(self, name, req,
                               generation: int) -> None:
        if not self._coord_dir:
            return
        man = load_table_manifest(self._coord_dir, name)
        if man is None:
            # Without the register spec the delta cannot be made
            # durable — loud, because a rebuild of this table now
            # CANNOT reach the new generation.
            telemetry.event("fleet_manifest_missing", table=name,
                            generation=generation)
            return
        spec = {k: v for k, v in req.items() if k != "request_id"}
        doc = table_manifest_doc(
            name, man["register"], man.get("key", "key"),
            generation, list(man.get("deltas") or []) + [spec],
            man.get("prep") or {})
        try:
            atomic_write_json(
                table_manifest_path(self._coord_dir, name), doc)
        except OSError as exc:
            telemetry.event("fleet_manifest_write_failed",
                            table=name,
                            error=f"{type(exc).__name__}: {exc}")

    def _drop_manifest(self, name) -> None:
        if not self._coord_dir:
            return
        try:
            os.remove(table_manifest_path(self._coord_dir, name))
        except OSError:
            pass

    # -- operator surfaces --------------------------------------------

    def dump_flight_recorder(self, reason: str) -> Optional[str]:
        """Dump the router's request ring (the daemon's postmortem
        contract, fleet-side): to ``flight_recorder_path``, else
        ``history_dir``. Called at stop when a path is configured;
        safe to call any time for a live snapshot."""
        path = self.config.flight_recorder_path
        if path is None:
            if self.config.history_dir is None:
                return None
            path = os.path.join(self.config.history_dir,
                                tel_live.FLIGHT_RECORDER_FILENAME)
        try:
            path = self.recorder.dump(path, reason)
        except OSError as exc:
            telemetry.event("fleet_flightrecorder_dump_failed",
                            path=path,
                            error=f"{type(exc).__name__}: {exc}")
            return None
        telemetry.event("fleet_flightrecorder_dumped", path=path,
                        reason=reason)
        return path

    def stats(self) -> dict:
        with self._lock:
            reps = [{
                "index": r.index,
                "generation": r.generation,
                "state": r.state,
                "port": getattr(r.backend, "port", None),
                "inflight": r.inflight,
                "strikes": r.strikes,
                "qps_60s": (r.last_stats or {}).get("qps_60s"),
                "p95_s": ((r.last_stats or {}).get("latency")
                          or {}).get("p95_s"),
                "poisoned": (r.last_stats or {}).get("poisoned"),
            } for r in self.replicas]
            counts: dict = {}
            for r in self.replicas:
                counts[r.state] = counts.get(r.state, 0) + 1
            return {
                "role": "fleet",
                "replicas": len(self.replicas),
                "healthy": counts.get("healthy", 0),
                "suspect": counts.get("suspect", 0),
                "drained": counts.get("drained", 0),
                "failed": counts.get("failed", 0),
                "failovers_total": self.failovers_total,
                "shed_total": self.shed_total,
                "replaced_total": self.replaced_total,
                "drains_total": self.drains_total,
                "rebuilds_total": self.rebuilds_total,
                "takeovers_total": self.takeovers_total,
                "router_role": self.role,
                "table_replication":
                    self.config.table_replication,
                "tables": {
                    name: {"generation": e["generation"],
                           "holders": {str(i): dict(h) for i, h
                                       in e["holders"].items()}}
                    for name, e in self._tables.items()},
                "served": self.served,
                "failed_requests": self.failed,
                "rejected": self.rejected,
                "qps_60s": round(self.live.qps(), 3),
                "uptime_s": round(self.live.uptime_s(), 3),
                "latency": self.live.overall_latency(),
                "replica_detail": reps,
                "tenants": self._tenant_stats_locked(),
                "autoscale": {
                    "enabled": bool(self.config.autoscale),
                    "spawns_total": self.autoscale_spawns_total,
                    "drains_total": self.autoscale_drains_total,
                },
            }

    def _tenant_stats_locked(self) -> dict:
        """Per-tenant stats block ({} when no tenant has been seen):
        the router LiveMetrics tenant summary (requests/outcomes/
        shed/qps/latency — the shape ``--watch`` renders) merged with
        the admission-side state (inflight, priority, quota,
        shed-by-kind tallies). Caller holds the router lock."""
        out = self.live.tenants_summary()
        for name, st in self._tenant_states.items():
            t = out.setdefault(name, {"requests": 0, "outcomes": {},
                                      "shed": 0, "qps_60s": 0.0,
                                      "latency": {}})
            t["inflight"] = st.inflight
            t["priority"] = st.priority
            t["quota_sheds"] = st.quota_sheds
            t["priority_sheds"] = st.priority_sheds
            if st.quota:
                t["quota"] = dict(st.quota)
        return out

    def prometheus_metrics(self) -> str:
        st = self.stats()
        text = self.live.to_prometheus(gauges={
            "fleet_replicas": st["replicas"],
            "fleet_healthy": st["healthy"],
            "fleet_suspect": st["suspect"],
            "fleet_drained": st["drained"],
            "fleet_failovers_total": st["failovers_total"],
            "fleet_shed_total": st["shed_total"],
            "fleet_replaced_total": st["replaced_total"],
            "fleet_drains_total": st["drains_total"],
            "fleet_rebuilds_total": st["rebuilds_total"],
            "router_takeovers_total": st["takeovers_total"],
            # 1 = actively serving (single/primary), 0 = standby or
            # fenced out.
            "router_role": (1 if st["router_role"] in ("single",
                                                       "primary")
                            else 0),
            "autoscale_enabled": (1 if st["autoscale"]["enabled"]
                                  else 0),
            "autoscale_spawns_total":
                st["autoscale"]["spawns_total"],
            "autoscale_drains_total":
                st["autoscale"]["drains_total"],
        })
        if st["tenants"]:
            # Labeled per-tenant admission gauges (the request/shed
            # counter series ride the shared LiveMetrics tenant
            # exposition above).
            lines = [text.rstrip("\n"),
                     "# TYPE djtpu_tenant_inflight gauge"]
            for name in sorted(st["tenants"]):
                t = st["tenants"][name]
                lines.append(
                    f'djtpu_tenant_inflight{{tenant="{name}"}} '
                    f'{t.get("inflight") or 0}')
            lines.append("# TYPE djtpu_tenant_priority gauge")
            for name in sorted(st["tenants"]):
                t = st["tenants"][name]
                lines.append(
                    f'djtpu_tenant_priority{{tenant="{name}"}} '
                    f'{t.get("priority") or 1}')
            text = "\n".join(lines) + "\n"
        if st["tables"]:
            # Labeled per-table gauge: serving-holder count (the
            # fleet's effective replication factor per table, live).
            lines = [text.rstrip("\n"),
                     "# TYPE djtpu_fleet_resident_holders gauge"]
            for name in sorted(st["tables"]):
                holders = st["tables"][name]["holders"]
                serving = sum(1 for h in holders.values()
                              if h.get("state") == "serving")
                lines.append(
                    f'djtpu_fleet_resident_holders'
                    f'{{table="{name}"}} {serving}')
            text = "\n".join(lines) + "\n"
        # One scrape sees the whole fleet: the replica metrics
        # fan-out merged into per-replica-labeled counters plus the
        # bucket-wise-summed latency histogram.
        return text + tel_live.fleet_prometheus(
            self._replica_metrics())

    def _replica_metrics(self) -> dict:
        """Best-effort ``metrics`` fan-out to every LIVE replica:
        index -> LiveMetrics snapshot (None for a slot that is
        drained/failed or did not answer — the fleet exposition
        reports it ``replica_up 0`` rather than stalling the
        scrape)."""
        with self._lock:
            reps = list(self.replicas)
        out: dict = {}
        for rep in reps:
            with self._lock:
                live = rep.state in ("healthy", "suspect")
            snap = None
            if live:
                try:
                    client = ServiceClient(
                        *rep.addr(),
                        timeout_s=self.config.probe_timeout_s)
                    try:
                        resp = client.send(tracectx.attach(
                            {"op": "metrics"}, tracectx.mint()))
                    finally:
                        client.close()
                    if resp.get("ok"):
                        snap = resp.get("metrics")
                except (OSError, ValueError):
                    snap = None
            out[rep.index] = snap
        return out

    def metrics_snapshot(self) -> dict:
        snap = self.live.snapshot()
        snap["stats"] = self.stats()
        snap["flight_records"] = len(self.recorder)
        snap["history_path"] = (self.history.path
                                if self.history is not None else None)
        per_replica = self._replica_metrics()
        snap["replicas"] = {str(i): s for i, s
                            in per_replica.items()}
        snap["fleet"] = tel_live.merge_snapshots(
            [s for s in per_replica.values() if s is not None])
        return snap

    def drain_replica(self, index: int,
                      reason: str = "operator drain") -> dict:
        """The operator op: drain (and replace) one replica by
        index."""
        with self._lock:
            if not 0 <= index < len(self.replicas):
                raise ValueError(f"no replica {index}")
            rep = self.replicas[index]
        self._drain(rep, reason)
        return {"replica": index, "state": rep.state,
                "reason": reason}

    def wait_replaced(self, index: int, timeout_s: float = 60.0
                      ) -> bool:
        """Block until replica ``index`` is healthy at a HIGHER
        generation than when it was last drained (test/smoke
        helper)."""
        rep = self.replicas[index]
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if rep.state == "healthy" \
                        and rep.replaced_at is not None \
                        and (rep.drained_at is None
                             or rep.replaced_at >= rep.drained_at):
                    return True
            time.sleep(0.05)
        return False


class _AttemptFailed(RuntimeError):
    """One failover-able dispatch attempt failed (connection death or
    a replica-fatal response) — retried by the bounded
    retry_with_backoff loop, never surfaced raw."""


# -- the router TCP daemon ---------------------------------------------


def _route(router: FleetRouter, req: dict) -> dict:
    op = req.get("op")
    if op == "ping":
        return {"ok": True, "op": "ping", "role": "fleet"}
    if op == "stats":
        return {"ok": True, **router.stats()}
    if op == "metrics":
        if req.get("format") == "prometheus":
            return {"ok": True, "op": "metrics",
                    "format": "prometheus",
                    "prometheus": router.prometheus_metrics()}
        return {"ok": True, "op": "metrics",
                "metrics": router.metrics_snapshot()}
    if op == "drain":
        if req.get("replica") is None:
            # Refuse rather than proxy: routing a bare drain to an
            # affinity-chosen replica would silently recycle one warm
            # replica while telling the operator the service drained.
            raise ValueError(
                "fleet drain needs \"replica\": <index> (drain one "
                "slot, which is then replaced); to stop the whole "
                "fleet use {\"op\": \"shutdown\"}")
        rec = router.drain_replica(
            int(req["replica"]),
            reason=str(req.get("reason", "operator drain")))
        return {"ok": True, "op": "drain", **rec}
    if op == "shutdown":
        # FLEET-level shutdown: stop the router (replicas drained and
        # reaped by the serving loop) — never routed to a replica,
        # which would just kill one daemon and watch it be replaced.
        router.shutdown_requested.set()
        return {"ok": True, "op": "shutdown", "role": "fleet"}
    return router.dispatch(req)


def start_router_daemon(router: FleetRouter, host: str = "127.0.0.1",
                        port: int = 0):
    """Bind + serve the fleet wire on a background thread; returns
    ``(server, port)``. Same line-JSON protocol as one daemon."""
    import socket
    import socketserver

    class Handler(socketserver.StreamRequestHandler):
        def setup(self):
            super().setup()
            with self.server._conns_lock:
                self.server._conns.add(self.connection)

        def finish(self):
            with self.server._conns_lock:
                self.server._conns.discard(self.connection)
            super().finish()

        def handle(self):
            for raw in self.rfile:
                line = raw.decode("utf-8").strip()
                if not line:
                    continue
                req = None
                try:
                    req = json.loads(line)
                    resp = _route(router, req)
                except Exception as exc:  # noqa: BLE001 - wire edge
                    resp = {"ok": False,
                            "error": type(exc).__name__,
                            "message": str(exc)}
                self.wfile.write(
                    (json.dumps(resp) + "\n").encode("utf-8"))
                self.wfile.flush()
                if isinstance(req, dict) \
                        and req.get("op") == "shutdown":
                    return

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

        def __init__(self, *args, **kwargs):
            super().__init__(*args, **kwargs)
            self._conns: set = set()
            self._conns_lock = threading.Lock()

        def close_connections(self) -> None:
            """Sever every ESTABLISHED connection. ``shutdown()``
            only stops the accept loop — handler threads keep
            serving open sockets, which is exactly wrong for the
            crash() path (a killed process tears its sockets, and
            clients must observe the tear to fail over)."""
            with self._conns_lock:
                conns = list(self._conns)
            for sock in conns:
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass

    server = Server((host, port), Handler)
    thread = threading.Thread(target=server.serve_forever,
                              daemon=True)
    thread.start()
    return server, server.server_address[1]


# -- router HA: fenced lease + standby takeover ------------------------


class RouterHA:
    """Primary/standby pairing for the fleet router (ROADMAP 5b).

    N router processes share the PURE affinity function, the durable
    table manifests, and the generation-fenced directory file; the
    fenced lease file (:class:`RouterLease`) elects the ONE serving
    primary — no consensus protocol. The primary renews the lease on
    ``lease_renew_s``; a standby polls it and, when it goes stale
    past ``lease_ttl_s`` (the primary died, or was fenced out),
    acquires the lease, ADOPTS the replica/table directory, binds the
    ADVERTISED endpoint, and serves. Clients ride the same
    reconnect+resend contract as replica failover: idempotent ops
    resend through their bounded backoff; the duplicate-request fence
    answers a resent id idempotently on whichever router serves it.
    """

    def __init__(self, router: FleetRouter, host: str = "127.0.0.1",
                 port: int = 0, owner: Optional[str] = None):
        self.router = router
        self.host = host
        self.port = port
        cfg = router.config
        self.owner = (owner or cfg.router_id
                      or f"router-{os.getpid()}-"
                         f"{os.urandom(2).hex()}")
        cfg.router_id = self.owner
        coord = router._coord_dir
        if coord is None:
            raise FleetError(
                "router HA needs a coord_dir (or persist_dir) for "
                "the lease + directory files")
        os.makedirs(coord, exist_ok=True)
        self.lease = RouterLease(
            os.path.join(coord, ROUTER_LEASE_FILENAME),
            self.owner, ttl_s=cfg.lease_ttl_s)
        self.server = None
        self.bound_port: Optional[int] = None
        self.took_over = threading.Event()
        self._stop = threading.Event()
        self._threads: list = []

    # -- primary ------------------------------------------------------

    def start_primary(self, spawn: bool = True) -> int:
        """Acquire the lease, spawn (or keep) the replica set, bind,
        advertise the serving addr in the lease, start renewing.
        Returns the bound port."""
        if not self.lease.acquire():
            raise FleetError(
                f"router {self.owner!r} could not acquire the lease "
                f"at {self.lease.path} (a live primary holds it)")
        self.router._lease = self.lease
        self.router.role = "primary"
        if spawn:
            self.router.start()
        self.server, self.bound_port = start_router_daemon(
            self.router, self.host, self.port)
        self.lease._write(self.lease.epoch,
                          addr=[self.host, self.bound_port])
        self.router._save_directory()
        self._start_renewer()
        telemetry.event("router_primary", owner=self.owner,
                        port=self.bound_port,
                        epoch=self.lease.epoch)
        return self.bound_port

    def _start_renewer(self):
        t = threading.Thread(target=self._renew_loop, daemon=True,
                             name=f"router-lease-{self.owner}")
        self._threads.append(t)
        t.start()

    def _renew_loop(self):
        while not self._stop.wait(self.router.config.lease_renew_s):
            if not self.lease.renew():
                # Fenced out: a higher epoch landed (another router
                # took over while this one stalled). Serving on
                # would split-brain the directory — stand down.
                self.router.role = "fenced"
                telemetry.event("router_fenced", owner=self.owner,
                                epoch=self.lease.epoch)
                self.router.shutdown_requested.set()
                return

    # -- standby ------------------------------------------------------

    def start_standby(self) -> None:
        """Watch the lease; take over when it goes stale."""
        self.router.role = "standby"
        t = threading.Thread(target=self._standby_loop, daemon=True,
                             name=f"router-standby-{self.owner}")
        self._threads.append(t)
        t.start()
        telemetry.event("router_standby", owner=self.owner)

    def _standby_loop(self):
        while not self._stop.wait(self.router.config.lease_renew_s):
            doc = self.lease.read()
            if doc is not None and not self.lease.stale(doc):
                continue
            addr = (doc or {}).get("addr")
            if not self.lease.acquire(addr=addr):
                continue  # lost the race to another standby
            try:
                self._take_over(addr)
            except Exception as exc:  # noqa: BLE001 - takeover edge
                telemetry.event(
                    "router_takeover_failed", owner=self.owner,
                    error=f"{type(exc).__name__}: {exc}")
            return

    def _take_over(self, addr):
        from distributed_join_tpu.parallel.faults import (
            retry_with_backoff,
        )

        r = self.router
        r._lease = self.lease
        r.adopt_from_directory()
        r.role = "primary"
        with r._lock:
            r.takeovers_total += 1
        host = addr[0] if addr else self.host
        port = int(addr[1]) if addr else self.port

        def bind():
            return start_router_daemon(r, host, port)

        # The dead primary's socket may linger a beat — retry the
        # bind under the same bounded backoff clients use.
        (self.server, self.bound_port), _ = retry_with_backoff(
            bind, max_attempts=20, backoff_s=0.1,
            retry_on=(OSError,))
        self.lease._write(self.lease.epoch,
                          addr=[host, self.bound_port])
        r._save_directory()
        self._start_renewer()
        self.took_over.set()
        telemetry.event("router_takeover", owner=self.owner,
                        port=self.bound_port,
                        epoch=self.lease.epoch)

    # -- lifecycle ----------------------------------------------------

    def crash(self) -> None:
        """Die like a killed process (test/smoke/chaos helper): stop
        renewing, close the listening socket, stop the prober —
        WITHOUT draining, reaping, or releasing anything. The lease
        goes stale on its own; the replicas belong to the fleet, not
        to this router incarnation."""
        self._stop.set()
        self.router._stop.set()
        if self.server is not None:
            self.server.shutdown()
            self.server.server_close()
            # A killed process tears its ESTABLISHED sockets too —
            # without this, in-process handler threads would keep
            # serving connected clients from beyond the grave (and
            # those clients would never fail over).
            self.server.close_connections()
            self.server = None
        telemetry.event("router_crashed", owner=self.owner)

    def stop(self, drain: bool = True) -> None:
        """Graceful teardown: release the lease (instant standby
        handoff), close the wire, stop the router."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        if self.server is not None:
            self.server.shutdown()
            self.server.server_close()
            self.server = None
        if self.router.role == "primary":
            self.lease.release()
        self.router.stop(drain=drain)


# -- the CI smoke ------------------------------------------------------


def run_fleet_smoke(args) -> dict:
    """The ``fleet`` lane's acceptance protocol (docs/FLEET.md), end
    to end through real subprocess replicas and the router TCP loop:

    1. 2 replicas share one persist dir; a cold query Q compiles on
       its affine replica, the warm repeat must land on the SAME
       replica with zero new traces;
    2. ONE SCRIPTED KILL (SIGKILL) of that replica; the immediate
       repeat of Q must fail over to the sibling within the bounded
       retry budget and answer with the SAME match count (graded
       against the pandas oracle);
    3. the killed replica must be drained and a replacement spawned
       (healthy at generation 1) — observed, with the drain stamped
       within one probe interval of the kill;
    4. the post-replacement repeat of Q must dispatch on the
       replacement with ZERO new traces (the shared persist dir is
       the distribution tier);
    5. a concurrent burst at inflight bound 1 must shed >= 1 request
       with a structured AdmissionError (and never queue unboundedly);
    6. fleet Prometheus gauges and the flight/history stamps are
       emitted for ``analyze check``.

    Returns the JSON record (kind ``fleet_smoke``) whose deterministic
    counter signature the perfgate lane gates against
    ``results/baselines/fleet_smoke.json``.
    """
    import tempfile

    violations: list = []
    workdir_owned = args.persist_dir is None
    workdir = args.persist_dir or tempfile.mkdtemp(
        prefix="djtpu_fleet_smoke_")
    cfg = FleetConfig(
        n_replicas=2,
        replica_ranks=args.replica_ranks,
        persist_dir=os.path.join(workdir, "programs"),
        history_dir=(args.history_dir
                     or os.path.join(workdir, "history")),
        # The failover gate (failovers_total >= 1) needs the REQUEST
        # path to discover the scripted kill: a sub-second prober
        # could drain the victim first and serve the repeat on
        # attempt 1, tripping the gate spuriously. Drain latency is
        # gated from the kill either way (the strike path drains in
        # milliseconds).
        probe_interval_s=max(args.probe_interval_s, 5.0),
        retry_budget=2,
        max_inflight_per_replica=args.max_inflight,
        flight_recorder_path=args.flight_recorder_path,
        spawn_timeout_s=args.spawn_timeout_s,
    )
    router = FleetRouter(
        process_fleet_factory(cfg, platform=args.platform or "cpu"),
        cfg)
    router.start()
    server, port = start_router_daemon(router)
    client = ServiceClient("127.0.0.1", port, retries=2)

    q = {"op": "join", "build_nrows": 2048, "probe_nrows": 2048,
         "seed": 17, "selectivity": 0.4, "rand_max": 1024,
         "out_capacity_factor": 3.0}

    def oracle_matches():
        from distributed_join_tpu.service.server import (
            _tables_from_spec,
        )

        build, probe = _tables_from_spec(q)
        return len(build.to_pandas().merge(probe.to_pandas(),
                                           on="key"))

    try:
        expected = oracle_matches()
        cold = client.send(q)
        if not cold.get("ok"):
            raise RuntimeError(f"cold query failed: {cold}")
        warm = client.send(q)
        if not warm.get("ok"):
            raise RuntimeError(f"warm query failed: {warm}")
        if warm["fleet"]["replica"] != cold["fleet"]["replica"]:
            violations.append(
                "affinity broke: warm repeat routed to replica "
                f"{warm['fleet']['replica']}, cold ran on "
                f"{cold['fleet']['replica']}")
        if warm["new_traces"] != 0:
            violations.append(
                f"warm repeat traced {warm['new_traces']} new "
                "program(s)")
        for name, resp in (("cold", cold), ("warm", warm)):
            if resp["matches"] != expected:
                violations.append(
                    f"{name} matches {resp['matches']} != pandas "
                    f"oracle {expected}")

        # THE scripted kill: SIGKILL the affine replica mid-traffic.
        victim = router.replicas[cold["fleet"]["replica"]]
        victim_index = victim.index
        t_kill = time.monotonic()
        victim.backend.kill()
        failover = client.send(q)
        if not failover.get("ok"):
            violations.append(
                f"failover repeat was not served: {failover}")
        else:
            if failover["matches"] != expected:
                violations.append(
                    f"failover matches {failover['matches']} != "
                    f"oracle {expected}")
            if failover["fleet"]["replica"] == victim_index:
                violations.append(
                    "failover answered from the killed replica")
            if failover["fleet"]["attempts"] > cfg.retry_budget + 1:
                violations.append(
                    f"failover took {failover['fleet']['attempts']} "
                    f"attempts > budget {cfg.retry_budget + 1}")

        # Drain observed within one probe interval (+ scheduling
        # slack), replacement healthy at generation 1.
        replaced = router.wait_replaced(victim_index,
                                        timeout_s=cfg.spawn_timeout_s)
        if not replaced:
            violations.append(
                f"killed replica {victim_index} was not replaced "
                f"within {cfg.spawn_timeout_s}s")
        drained_after_s = ((victim.drained_at or time.monotonic())
                           - t_kill)
        if victim.drained_at is None or drained_after_s > \
                3 * cfg.probe_interval_s + 5.0:
            violations.append(
                f"kill -> drained took {drained_after_s:.2f}s "
                f"(> probe interval {cfg.probe_interval_s}s + slack)")

        # Post-replacement repeat: the replacement must serve the
        # pre-fault signature WARM (zero traces via the shared
        # persist dir). Route directly at the replacement to pin the
        # assertion on it — only when a replacement is actually up
        # (dialing the SIGKILLed backend's old port would crash the
        # harness instead of reporting the violation above).
        replay: dict = {}
        if replaced:
            try:
                direct = ServiceClient(
                    *router.replicas[victim_index].addr(),
                    timeout_s=120.0)
                try:
                    replay = direct.send(
                        {**q, "request_id": "smoke-replay"})
                finally:
                    direct.close()
            except (OSError, ValueError) as exc:
                violations.append(
                    "replacement replica unreachable for the "
                    f"replay: {type(exc).__name__}: {exc}")
            if replay and not replay.get("ok"):
                violations.append(
                    f"replacement replica refused the replay: "
                    f"{replay}")
            elif replay:
                if replay["matches"] != expected:
                    violations.append(
                        f"replacement matches {replay['matches']} "
                        f"!= oracle {expected}")
                if replay["new_traces"] != 0:
                    violations.append(
                        "replacement was not warm: "
                        f"{replay['new_traces']} new trace(s) — the "
                        "shared persist dir must hand it the "
                        "compiled program")

        # Synthetic overload: a concurrent burst at inflight bound 1
        # must shed with structured errors, never queue unboundedly.
        router.config.max_inflight_per_replica = 1
        burst_n = 8
        results = [None] * burst_n

        def fire(i):
            c = ServiceClient("127.0.0.1", port)
            try:
                results[i] = c.send(
                    {**q, "request_id": f"burst-{i}"})
            finally:
                c.close()

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(burst_n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300.0)
        shed = [r for r in results
                if r is not None and r.get("shed")]
        served = [r for r in results
                  if r is not None and r.get("ok")]
        if not shed:
            violations.append(
                f"burst of {burst_n} at inflight bound 1 shed "
                "nothing — admission is queueing unboundedly")
        for r in served:
            if r["matches"] != expected:
                violations.append(
                    f"burst answer {r['matches']} != oracle "
                    f"{expected}")
        if len(shed) + len(served) != burst_n:
            violations.append(
                f"burst lost requests: {len(served)} served + "
                f"{len(shed)} shed != {burst_n}")
        router.config.max_inflight_per_replica = args.max_inflight

        prom = router.prometheus_metrics()
        for gauge in ("djtpu_fleet_replicas", "djtpu_fleet_healthy",
                      "djtpu_fleet_drained",
                      "djtpu_fleet_failovers_total",
                      "djtpu_fleet_shed_total"):
            if gauge not in prom:
                violations.append(
                    f"prometheus exposition missing {gauge}")

        stats = router.stats()
        if stats["replaced_total"] < 1:
            violations.append("no replacement counted")
        if stats["failovers_total"] < 1:
            violations.append("no failover counted")
        if stats["healthy"] != 2:
            violations.append(
                f"fleet did not return to 2 healthy replicas: "
                f"{stats}")
    finally:
        client.close()
        server.shutdown()
        server.server_close()
        router.stop()

    record = {
        "kind": "fleet_smoke",
        "benchmark": "fleet_smoke",
        "n_ranks": cfg.replica_ranks,
        "replicas": cfg.n_replicas,
        "matches_expected": expected,
        "killed_replica": victim_index,
        "drained_after_s": round(drained_after_s, 3),
        "failover_attempts": (failover.get("fleet", {})
                              .get("attempts")),
        "burst_served": len(served),
        "burst_shed": len(shed),
        "stats": stats,
        "history_path": (router.history.path
                         if router.history is not None else None),
        "violations": violations,
        # The deterministic gate body (integer counters only; shed/
        # failover TIMINGS and counts beyond the gates above are
        # load-dependent and stay outside the signature).
        "counter_signature": {
            "signature_version": 1,
            "n_ranks": cfg.replica_ranks,
            "counters": {
                "replicas": cfg.n_replicas,
                "matches_cold": cold["matches"],
                "matches_warm": warm["matches"],
                "matches_failover": failover.get("matches", -1),
                "matches_replacement": replay.get("matches", -1),
                "warm_new_traces": warm["new_traces"],
                "replacement_new_traces": replay.get("new_traces",
                                                     -1),
                "requests_lost": burst_n - len(served) - len(shed),
            },
        },
    }
    if violations:
        # Keep the workdir (program blobs, history, flight dumps) —
        # it IS the postmortem of a failed smoke.
        record["workdir"] = workdir
        raise FleetSmokeError(
            "fleet smoke violations: " + "; ".join(violations),
            record)
    if workdir_owned:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    return record


class FleetSmokeError(RuntimeError):
    def __init__(self, msg, record):
        super().__init__(msg)
        self.record = record


def run_tenant_smoke(args) -> dict:
    """The ``fleet`` lane's multi-tenancy + autoscaling acceptance
    protocol (docs/FLEET.md "Multi-tenancy & autoscaling"), end to
    end through real subprocess replicas and the router TCP loop:

    1. two configured tenants — ``gold`` (priority 2, no quota) and
       ``bronze`` (priority 1, 0.5 QPS token bucket); gold's cold +
       warm query Q is oracle-graded and must repeat with zero new
       traces, its responses and the replica's own stats carrying
       the tenant stamp;
    2. QUOTA REFUSAL: a back-to-back bronze burst must shed with a
       structured ``QuotaExceededError`` naming the QPS bound (and
       ``shed: true`` + the tenant echoed on the wire);
    3. PRIORITY SHED ORDER: with every replica's inflight pinned at
       bronze's priority share of the bound, a bronze request must
       shed with ``ShedError`` while the SAME-instant gold request
       is served — the low-priority tenant yields first, never the
       quiet one;
    4. AUTOSCALE SPAWN, WARM: the control loop (low QPS bound, short
       sustain) must spawn a third replica whose pre-warm rotation
       gate replayed the hottest retained signature with ZERO new
       traces (``warm_verified``) BEFORE entering rotation;
    5. the per-tenant + autoscale Prometheus series are emitted and
       the ``fleet_autoscale`` record is well-formed.

    Returns the JSON record (kind ``fleet_tenant_smoke``) for
    ``analyze check`` in the fleet lane.
    """
    import tempfile

    violations: list = []
    workdir_owned = args.persist_dir is None
    workdir = args.persist_dir or tempfile.mkdtemp(
        prefix="djtpu_tenant_smoke_")
    cfg = FleetConfig(
        n_replicas=2,
        replica_ranks=args.replica_ranks,
        persist_dir=os.path.join(workdir, "programs"),
        history_dir=(args.history_dir
                     or os.path.join(workdir, "history")),
        probe_interval_s=0.5,
        retry_budget=2,
        max_inflight_per_replica=2,
        flight_recorder_path=args.flight_recorder_path,
        spawn_timeout_s=args.spawn_timeout_s,
        tenants={
            "gold": {"priority": 2},
            "bronze": {"qps": 0.5, "burst_s": 1.0, "priority": 1},
        },
        autoscale=True,
        autoscale_max_replicas=3,
        autoscale_up_qps=0.01,
        autoscale_interval_s=0.5,
        autoscale_sustain=2,
    )
    router = FleetRouter(
        process_fleet_factory(cfg, platform=args.platform or "cpu"),
        cfg)
    router.start()
    server, port = start_router_daemon(router)
    client = ServiceClient("127.0.0.1", port, retries=2)

    q = {"op": "join", "build_nrows": 2048, "probe_nrows": 2048,
         "seed": 17, "selectivity": 0.4, "rand_max": 1024,
         "out_capacity_factor": 3.0}

    def oracle_matches():
        from distributed_join_tpu.service.server import (
            _tables_from_spec,
        )

        build, probe = _tables_from_spec(q)
        return len(build.to_pandas().merge(probe.to_pandas(),
                                           on="key"))

    bronze_refused = gold_pressure = None
    autoscale = {}
    try:
        expected = oracle_matches()
        cold = client.send({**q, "tenant": "gold"})
        if not cold.get("ok"):
            raise RuntimeError(f"gold cold query failed: {cold}")
        warm = client.send({**q, "tenant": "gold"})
        if not warm.get("ok"):
            raise RuntimeError(f"gold warm query failed: {warm}")
        for name, resp in (("cold", cold), ("warm", warm)):
            if resp["matches"] != expected:
                violations.append(
                    f"gold {name} matches {resp['matches']} != "
                    f"pandas oracle {expected}")
        if warm["new_traces"] != 0:
            violations.append(
                f"gold warm repeat traced {warm['new_traces']} new "
                "program(s)")
        # The tenant stamp rides the wire to the REPLICA: its own
        # stats must account the gold traffic per-tenant.
        serving = router.replicas[cold["fleet"]["replica"]]
        try:
            direct = ServiceClient(*serving.addr(), timeout_s=30.0)
            try:
                rep_stats = direct.send(
                    tracectx.attach({"op": "stats"},
                                    tracectx.mint()))
            finally:
                direct.close()
        except (OSError, ValueError) as exc:
            rep_stats = {}
            violations.append(
                "serving replica unreachable for the tenant-stamp "
                f"check: {type(exc).__name__}: {exc}")
        if "gold" not in (rep_stats.get("tenants") or {}):
            violations.append(
                "replica stats carry no 'gold' tenant slot — the "
                "tenant field did not ride the wire to the replica")

        # Quota refusal: bronze's 0.5 QPS bucket holds ONE token —
        # back-to-back sends must shed with the bound named.
        bronze_results = []
        for i in range(6):
            bronze_results.append(client.send(
                {**q, "tenant": "bronze",
                 "request_id": f"bronze-burst-{i}"}))
        bronze_refused = [
            r for r in bronze_results
            if r.get("error") == "QuotaExceededError"]
        if not bronze_refused:
            violations.append(
                "bronze burst of 6 over a 0.5 QPS quota was never "
                "quota-refused")
        for r in bronze_refused:
            if not r.get("shed") or r.get("tenant") != "bronze":
                violations.append(
                    "quota refusal missing shed/tenant stamps: "
                    f"{r}")
            if "QPS quota" not in str(r.get("message")):
                violations.append(
                    "quota refusal does not name the QPS bound: "
                    f"{r.get('message')}")
        leaked = [r for r in bronze_results
                  if not r.get("ok")
                  and r.get("error") not in ("QuotaExceededError",
                                             "ShedError")]
        if leaked:
            violations.append(
                f"bronze burst leaked unstructured errors: "
                f"{leaked[:2]}")

        # Autoscale spawn: the sustained (tiny) QPS bound must spawn
        # replica 2, pre-warm verified with zero new traces BEFORE
        # rotation. Waiting for it FIRST also settles the fleet at
        # autoscale_max_replicas so the priority-shed gate below
        # pins a stable replica set.
        deadline = time.monotonic() + cfg.spawn_timeout_s
        while time.monotonic() < deadline:
            with router._lock:
                if router.autoscale_spawns_total >= 1:
                    break
            # Keep the probed qps_60s above the bound while waiting.
            client.send({**q, "tenant": "gold",
                         "request_id":
                             f"keepwarm-{int(time.monotonic())}"})
            time.sleep(0.5)
        autoscale = router.autoscale_record()
        spawns = [e for e in autoscale["events"]
                  if e["action"] == "spawn"]
        if not spawns:
            violations.append(
                "autoscaler never spawned under sustained load "
                f"(events: {autoscale['events']})")
        else:
            ev = spawns[0]
            if not ev.get("warm_verified"):
                violations.append(
                    f"autoscale spawn was not warm-verified: {ev}")
            if ev.get("new_traces") != 0:
                violations.append(
                    "autoscale pre-warm replay traced "
                    f"{ev.get('new_traces')} new program(s)")
            with router._lock:
                scaled = [r for r in router.replicas
                          if r.index == ev["replica"]
                          and r.state in ("healthy", "suspect")]
            if not scaled:
                violations.append(
                    f"spawned replica {ev['replica']} is not in "
                    "rotation")

        # Priority shed order: pin every replica's inflight at
        # bronze's share (priority 1 of max 2 -> bound 1 of 2). The
        # SAME pressure must shed bronze with ShedError and still
        # serve gold.
        time.sleep(2.5)  # refill bronze's bucket past one token
        with router._lock:
            pinned = [r for r in router.replicas
                      if r.state in ("healthy", "suspect")]
            for r in pinned:
                r.inflight += 1
        try:
            bronze_pressure = client.send(
                {**q, "tenant": "bronze",
                 "request_id": "bronze-pressure"})
            gold_pressure = client.send(
                {**q, "tenant": "gold",
                 "request_id": "gold-pressure"})
        finally:
            with router._lock:
                for r in pinned:
                    r.inflight = max(r.inflight - 1, 0)
        if bronze_pressure.get("error") != "ShedError":
            violations.append(
                "bronze under pressure was not priority-shed "
                f"(ShedError): {bronze_pressure}")
        elif "priority" not in str(
                bronze_pressure.get("message")):
            violations.append(
                "priority shed does not name the priority bound: "
                f"{bronze_pressure.get('message')}")
        if not gold_pressure.get("ok") \
                or gold_pressure.get("matches") != expected:
            violations.append(
                "gold under the SAME pressure was not served "
                f"exactly: {gold_pressure}")

        prom = router.prometheus_metrics()
        for series in ("djtpu_tenant_requests_total",
                       "djtpu_tenant_shed_total",
                       "djtpu_tenant_inflight",
                       "djtpu_tenant_priority",
                       "djtpu_autoscale_enabled",
                       "djtpu_autoscale_spawns_total",
                       "djtpu_autoscale_drains_total"):
            if series not in prom:
                violations.append(
                    f"prometheus exposition missing {series}")
        stats = router.stats()
        for name in ("gold", "bronze"):
            if name not in (stats.get("tenants") or {}):
                violations.append(
                    f"router stats missing tenant {name!r}")
        if (stats["tenants"].get("gold") or {}).get("shed"):
            violations.append(
                "gold (the quiet tenant) was shed "
                f"{stats['tenants']['gold']['shed']} time(s)")
    finally:
        client.close()
        server.shutdown()
        server.server_close()
        router.stop()

    record = {
        "kind": "fleet_tenant_smoke",
        "benchmark": "fleet_tenant_smoke",
        "n_ranks": cfg.replica_ranks,
        "replicas": cfg.n_replicas,
        "matches_expected": expected,
        "tenants": stats.get("tenants"),
        "autoscale": {
            "enabled": autoscale.get("enabled"),
            "spawns_total": autoscale.get("spawns_total"),
            "drains_total": autoscale.get("drains_total"),
            "events": autoscale.get("events"),
        },
        "stats": stats,
        "history_path": (router.history.path
                         if router.history is not None else None),
        "violations": violations,
        # Deterministic gate body: indicator counters only (shed
        # COUNTS are timing-dependent and stay outside).
        "counter_signature": {
            "signature_version": 1,
            "n_ranks": cfg.replica_ranks,
            "counters": {
                "replicas": cfg.n_replicas,
                "matches_gold_cold": cold["matches"],
                "matches_gold_warm": warm["matches"],
                "gold_warm_new_traces": warm["new_traces"],
                "bronze_quota_refused":
                    int(bool(bronze_refused)),
                "bronze_priority_shed": int(
                    bronze_pressure.get("error") == "ShedError"),
                "gold_served_under_pressure": int(
                    bool(gold_pressure
                         and gold_pressure.get("ok"))),
                "autoscale_spawned": int(bool(spawns)),
                "autoscale_warm_verified": int(
                    bool(spawns
                         and spawns[0].get("warm_verified"))),
            },
        },
    }
    if violations:
        record["workdir"] = workdir
        raise FleetSmokeError(
            "tenant smoke violations: " + "; ".join(violations),
            record)
    if workdir_owned:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    return record


def run_tracing_smoke(args) -> dict:
    """The ``tracing`` lane's acceptance protocol
    (docs/OBSERVABILITY.md "Distributed tracing"): ONE causal
    timeline across the router and real subprocess replicas, through
    one scripted SIGKILL.

    1. 2 subprocess replicas, each writing its OWN telemetry session
       dir; the router (and the harness client) run an in-process
       session beside them — three per-process JSONL streams;
    2. a cold join Q and its warm repeat flow end to end (client mint
       -> router child span -> replica adoption);
    3. ONE SCRIPTED SIGKILL of the affine replica, then the repeat of
       Q under a harness-minted trace context: the router's FAILED
       attempt and the winning failover retry must share that ONE
       trace_id — in the flight ring (per-attempt records) and in the
       merged timeline (``trace_ids_for_request``);
    4. the per-process session dirs assemble into ONE Perfetto
       timeline (``telemetry/timeline.py``) whose focus trace spans
       both surviving processes with >= 1 cross-process hop and a
       non-empty critical path; both artifacts must pass
       ``analyze check``;
    5. the record (kind ``tracing_smoke``) carries the deterministic
       counter signature the perfgate lane gates against
       ``results/baselines/tracing_smoke.json``.
    """
    import tempfile

    from distributed_join_tpu.telemetry import timeline
    from distributed_join_tpu.telemetry.analyze import check_file

    violations: list = []
    workdir_owned = args.persist_dir is None
    workdir = args.persist_dir or tempfile.mkdtemp(
        prefix="djtpu_tracing_smoke_")
    tel_root = os.path.join(workdir, "telemetry")
    router_dir = os.path.join(tel_root, "router")
    rep_dirs = {i: os.path.join(tel_root, f"replica{i}")
                for i in range(2)}
    cfg = FleetConfig(
        n_replicas=2,
        replica_ranks=args.replica_ranks,
        persist_dir=os.path.join(workdir, "programs"),
        history_dir=(args.history_dir
                     or os.path.join(workdir, "history")),
        # Same rationale as run_fleet_smoke: the REQUEST path (not
        # the prober) must discover the scripted kill, so the failed
        # attempt actually happens and lands on the trace.
        probe_interval_s=max(args.probe_interval_s, 5.0),
        retry_budget=2,
        max_inflight_per_replica=args.max_inflight,
        spawn_timeout_s=args.spawn_timeout_s,
    )
    # DISTINCT per-slot session dirs (generation 0 only — a
    # replacement must never append into its predecessor's stream).
    overrides = {i: {"extra_args": ["--telemetry", rep_dirs[i]]}
                 for i in rep_dirs}
    router = FleetRouter(
        process_fleet_factory(cfg, platform=args.platform or "cpu",
                              replica_overrides=overrides),
        cfg)
    sink = telemetry.configure(router_dir, rank=0)
    router.start()
    server, port = start_router_daemon(router)
    client = ServiceClient("127.0.0.1", port, retries=2)

    q = {"op": "join", "build_nrows": 2048, "probe_nrows": 2048,
         "seed": 17, "selectivity": 0.4, "rand_max": 1024,
         "out_capacity_factor": 3.0}
    rid = "tracing-failover"
    root = tracectx.mint()
    root_tid = root["trace_id"]

    try:
        cold = client.send(q)
        if not cold.get("ok"):
            raise RuntimeError(f"cold query failed: {cold}")
        warm = client.send(q)
        if not warm.get("ok"):
            raise RuntimeError(f"warm query failed: {warm}")
        # The response must echo the trace the client minted — the
        # wire-propagation contract, asserted before any fault.
        for name, resp in (("cold", cold), ("warm", warm)):
            if not (resp.get(tracectx.TRACE_FIELD) or {}) \
                    .get("trace_id"):
                violations.append(
                    f"{name} response carries no trace context")

        # THE scripted kill, then the repeat under a KNOWN root
        # trace: the failed attempt and the failover retry must both
        # be children of it.
        victim = router.replicas[cold["fleet"]["replica"]]
        victim_index = victim.index
        victim.backend.kill()
        failover = client.send(
            tracectx.attach({**q, "request_id": rid}, root))
        if not failover.get("ok"):
            violations.append(
                f"failover repeat was not served: {failover}")
        else:
            if failover["fleet"]["replica"] == victim_index:
                violations.append(
                    "failover answered from the killed replica")
            if failover["matches"] != cold["matches"]:
                violations.append(
                    f"failover matches {failover['matches']} != "
                    f"cold {cold['matches']}")
            if (failover.get(tracectx.TRACE_FIELD) or {}) \
                    .get("trace_id") != root_tid:
                violations.append(
                    "failover response does not echo the "
                    "harness-minted trace id")

        # Flight-ring continuity: every per-attempt record for rid —
        # the failed dispatch included — must resolve to the ONE
        # root trace id.
        ring = [r for r in router.recorder.snapshot()["records"]
                if r.get("request_id") == rid]
        failed = [r for r in ring
                  if r.get("outcome") == "attempt_failed"]
        ring_tids = {(r.get("trace") or {}).get("trace_id")
                     for r in ring}
        if not failed:
            violations.append(
                "no attempt_failed flight record for the killed "
                "dispatch — the failed attempt left no trace")
        if ring_tids != {root_tid}:
            violations.append(
                f"flight records for {rid!r} carry trace ids "
                f"{sorted(map(str, ring_tids))} != the one root "
                f"{root_tid}")
    finally:
        client.close()
        server.shutdown()
        server.server_close()
        router.stop()
        if telemetry.sink() is sink:
            telemetry.finalize()

    # -- assemble the fleet timeline from the per-process streams ----
    tl_record: dict = {}
    n_timeline_procs = 0
    focus_procs: list = []
    check_problems: list = []
    tids: set = set()
    try:
        asm = timeline.assemble(
            [router_dir] + [rep_dirs[i] for i in sorted(rep_dirs)],
            trace_id=root_tid)
        n_timeline_procs = len(asm["procs"])
        tids = timeline.trace_ids_for_request(asm, rid)
        if tids != {root_tid}:
            violations.append(
                f"timeline records for {rid!r} resolve to trace ids "
                f"{sorted(map(str, tids))} != the one root")
        agg = asm["traces"].get(root_tid)
        focus_procs = sorted(agg["procs"]) if agg else []
        if len(focus_procs) < 2:
            violations.append(
                "the failover trace does not span 2 processes "
                f"(saw {focus_procs}) — no cross-process causal "
                "chain")
        if not asm["hops"]:
            violations.append(
                "no cross-process hop detected in the merged "
                "timeline")
        if not asm["critical_path"]:
            violations.append("empty cross-process critical path")

        trace_path = os.path.join(tel_root,
                                  "fleet_timeline.trace.json")
        timeline.write_perfetto(asm, trace_path)
        tl_record = timeline.as_record(asm, trace_file=trace_path)
        tl_path = os.path.join(tel_root, "fleet_timeline.json")
        with open(tl_path, "w") as f:
            json.dump(tl_record, f, indent=1, sort_keys=True)
            f.write("\n")
        for p in (tl_path, trace_path):
            probs = check_file(p)
            if probs:
                check_problems.extend(
                    f"{os.path.basename(p)}: {x}" for x in probs)
        if check_problems:
            violations.append(
                "analyze check rejected the timeline artifacts: "
                + "; ".join(check_problems))
    except (OSError, ValueError) as exc:
        violations.append(
            f"timeline assembly failed: {type(exc).__name__}: {exc}")

    record = {
        "kind": "tracing_smoke",
        "benchmark": "tracing_smoke",
        "n_ranks": cfg.replica_ranks,
        "replicas": cfg.n_replicas,
        "killed_replica": victim_index,
        "root_trace_id": root_tid,
        "failover_attempts": (failover.get("fleet", {})
                              .get("attempts")),
        "attempt_failed_records": len(failed),
        "timeline_processes": n_timeline_procs,
        "focus_trace_processes": focus_procs,
        "timeline": {k: tl_record.get(k)
                     for k in ("n_spans", "n_events", "n_traces",
                               "hops", "skew_bound_us")},
        "violations": violations,
        # Integer gates only: counts that depend on load/timing
        # (span totals, hop totals, skew) stay outside the signature.
        "counter_signature": {
            "signature_version": 1,
            "n_ranks": cfg.replica_ranks,
            "counters": {
                "replicas": cfg.n_replicas,
                "matches_cold": cold["matches"],
                "matches_warm": warm["matches"],
                "matches_failover": failover.get("matches", -1),
                "warm_new_traces": warm["new_traces"],
                "failover_trace_ids": len(tids),
                "failed_attempt_on_trace": int(bool(
                    failed and ring_tids == {root_tid})),
                "timeline_processes": n_timeline_procs,
                "focus_trace_processes": len(focus_procs),
            },
        },
    }
    if violations:
        record["workdir"] = workdir
        raise FleetSmokeError(
            "tracing smoke violations: " + "; ".join(violations),
            record)
    if workdir_owned:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    return record


def run_fleet_ha_smoke(args) -> dict:
    """The ``fleet_ha`` lane's acceptance protocol (docs/FLEET.md
    "Replication & HA"), end to end through subprocess replicas, the
    durable coord dir, and TWO router incarnations:

    1. K=2 registration: both replicas hold the table (directory +
       versioned manifest on disk), one append lands on both holders
       (generation 2 everywhere);
    2. probe-only cold/warm discipline: the warm repeat adds zero
       traces at the correct generation;
    3. ONE SCRIPTED HOLDER KILL (SIGKILL of the table's primary
       holder): the immediate probe-only repeat fails over to the
       surviving holder within the retry budget, oracle-equal;
    4. the replacement REBUILDS its image from the manifest
       (``rebuilding -> serving``), and a direct fenced replay on it
       answers warm — ZERO new traces at generation 2;
    5. ONE SCRIPTED ROUTER KILL: the standby takes the fenced lease,
       adopts the directory, binds the SAME advertised endpoint, and
       the client's reconnect+resend gets the pre-fault signature
       served warm at the correct generation;
    6. a post-takeover append still reaches BOTH holders (generation
       3), and the final probe-only join is oracle-equal over
       register + both deltas.

    Returns the JSON record (kind ``fleet_ha_smoke``) whose
    deterministic counter signature the perfgate lane gates against
    ``results/baselines/fleet_ha_smoke.json``.
    """
    import tempfile

    violations: list = []
    workdir_owned = args.persist_dir is None
    workdir = args.persist_dir or tempfile.mkdtemp(
        prefix="djtpu_fleet_ha_smoke_")
    cfg = FleetConfig(
        n_replicas=2,
        replica_ranks=args.replica_ranks,
        persist_dir=os.path.join(workdir, "programs"),
        history_dir=(args.history_dir
                     or os.path.join(workdir, "history")),
        coord_dir=os.path.join(workdir, "coord"),
        table_replication=2,
        # Request-path fault discovery, as in run_fleet_smoke: the
        # prober must not drain the victim before the failover
        # attempt counts are graded.
        probe_interval_s=max(args.probe_interval_s, 5.0),
        retry_budget=2,
        max_inflight_per_replica=args.max_inflight,
        spawn_timeout_s=args.spawn_timeout_s,
        lease_ttl_s=2.0,
        lease_renew_s=0.25,
        flight_recorder_path=args.flight_recorder_path,
    )
    factory = process_fleet_factory(cfg,
                                    platform=args.platform or "cpu")
    router = FleetRouter(factory, cfg)
    ha1 = RouterHA(router, owner="router-a")
    port = ha1.start_primary()
    # retries=8 spans the takeover gap (lease TTL + poll + settle +
    # bind) under the client's jittered exponential backoff.
    client = ServiceClient("127.0.0.1", port, retries=8)

    table = "ha_users"
    reg = {"op": "register", "name": table, "rows": 4096,
           "seed": 23, "rand_max": 8192, "unique_keys": True}
    delta = {"op": "append", "name": table, "rows": 512,
             "seed": 29, "rand_max": 8192}
    delta2 = {"op": "append", "name": table, "rows": 256,
              "seed": 31, "rand_max": 8192}
    q = {"op": "join", "table": table, "probe_nrows": 2048,
         "seed": 23, "selectivity": 0.4, "rand_max": 8192,
         "out_capacity_factor": 3.0}

    def oracle_matches(delta_specs):
        import pandas as pd

        from distributed_join_tpu.service.server import (
            _build_from_spec,
            _probe_from_spec,
        )

        base = _build_from_spec(reg)
        frames = [base.to_pandas()]
        frames += [_build_from_spec(d).to_pandas()
                   for d in delta_specs]

        class _Stub:
            wire_spec = {k: reg[k] for k in
                         ("rows", "seed", "rand_max", "unique_keys")
                         if reg.get(k) is not None}
            wire_build_keys = base.columns["key"]

        probe = _probe_from_spec(q, _Stub)
        return len(pd.concat(frames, ignore_index=True)
                   .merge(probe.to_pandas(), on="key"))

    standby_router = None
    ha2 = None
    crashed_primary = False
    try:
        # 1. replicated registration + append.
        r = client.send(reg)
        if not r.get("ok"):
            raise RuntimeError(f"register failed: {r}")
        reg_holders = r.get("fleet", {}).get("holders") or []
        reg_gen = int(r.get("generation", -1))
        if len(reg_holders) != 2:
            violations.append(
                f"register landed on {reg_holders}, wanted 2 "
                "holders")
        if reg_gen != 1:
            violations.append(
                f"register generation {reg_gen} != 1")
        a = client.send(delta)
        if not a.get("ok"):
            raise RuntimeError(f"append failed: {a}")
        append_gen = int(a.get("generation", -1))
        append_applied = a.get("fleet", {}).get("applied") or []
        if append_gen != 2:
            violations.append(f"append generation {append_gen} != 2")
        if len(append_applied) != 2:
            violations.append(
                f"append applied on {append_applied}, wanted both "
                "holders")

        # Durable artifacts on disk.
        man = load_table_manifest(cfg.coord_dir, table)
        if man is None:
            violations.append("no table manifest on disk")
        else:
            if man.get("generation") != 2:
                violations.append(
                    f"manifest generation {man.get('generation')} "
                    "!= 2")
            if len(man.get("deltas") or []) != 1:
                violations.append(
                    f"manifest holds {len(man.get('deltas') or [])} "
                    "delta(s), wanted 1")
            if not man.get("payload_digest"):
                violations.append("manifest missing payload_digest")
        dirdoc = load_router_directory(cfg.coord_dir)
        if dirdoc is None:
            violations.append("no router directory on disk")
        elif table not in (dirdoc.get("tables") or {}):
            violations.append(
                f"router directory does not list {table!r}")

        # 2. cold/warm probe-only discipline.
        expected2 = oracle_matches([delta])
        cold = client.send(q)
        if not cold.get("ok"):
            raise RuntimeError(f"cold probe-only join failed: "
                               f"{cold}")
        warm = client.send(q)
        if not warm.get("ok"):
            raise RuntimeError(f"warm probe-only join failed: "
                               f"{warm}")
        for name_, resp_ in (("cold", cold), ("warm", warm)):
            if resp_["matches"] != expected2:
                violations.append(
                    f"{name_} matches {resp_['matches']} != oracle "
                    f"{expected2}")
            gen_ = (resp_.get("resident") or {}).get("generation")
            if gen_ != 2:
                violations.append(
                    f"{name_} served at generation {gen_} != 2")
        if warm["new_traces"] != 0:
            violations.append(
                f"warm probe-only repeat traced "
                f"{warm['new_traces']} new program(s)")

        # 3. THE holder kill: SIGKILL the serving (primary) holder.
        victim_index = cold["fleet"]["replica"]
        router.replicas[victim_index].backend.kill()
        failover = client.send(q)
        if not failover.get("ok"):
            violations.append(
                f"probe-only failover was not served: {failover}")
        else:
            if failover["matches"] != expected2:
                violations.append(
                    f"failover matches {failover['matches']} != "
                    f"oracle {expected2}")
            if failover["fleet"]["replica"] == victim_index:
                violations.append(
                    "failover answered from the killed holder")
            if failover["fleet"]["attempts"] > cfg.retry_budget + 1:
                violations.append(
                    f"failover took "
                    f"{failover['fleet']['attempts']} attempts > "
                    f"budget {cfg.retry_budget + 1}")
            fgen = (failover.get("resident") or {}).get("generation")
            if fgen != 2:
                violations.append(
                    f"failover served at generation {fgen} != 2")

        # 4. replacement rebuild from the manifest -> serving, then
        # a direct FENCED replay answers warm at generation 2.
        if not router.wait_replaced(victim_index,
                                    timeout_s=cfg.spawn_timeout_s):
            violations.append(
                f"killed holder {victim_index} was not replaced "
                f"within {cfg.spawn_timeout_s}s")
        holder_ok = False
        deadline = time.monotonic() + cfg.spawn_timeout_s
        while time.monotonic() < deadline:
            tbl = router.stats()["tables"].get(table) or {}
            h = (tbl.get("holders") or {}).get(str(victim_index))
            if h and h["state"] == "serving" \
                    and h["generation"] == 2:
                holder_ok = True
                break
            time.sleep(0.2)
        if not holder_ok:
            violations.append(
                f"replacement holder {victim_index} never reached "
                "serving at generation 2")
        rebuilds = router.stats()["rebuilds_total"]
        if rebuilds < 1:
            violations.append("no rebuild counted")
        replay: dict = {}
        if holder_ok:
            direct = ServiceClient(
                *router.replicas[victim_index].addr(),
                timeout_s=120.0)
            try:
                replay = direct.send(
                    {**q, "min_generation": 2,
                     "request_id": "ha-smoke-replay"})
            finally:
                direct.close()
            if not replay.get("ok"):
                violations.append(
                    f"rebuilt holder refused the fenced replay: "
                    f"{replay}")
            else:
                if replay["matches"] != expected2:
                    violations.append(
                        f"rebuilt replay matches "
                        f"{replay['matches']} != oracle "
                        f"{expected2}")
                if replay["new_traces"] != 0:
                    violations.append(
                        "rebuilt holder was not warm: "
                        f"{replay['new_traces']} new trace(s) — "
                        "the shared persist dir must hand it the "
                        "probe-only program")
                rgen = (replay.get("resident")
                        or {}).get("generation")
                if rgen != 2:
                    violations.append(
                        f"rebuilt replay served at generation "
                        f"{rgen} != 2")

        # 5. THE router kill: crash the primary, standby takes over
        # the same advertised endpoint, the client resends.
        standby_router = FleetRouter(factory,
                                     dataclasses.replace(cfg))
        ha2 = RouterHA(standby_router, owner="router-b")
        ha2.start_standby()
        ha1.crash()
        crashed_primary = True
        if not ha2.took_over.wait(timeout=cfg.lease_ttl_s * 10
                                  + 30.0):
            raise RuntimeError(
                "standby router never took over the lease")
        # The resend rides ONE trace across the takeover
        # (docs/OBSERVABILITY.md "Distributed tracing"):
        # ServiceClient.send mints once per LOGICAL send, before its
        # reconnect loop, so every retry against the dead primary and
        # the attempt the standby finally serves carry this context.
        ha_ctx = tracectx.mint()
        after = client.send(tracectx.attach(
            {**q, "request_id": "ha-after-takeover"}, ha_ctx))
        after_tid = (after.get(tracectx.TRACE_FIELD)
                     or {}).get("trace_id")
        if after_tid != ha_ctx["trace_id"]:
            violations.append(
                "post-takeover resend left its original trace: "
                f"response trace {after_tid} != minted "
                f"{ha_ctx['trace_id']}")
        ha_ring = [r for r in
                   standby_router.recorder.snapshot()["records"]
                   if r.get("request_id") == "ha-after-takeover"]
        ha_ring_tids = {(r.get("trace") or {}).get("trace_id")
                        for r in ha_ring}
        if not ha_ring or ha_ring_tids != {ha_ctx["trace_id"]}:
            violations.append(
                "standby flight ring does not tie the takeover "
                f"resend to its trace: ids "
                f"{sorted(map(str, ha_ring_tids))} over "
                f"{len(ha_ring)} record(s), wanted exactly "
                f"{ha_ctx['trace_id']}")
        if not after.get("ok"):
            violations.append(
                f"post-takeover resend was not served: {after}")
        else:
            if after["matches"] != expected2:
                violations.append(
                    f"post-takeover matches {after['matches']} != "
                    f"oracle {expected2}")
            if after["new_traces"] != 0:
                violations.append(
                    "post-takeover repeat traced "
                    f"{after['new_traces']} new program(s)")
            agen = (after.get("resident") or {}).get("generation")
            if agen != 2:
                violations.append(
                    f"post-takeover served at generation {agen} "
                    "!= 2")
        st2 = standby_router.stats()
        if st2["router_role"] != "primary":
            violations.append(
                f"standby role after takeover is "
                f"{st2['router_role']!r}, wanted 'primary'")
        if st2["takeovers_total"] != 1:
            violations.append(
                f"takeovers_total {st2['takeovers_total']} != 1")

        # 6. the new primary still owns the table: append reaches
        # both holders; the final probe-only join is oracle-equal
        # over register + both deltas.
        a2 = client.send(delta2)
        if not a2.get("ok"):
            violations.append(f"post-takeover append failed: {a2}")
        post_gen = int(a2.get("generation", -1))
        post_applied = a2.get("fleet", {}).get("applied") or []
        if post_gen != 3:
            violations.append(
                f"post-takeover append generation {post_gen} != 3")
        if len(post_applied) != 2:
            violations.append(
                f"post-takeover append applied on {post_applied}, "
                "wanted both holders")
        expected3 = oracle_matches([delta, delta2])
        final = client.send(q)
        if not final.get("ok"):
            violations.append(f"final probe-only join failed: "
                              f"{final}")
        else:
            if final["matches"] != expected3:
                violations.append(
                    f"final matches {final['matches']} != oracle "
                    f"{expected3}")
            lgen = (final.get("resident") or {}).get("generation")
            if lgen != 3:
                violations.append(
                    f"final served at generation {lgen} != 3")

        prom = standby_router.prometheus_metrics()
        for needle in ("djtpu_fleet_rebuilds_total",
                       "djtpu_router_takeovers_total",
                       "djtpu_router_role",
                       'djtpu_fleet_resident_holders{table="'):
            if needle not in prom:
                violations.append(
                    f"prometheus exposition missing {needle}")
    finally:
        client.close()
        if ha2 is not None:
            try:
                ha2.stop(drain=False)
            except Exception:  # noqa: BLE001 - teardown boundary
                pass
        if not crashed_primary:
            try:
                ha1.crash()
            except Exception:  # noqa: BLE001 - teardown boundary
                pass
        # Reap every subprocess replica from BOTH router
        # incarnations (adopted AttachedReplica endpoints hold no
        # process handle — the originals do).
        seen: set = set()
        for rep_ in (list(router.replicas)
                     + list(getattr(standby_router, "replicas",
                                    None) or [])):
            if id(rep_.backend) in seen:
                continue
            seen.add(id(rep_.backend))
            try:
                rep_.backend.stop()
            except Exception:  # noqa: BLE001 - teardown boundary
                pass

    record = {
        "kind": "fleet_ha_smoke",
        "benchmark": "fleet_ha_smoke",
        "n_ranks": cfg.replica_ranks,
        "replicas": cfg.n_replicas,
        "table_replication": cfg.table_replication,
        "table": table,
        "matches_expected": expected2,
        "matches_expected_final": expected3,
        "killed_holder": victim_index,
        "failover_attempts": (failover.get("fleet", {})
                              .get("attempts")),
        "rebuilds_total": rebuilds,
        "takeovers_total": st2["takeovers_total"],
        "coord_dir": cfg.coord_dir,
        "violations": violations,
        "counter_signature": {
            "signature_version": 1,
            "n_ranks": cfg.replica_ranks,
            "counters": {
                "replicas": cfg.n_replicas,
                "table_replication": cfg.table_replication,
                "register_holders": len(reg_holders),
                "register_generation": reg_gen,
                "append_generation": append_gen,
                "append_applied": len(append_applied),
                "matches_cold": cold["matches"],
                "matches_warm": warm["matches"],
                "warm_new_traces": warm["new_traces"],
                "matches_failover": failover.get("matches", -1),
                "rebuilds_total": rebuilds,
                "rebuilt_replay_matches": replay.get("matches",
                                                     -1),
                "rebuilt_replay_new_traces": replay.get(
                    "new_traces", -1),
                "takeovers_total": st2["takeovers_total"],
                "matches_after_takeover": after.get("matches",
                                                    -1),
                "takeover_new_traces": after.get("new_traces",
                                                 -1),
                "post_takeover_append_generation": post_gen,
                "post_takeover_append_applied":
                    len(post_applied),
                "matches_final": final.get("matches", -1),
            },
        },
    }
    if violations:
        record["workdir"] = workdir
        raise FleetSmokeError(
            "fleet HA smoke violations: " + "; ".join(violations),
            record)
    if workdir_owned:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    return record


# -- CLI ---------------------------------------------------------------


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="router TCP port (0 = ephemeral; printed on "
                        "the 'listening' line)")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--replica-ranks", type=int, default=2,
                   help="mesh size of EACH replica (disjoint hosts "
                        "on hardware; per-process virtual CPU meshes "
                        "in tests)")
    p.add_argument("--platform", default=None,
                   help="forwarded to each replica (--platform cpu "
                        "for the CPU-mesh fleet)")
    p.add_argument("--persist-dir", default=None, metavar="DIR",
                   help="SHARED compiled-program dir: the fleet's "
                        "distribution tier (replacements restart "
                        "warm)")
    p.add_argument("--history-dir", default=None, metavar="DIR",
                   help="fleet-level per-request history store "
                        "(entries stamped with the serving replica)")
    p.add_argument("--probe-interval-s", type=float, default=1.0)
    p.add_argument("--probe-timeout-s", type=float, default=5.0)
    p.add_argument("--spawn-timeout-s", type=float, default=180.0)
    p.add_argument("--suspect-strikes", type=int, default=2)
    p.add_argument("--retry-budget", type=int, default=2,
                   help="bounded failover attempts per request "
                        "beyond the first")
    p.add_argument("--request-deadline-s", type=float, default=300.0)
    p.add_argument("--max-inflight", type=int, default=4,
                   help="per-replica inflight admission bound "
                        "(beyond it the router sheds with a "
                        "structured AdmissionError)")
    p.add_argument("--shed-p95-s", type=float, default=None)
    p.add_argument("--shed-qps", type=float, default=None)
    p.add_argument("--no-respawn", action="store_true",
                   help="drain faulted replicas but do not replace "
                        "them (debugging)")
    p.add_argument("--replica-arg", action="append", default=[],
                   metavar="ARG",
                   help="extra argv token forwarded to every "
                        "replica (repeatable)")
    p.add_argument("--flight-records", type=int, default=256)
    p.add_argument("--flight-recorder-path", default=None)
    p.add_argument("--smoke", action="store_true",
                   help="run the CI acceptance protocol (2-replica "
                        "CPU-mesh fleet, scripted replica kill, "
                        "oracle/drain/replace/shed gates) instead of "
                        "serving; JSON record on stdout")
    p.add_argument("--tenant-smoke", action="store_true",
                   help="run the multi-tenancy + autoscaling "
                        "acceptance protocol (two-tenant 2-replica "
                        "fleet: quota refusal, priority shed order, "
                        "autoscale spawn with warm-serve gate) "
                        "instead of serving; JSON record on stdout")
    p.add_argument("--tracing-smoke", action="store_true",
                   help="run the distributed-tracing acceptance "
                        "protocol (2-replica fleet with per-slot "
                        "telemetry dirs, scripted kill, one-trace "
                        "failover continuity, merged fleet timeline) "
                        "instead of serving; JSON record on stdout")
    p.add_argument("--ha-smoke", action="store_true",
                   help="run the replication/HA acceptance protocol "
                        "(K=2 resident table, scripted holder kill "
                        "with manifest rebuild, scripted router kill "
                        "with lease takeover) instead of serving; "
                        "JSON record on stdout")
    p.add_argument("--coord-dir", default=None, metavar="DIR",
                   help="SHARED durable coordination dir (table "
                        "manifests, router directory, router lease); "
                        "enables the HA tier when set")
    p.add_argument("--table-replication", type=int, default=1,
                   metavar="K",
                   help="resident-table replication factor: "
                        "register/append fan out to the first K live "
                        "replicas on the signature ring (1 = legacy "
                        "single-holder)")
    p.add_argument("--standby", action="store_true",
                   help="serve as a STANDBY router: poll the lease "
                        "in --coord-dir and take over the advertised "
                        "endpoint when the primary dies")
    p.add_argument("--router-id", default=None,
                   help="stable owner id stamped into the lease and "
                        "directory (default: fleet-<pid>)")
    p.add_argument("--lease-ttl-s", type=float, default=3.0)
    p.add_argument("--lease-renew-s", type=float, default=0.5)
    p.add_argument("--json-output", default=None)
    return p.parse_args(argv)


def main(argv=None) -> int:
    from distributed_join_tpu.benchmarks import report

    args = parse_args(argv)
    if args.tracing_smoke:
        try:
            record = run_tracing_smoke(args)
        except FleetSmokeError as exc:
            report("tracing smoke FAILED", exc.record,
                   args.json_output)
            print(str(exc), file=sys.stderr)
            return 1
        report(
            f"tracing smoke: {record['replicas']} replicas, kill -> "
            f"failover in {record['failover_attempts']} attempt(s) "
            f"sharing trace {record['root_trace_id'][:18]}, "
            f"{record['attempt_failed_records']} failed attempt(s) "
            "on-trace, timeline over "
            f"{record['timeline_processes']} process(es) with "
            f"{record['timeline']['hops']} hop(s)",
            record, args.json_output)
        return 0
    if args.ha_smoke:
        try:
            record = run_fleet_ha_smoke(args)
        except FleetSmokeError as exc:
            report("fleet HA smoke FAILED", exc.record,
                   args.json_output)
            print(str(exc), file=sys.stderr)
            return 1
        sig = record["counter_signature"]["counters"]
        report(
            f"fleet HA smoke: K={record['table_replication']} "
            f"holders, holder kill -> failover in "
            f"{record['failover_attempts']} attempt(s) + rebuild "
            f"warm ({sig['rebuilt_replay_new_traces']} traces), "
            f"router kill -> takeover #{record['takeovers_total']} "
            f"warm ({sig['takeover_new_traces']} traces)",
            record, args.json_output)
        return 0
    if args.tenant_smoke:
        try:
            record = run_tenant_smoke(args)
        except FleetSmokeError as exc:
            report("tenant smoke FAILED", exc.record,
                   args.json_output)
            print(str(exc), file=sys.stderr)
            return 1
        sig = record["counter_signature"]["counters"]
        report(
            f"tenant smoke: {record['replicas']} replicas + "
            f"{sig['autoscale_spawned']} autoscaled (warm-verified="
            f"{sig['autoscale_warm_verified']}), bronze quota-"
            f"refused={sig['bronze_quota_refused']} priority-shed="
            f"{sig['bronze_priority_shed']}, gold served exactly "
            "under the same pressure",
            record, args.json_output)
        return 0
    if args.smoke:
        try:
            record = run_fleet_smoke(args)
        except FleetSmokeError as exc:
            report("fleet smoke FAILED", exc.record,
                   args.json_output)
            print(str(exc), file=sys.stderr)
            return 1
        report(
            f"fleet smoke: {record['replicas']} replicas, kill -> "
            f"drained in {record['drained_after_s']}s, failover in "
            f"{record['failover_attempts']} attempt(s), replacement "
            "warm with 0 traces, "
            f"{record['burst_shed']} shed under synthetic overload",
            record, args.json_output)
        return 0

    cfg = FleetConfig(
        n_replicas=args.replicas,
        replica_ranks=args.replica_ranks,
        persist_dir=args.persist_dir,
        history_dir=args.history_dir,
        probe_interval_s=args.probe_interval_s,
        probe_timeout_s=args.probe_timeout_s,
        spawn_timeout_s=args.spawn_timeout_s,
        suspect_strikes=args.suspect_strikes,
        retry_budget=args.retry_budget,
        request_deadline_s=args.request_deadline_s,
        max_inflight_per_replica=args.max_inflight,
        shed_p95_s=args.shed_p95_s,
        shed_qps=args.shed_qps,
        respawn=not args.no_respawn,
        flight_records=args.flight_records,
        flight_recorder_path=args.flight_recorder_path,
        coord_dir=args.coord_dir,
        table_replication=args.table_replication,
        lease_ttl_s=args.lease_ttl_s,
        lease_renew_s=args.lease_renew_s,
        router_id=args.router_id,
    )
    router = FleetRouter(
        process_fleet_factory(cfg, platform=args.platform or "cpu",
                              extra_args=args.replica_arg),
        cfg)
    ha = None
    if args.standby:
        if not args.coord_dir:
            print("--standby requires --coord-dir", file=sys.stderr)
            return 2
        # Standby mode: no replicas are spawned here — on takeover
        # the fleet is ADOPTED from the durable directory and the
        # primary's advertised endpoint is re-bound.
        ha = RouterHA(router, host=args.host, port=args.port,
                      owner=args.router_id)
        ha.start_standby()
        print(f"join-fleet STANDBY watching lease in "
              f"{args.coord_dir}", flush=True)
    elif args.coord_dir:
        ha = RouterHA(router, host=args.host, port=args.port,
                      owner=args.router_id)
        port = ha.start_primary()
        print(f"join-fleet listening on {args.host}:{port} "
              f"({cfg.n_replicas} replicas x {cfg.replica_ranks} "
              f"ranks, K={cfg.table_replication}, leased primary)",
              flush=True)
    else:
        router.start()
        server, port = start_router_daemon(router, args.host,
                                           args.port)
        print(f"join-fleet listening on {args.host}:{port} "
              f"({cfg.n_replicas} replicas x "
              f"{cfg.replica_ranks} ranks)",
              flush=True)
    try:
        import signal

        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        while not stop.wait(0.5):
            if router.shutdown_requested.is_set():
                break
    except KeyboardInterrupt:
        pass
    finally:
        if ha is not None:
            ha.stop()
        else:
            server.shutdown()
            server.server_close()
            router.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
