"""The join program cache — compiled executables keyed by signature.

``make_distributed_join`` builds a fresh closure and a fresh ``jax.jit``
wrapper per call, so every query — and every rung of the capacity retry
ladder — re-traces and re-compiles before a single row moves
(``distributed_join.py``: "Every retry recompiles"). The serving answer
is the :class:`JoinProgramCache`: a canonical :class:`JoinSignature`
over everything that determines the compiled program (table schemas and
capacities, key columns, shuffle mode, over-decomposition, the full
capacity contract including the ladder rung's sizing, skew policy,
compression bits, telemetry/integrity switches) maps to ONE resident
executable. A repeat query is a dict lookup and a dispatch; a retry
rung whose sizing was seen before reuses its executable instead of
paying trace + compile again.

Two storage tiers:

- **memory** (always): signature -> :class:`CachedProgram`. A hit adds
  zero traces and zero compiles (tests/test_service.py locks the
  program count).
- **disk** (opt-in, ``persist_dir=``): the AOT path that
  ``scripts/check_overlap.py --aot-tpu`` proves out —
  ``jit(...).lower(...).compile()`` then
  ``jax.experimental.serialize_executable`` — writes each executable
  next to its canonical signature, so a RESTARTED server skips even
  the first trace. Executables are backend- and topology-bound; a blob
  that fails to load (new jaxlib, different mesh) silently falls back
  to a fresh trace — persistence is an optimization, never a
  correctness dependency.

The chipless AOT helper (:func:`aot_compile_chipless`) lives here too:
compiling the full join for a TPU topology this host does not have is
the same lower-and-compile path the persistence tier uses, and
``scripts/check_overlap.py`` is a thin wrapper over it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import inspect
import json
import os
import pickle
from typing import Callable, Optional

from distributed_join_tpu import telemetry
from distributed_join_tpu.parallel.communicator import Communicator
from distributed_join_tpu.parallel.distributed_join import (
    JOIN_METRICS_SHARDED_OUT,
    JOIN_SHARDED_OUT,
    make_join_step,
)

# Every make_join_step option participates in the signature, at its
# default when the caller did not pass it — derived from the function
# signature itself so a new knob can never silently alias two distinct
# programs to one cache entry.
_STEP_DEFAULTS = {
    name: p.default
    for name, p in inspect.signature(make_join_step).parameters.items()
    if p.default is not inspect.Parameter.empty
}

PROGRAM_SUFFIX = ".joinprog"


def _canon(v):
    """Hashable, JSON-stable form of one option value."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, dict):
        return tuple(sorted((str(k), _canon(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        return tuple(_canon(x) for x in v)
    # KernelConfig and friends: flat frozen dataclasses whose repr is
    # total — good enough to DISTINGUISH programs, which is all a
    # cache key must do.
    return repr(v)


def spec_digest(doc) -> str:
    """Stable content digest of a JSON-ish document (dicts, lists,
    scalars), through the same canonicalizer the program-cache
    signatures use. Table manifests (docs/FLEET.md) stamp their
    register spec / delta payloads with this so a rebuilt holder can
    prove it replayed the same rows the original held."""
    canon = _canon(doc)
    return hashlib.sha256(
        json.dumps(canon, sort_keys=True, default=str).encode()
    ).hexdigest()


def atomic_write_json(path: str, doc) -> str:
    """Crash-safe JSON write: tmp file + ``os.replace`` so a reader
    never observes a torn document. The same discipline the program
    persist tier and the join manifest use; table manifests and the
    router directory (service/fleet.py) share it."""
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return path


def _schema_of(table) -> tuple:
    """(name, dtype, trailing-dims) triples, name-sorted — the aval
    identity of a Table (or a Table of ShapeDtypeStructs) minus the
    shared row capacity, which is carried separately."""
    return tuple(sorted(
        (name, str(c.dtype), tuple(int(d) for d in c.shape[1:]))
        for name, c in table.columns.items()
    ))


@dataclasses.dataclass(frozen=True)
class JoinSignature:
    """The canonical identity of one compiled join program.

    Two calls with equal signatures compile to the same executable;
    two calls that could compile differently MUST differ somewhere in
    here. ``options`` is the name-sorted tuple of every
    ``make_join_step`` option (defaults filled in), so telemetry
    on/off, integrity on/off, shuffle mode, ladder-rung sizing, skew
    capacities and compression bits all key distinct entries.
    """

    n_ranks: int
    build_schema: tuple
    build_capacity: int
    probe_schema: tuple
    probe_capacity: int
    options: tuple
    # Slow-tier topology (hierarchical shuffle, docs/HIERARCHY.md):
    # two slice-splits of the same rank count compile DIFFERENT
    # routing programs, so the split is part of the program identity.
    # 1 = flat mesh.
    n_slices: int = 1

    @classmethod
    def of(cls, comm: Communicator, build, probe,
           **opts) -> "JoinSignature":
        unknown = set(opts) - set(_STEP_DEFAULTS)
        if unknown:
            raise TypeError(
                f"unknown join option(s) {sorted(unknown)}; the "
                "signature covers make_join_step's keywords"
            )
        merged = {**_STEP_DEFAULTS, **opts}
        return cls(
            n_ranks=comm.n_ranks,
            build_schema=_schema_of(build),
            build_capacity=int(
                next(iter(build.columns.values())).shape[0]),
            probe_schema=_schema_of(probe),
            probe_capacity=int(
                next(iter(probe.columns.values())).shape[0]),
            options=tuple(sorted(
                (name, _canon(v)) for name, v in merged.items()
            )),
            n_slices=int(getattr(comm, "n_slices", 1)),
        )

    def canonical(self) -> dict:
        """JSON-shaped form (what the on-disk blob binds to)."""
        return dataclasses.asdict(self)

    def digest(self) -> str:
        return hashlib.sha256(
            json.dumps(self.canonical(), sort_keys=True,
                       default=str).encode()
        ).hexdigest()


@dataclasses.dataclass
class CachedProgram:
    """One resident executable plus the call convention around it.

    ``raw`` is the dispatchable program — a ``jax.jit`` callable
    (trace tier), a ``jax.stages.Compiled`` (AOT tier), or a
    deserialized loaded executable (disk tier). ``with_aux`` marks the
    ``(JoinResult, Metrics)`` aux convention of the metrics/integrity
    programs; the wrapper re-attaches the host-side ``telemetry``
    attribute exactly as ``make_distributed_join`` does, so callers
    cannot tell a cached program from a fresh one.
    """

    signature: JoinSignature
    raw: Callable
    with_aux: bool
    source: str                  # "trace" | "disk"
    persisted: bool = False

    def __call__(self, *args):
        out = self.raw(*args)
        if not self.with_aux:
            return out
        res, metrics = out
        object.__setattr__(res, "telemetry", metrics)
        return res


class JoinProgramCache:
    """Executable cache for one communicator's mesh.

    The cache is keyed on :class:`JoinSignature` — never on table
    CONTENTS — so any stream of same-shaped queries shares one
    program. ``with_metrics=None`` resolves from the telemetry session
    exactly like ``make_distributed_join``, which means a session flip
    mid-stream keys a separate (instrumented) entry rather than
    silently reusing the seed program.

    Not thread-safe by itself; :class:`..server.JoinService` serializes
    access (one mesh executes one program at a time anyway).
    """

    def __init__(self, comm: Communicator,
                 persist_dir: Optional[str] = None,
                 max_entries: Optional[int] = None):
        from collections import OrderedDict

        self.comm = comm
        self.persist_dir = persist_dir
        # None = unbounded (library use); a long-lived server MUST
        # bound it — the wire lets every request choose its own table
        # shape, and each distinct shape is a resident executable.
        self.max_entries = max_entries
        self._entries = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.traces = 0
        self.disk_loads = 0
        self.disk_load_failures = 0
        self.disk_persists = 0
        self.lru_evictions = 0
        self.integrity_evictions = 0
        self.generation_evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def stats(self) -> dict:
        """Operator-facing occupancy + tier counters (the ``stats``
        wire op and Prometheus exposition surface every field —
        PR 6's LRU bound and disk tier are invisible otherwise)."""
        return {
            "entries": len(self._entries),
            "max_entries": self.max_entries,
            "occupancy": (round(len(self._entries) / self.max_entries,
                                4)
                          if self.max_entries else None),
            "hits": self.hits,
            "misses": self.misses,
            "traces": self.traces,
            "disk_loads": self.disk_loads,
            "disk_load_failures": self.disk_load_failures,
            "disk_persists": self.disk_persists,
            "lru_evictions": self.lru_evictions,
            "integrity_evictions": self.integrity_evictions,
            "generation_evictions": self.generation_evictions,
        }

    def signature(self, build, probe, with_metrics=None,
                  **opts) -> JoinSignature:
        """The signature :meth:`get` would key this call under (the
        ``with_metrics=None`` session resolution applied)."""
        if with_metrics is None:
            with_metrics = telemetry.enabled()
        return JoinSignature.of(self.comm, build, probe,
                                with_metrics=with_metrics, **opts)

    def get(self, build, probe, with_metrics=None, **opts):
        """Return ``(program, hit)`` for this build/probe shape and
        option set — tracing and compiling only on a cold miss."""
        if with_metrics is None:
            with_metrics = telemetry.enabled()
        sig = JoinSignature.of(self.comm, build, probe,
                               with_metrics=with_metrics, **opts)
        entry = self._entries.get(sig)
        if entry is not None:
            self.hits += 1
            self._entries.move_to_end(sig)
            return entry, True
        self.misses += 1
        entry = self._load_persisted(sig)
        if entry is None:
            entry = self._build(sig, build, probe,
                                dict(opts, with_metrics=with_metrics))
        self._admit_entry(sig, entry)
        return entry, False

    def _admit_entry(self, sig, entry) -> None:
        self._entries[sig] = entry
        if self.max_entries is not None \
                and len(self._entries) > self.max_entries:
            # Least-recently-USED memory eviction; the disk blob (if
            # any) stays, so a re-miss reloads instead of re-tracing.
            old_sig, _ = self._entries.popitem(last=False)
            self.lru_evictions += 1
            telemetry.event("program_cache_lru_evict",
                            digest=old_sig.digest()[:12],
                            entries=len(self._entries))

    def get_keyed(self, sig, builder, *, example_args=None,
                  with_aux: bool = False):
        """Generic admission for programs that are not a plain
        (build, probe) join — the resident prep/merge/probe-only
        programs of :mod:`..service.resident`. ``sig`` is any frozen
        signature object with ``digest()``/``canonical()`` (and a
        name-sorted ``options`` tuple when the program carries an aux
        Metrics output); ``builder()`` returns the dispatchable
        program on a cold miss. Same memory LRU + disk-AOT tiers and
        the same hit/miss/trace counters as :meth:`get`;
        ``example_args`` (the program's real call arguments) arms the
        persist tier's lower+compile."""
        entry = self._entries.get(sig)
        if entry is not None:
            self.hits += 1
            self._entries.move_to_end(sig)
            return entry, True
        self.misses += 1
        entry = self._load_persisted(sig)
        if entry is None:
            raw = builder()
            self.traces += 1
            telemetry.event("program_cache_trace",
                            digest=sig.digest()[:12],
                            entries=len(self._entries) + 1)
            persisted = False
            if self.persist_dir is not None and hasattr(raw, "lower") \
                    and example_args is not None:
                try:
                    compiled = self._aot_compile(raw, *example_args)
                    persisted = self._persist(sig, compiled)
                    raw = compiled
                except Exception as exc:  # pragma: no cover - backend-dependent
                    telemetry.event("program_cache_persist_failed",
                                    digest=sig.digest()[:12],
                                    error=f"{type(exc).__name__}: "
                                          f"{exc}")
            entry = CachedProgram(sig, raw, with_aux, "trace",
                                  persisted=persisted)
        self._admit_entry(sig, entry)
        return entry, False

    def predict_hit(self, digest: str) -> dict:
        """Cache-hit prediction for a plan digest (the ``explain``
        wire op's dry-run verdict): would this signature dispatch a
        resident executable, rehydrate a persisted blob, or pay a
        fresh trace? Read-only — never loads or traces."""
        # Snapshot before iterating: the explain wire op calls this
        # WITHOUT the service exec lock, and a concurrent join may be
        # inserting/evicting entries (dict-changed-size mid-iteration
        # otherwise; a momentarily stale verdict is fine, a crash not).
        resident = any(sig.digest() == digest
                       for sig in list(self._entries))
        persisted = bool(
            self.persist_dir is not None
            and os.path.exists(os.path.join(
                self.persist_dir, digest + PROGRAM_SUFFIX)))
        return {
            "resident": resident,
            "persisted": persisted,
            "would_trace": not (resident or persisted),
        }

    def evict(self, signature: JoinSignature,
              reason: str = "integrity") -> bool:
        """Drop one entry (memory AND its disk blob). The integrity
        retry rung uses this: a wire-corruption verdict taints the
        resident program — injected corruption is woven at trace time,
        so only a RE-trace is guaranteed to face a fresh schedule —
        and the corrupt-adjacent blob must not be reloaded either.
        ``reason="integrity"`` (the only production caller) is counted
        so operators can see taint-driven churn in ``stats``."""
        dropped = self._entries.pop(signature, None) is not None
        if self.persist_dir is not None:
            try:
                os.unlink(self._blob_path(signature))
                dropped = True
            except OSError:
                pass
        if dropped and reason == "integrity":
            self.integrity_evictions += 1
        elif dropped and reason == "generation":
            # A resident table's generation bump taints exactly the
            # probe-only entries compiled against the old build image
            # (service/resident.py) — counted so operators can see
            # delta-driven churn next to integrity churn.
            self.generation_evictions += 1
        return dropped

    def clear(self) -> None:
        self._entries.clear()

    # -- build + persistence tiers ------------------------------------

    def _build(self, sig: JoinSignature, build, probe,
               opts: dict) -> CachedProgram:
        with_aux = bool(opts.get("with_metrics")
                        or opts.get("with_integrity"))
        sharded_out = (JOIN_METRICS_SHARDED_OUT if with_aux
                       else JOIN_SHARDED_OUT)
        step = make_join_step(self.comm, **opts)
        raw = self.comm.spmd(step, sharded_out=sharded_out)
        self.traces += 1
        telemetry.event("program_cache_trace", digest=sig.digest()[:12],
                        entries=len(self._entries) + 1)
        persisted = False
        if self.persist_dir is not None and hasattr(raw, "lower"):
            # The AOT tier: lower+compile now (the jit wrapper would
            # have paid the same compile on first dispatch) and keep
            # the Compiled as the dispatch target so it can also be
            # serialized. Wrapped communicators whose spmd returns a
            # plain callable (fault injection) skip this tier.
            try:
                compiled = self._aot_compile(raw, build, probe)
                persisted = self._persist(sig, compiled)
                raw = compiled
            except Exception as exc:  # pragma: no cover - backend-dependent
                telemetry.event("program_cache_persist_failed",
                                digest=sig.digest()[:12],
                                error=f"{type(exc).__name__}: {exc}")
        return CachedProgram(sig, raw, with_aux, "trace",
                             persisted=persisted)

    def _blob_path(self, sig: JoinSignature) -> str:
        return os.path.join(self.persist_dir,
                            sig.digest() + PROGRAM_SUFFIX)

    @staticmethod
    def _aot_compile(raw, *example_args):
        """Lower+compile for the persistence tier with jax's OWN
        persistent compilation cache bypassed: an executable
        rehydrated from that cache serializes into a blob whose CPU
        object symbols are missing (observed on jaxlib 0.4.37 —
        deserialize fails with "Symbols not found"), so a
        self-contained blob needs a real compile."""
        import jax

        # jax memoizes the "is the persistent cache used" decision at
        # first compile (compilation_cache._cache_checked), so merely
        # clearing the config flag is not enough once any compile ran
        # warm — reset the cache module around this one compile, then
        # reset again so later compiles re-initialize with the
        # restored settings. Private API by necessity; if it moves,
        # the AttributeError lands in _build's persist guard and the
        # entry simply stays memory-tier.
        from jax._src import compilation_cache

        prev = jax.config.jax_compilation_cache_dir
        try:
            jax.config.update("jax_compilation_cache_dir", None)
            compilation_cache.reset_cache()
            return raw.lower(*example_args).compile()
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)
            compilation_cache.reset_cache()

    def _persist(self, sig: JoinSignature, compiled) -> bool:
        import jax
        from jax.experimental import serialize_executable

        blob = serialize_executable.serialize(compiled)
        # Loadability check NOW, not at restart: a blob that cannot
        # deserialize is a silent trace-per-restart, the exact cost
        # this tier exists to remove.
        serialize_executable.deserialize_and_load(*blob)
        os.makedirs(self.persist_dir, exist_ok=True)
        path = self._blob_path(sig)
        payload = {
            "signature": sig.canonical(),
            "backend": jax.default_backend(),
            "program": blob,
        }
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(payload, f)
        os.replace(tmp, path)
        self.disk_persists += 1
        return True

    def _load_persisted(self, sig: JoinSignature):
        if self.persist_dir is None:
            return None
        path = self._blob_path(sig)
        if not os.path.exists(path):
            return None
        import jax
        from jax.experimental import serialize_executable

        try:
            with open(path, "rb") as f:
                payload = pickle.load(f)
            if (payload.get("signature") != sig.canonical()
                    or payload.get("backend") != jax.default_backend()):
                # A foreign/stale blob degrading to a miss is still a
                # disk-tier event operators should see.
                self.disk_load_failures += 1
                return None
            raw = serialize_executable.deserialize_and_load(
                *payload["program"])
        except Exception as exc:
            # A stale blob (jaxlib bump, different device topology) is
            # a cache miss, not an outage.
            self.disk_load_failures += 1
            telemetry.event("program_cache_load_failed", path=path,
                            error=f"{type(exc).__name__}: {exc}")
            return None
        self.disk_loads += 1
        telemetry.event("program_cache_disk_load",
                        digest=sig.digest()[:12])
        with_aux = bool(dict(sig.options).get("with_metrics")
                        or dict(sig.options).get("with_integrity"))
        return CachedProgram(sig, raw, with_aux, "disk", persisted=True)


# -- chipless AOT (the check_overlap --aot-tpu path, factored) ---------


AOT_TOPOLOGY = "v5e:2x4"


def chipless_tpu_communicator(topology: str = AOT_TOPOLOGY):
    """A :class:`TpuCommunicator` over a CHIPLESS AOT topology — the
    terminal compiler needs device descriptions, not devices, so the
    full 8-rank join compiles (and serializes) on any host."""
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh

    from distributed_join_tpu.parallel.communicator import (
        TpuCommunicator,
    )

    topo = topologies.get_topology_desc(
        platform="tpu", topology_name=topology)
    devs = np.array(topo.devices)
    mesh = Mesh(devs.reshape(devs.size), ("ranks",))
    return TpuCommunicator(mesh=mesh)


def abstract_join_tables(comm, rows: int, payload: str = "payload"):
    """Abstract (ShapeDtypeStruct) build/probe Tables for AOT lowering:
    the int64 key + int64 payload layout of the generators, row-sharded
    over ``comm``'s mesh. ``rows`` is the GLOBAL row count."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_join_tpu.table import Table

    sh = NamedSharding(comm.mesh, P(comm.axis_name))

    def tbl(payload_name):
        return Table(
            {"key": jax.ShapeDtypeStruct((rows,), jnp.int64,
                                         sharding=sh),
             payload_name: jax.ShapeDtypeStruct((rows,), jnp.int64,
                                                sharding=sh)},
            jax.ShapeDtypeStruct((rows,), jnp.bool_, sharding=sh),
        )

    return tbl("build_" + payload), tbl("probe_" + payload)


def aot_compile_chipless(shuffle: str = "padded",
                         rows_per_rank: int = 65536,
                         over_decomposition: int = 2,
                         out_capacity_factor: float = 3.0,
                         topology: str = AOT_TOPOLOGY,
                         **opts):
    """Lower + compile the full distributed join for a chipless TPU
    topology and return the ``jax.stages.Compiled`` (``.as_text()`` is
    the scheduled HLO). This is the persistence tier's lower-and-
    compile path pointed at hardware the host does not have —
    ``scripts/check_overlap.py --aot-tpu`` is a thin wrapper."""
    from distributed_join_tpu.parallel.distributed_join import (
        make_distributed_join,
    )

    comm = chipless_tpu_communicator(topology)
    build, probe = abstract_join_tables(
        comm, rows_per_rank * comm.n_ranks)
    fn = make_distributed_join(
        comm, key="key", over_decomposition=over_decomposition,
        out_capacity_factor=out_capacity_factor, shuffle=shuffle,
        **opts)
    return fn.lower(build, probe).compile()
