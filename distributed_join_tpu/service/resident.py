"""Resident build tables — register once, serve probe-only joins.

Every request through the full pipeline re-partitions, re-shuffles,
and re-sorts BOTH tables, yet real serving traffic overwhelmingly
joins many small probes against a few large, slowly-changing build
tables (ROADMAP item 4) — ~2/3 of each request's work recomputes a
result that hasn't changed. This module makes that work resident:

- :func:`make_resident_prep_step` runs the expensive build-side 2/3
  ONCE — hash-partition into ``n_ranks`` buckets, all-to-all shuffle,
  key-sort the received rows into a valid-prefix **sorted run** — and
  the result stays on-device as one row-sharded :class:`~..table.
  Table` under a named handle with a monotonic **generation** stamp;
- :func:`~..parallel.distributed_join.make_probe_join_step` then
  serves repeat joins as probe-only programs: partition + shuffle +
  sort the probe side only, merge each batch against the resident
  run. Programs are cached under a :class:`ResidentSignature`
  extended with ``(handle, generation)`` — a warm probe-only repeat
  is a zero-trace dict-lookup dispatch, exactly like the full join's
  warm path (docs/SERVICE.md);
- streaming ingestion closes the loop LSM-style: :meth:`
  ResidentTableRegistry.append` lands a delta as a small sorted run
  (same prep program at the delta's slot shape), and a **maintenance
  pass** merges pending runs into the resident shards — a merge of
  PRE-sorted runs, the structure docs/ROOFLINE.md §8 names as the
  only regime where run-length effects pay ("the run-length effect
  pays only when data ARRIVES pre-bucketed"). XLA exposes no
  dedicated merge primitive, so the pass is expressed as concat +
  ``lax.sort`` over the two runs (§6: sort cost is run-length
  dominated, and a concat of two sorted runs is exactly two runs); a
  future Pallas merge-path kernel slots in behind the same step
  factory. Each generation bump evicts ONLY the probe-only cache
  entries compiled against the old build image
  (``JoinProgramCache.evict(reason="generation")``).

Integrity: registration and every append/merge are conservation-
checked — the global valid-row count AND an order-invariant key-hash
sum must survive the prep/merge program exactly, or the operation
refuses loudly (:class:`ResidentError`) and the handle is left
untouched (a failed merge poisons the handle instead of blessing
wrong rows — the chaos suite grades exactly this). Out-of-core paging
of resident shards larger than HBM is explicitly deferred to the
``out_of_core`` manifest machinery (ROADMAP item 4's tail).
"""

from __future__ import annotations

import dataclasses
import hashlib
import inspect
import json
import math
import threading
from typing import Optional, Sequence

import numpy as np

import jax.numpy as jnp
from jax import lax

from distributed_join_tpu import telemetry
from distributed_join_tpu.ops.hashing import hash_columns
from distributed_join_tpu.ops.join import _dtype_sentinel_max
from distributed_join_tpu.ops.partition import radix_hash_partition
from distributed_join_tpu.parallel.distributed_join import (
    DEFAULT_SHUFFLE_CAPACITY_FACTOR,
    JOIN_METRICS_SHARDED_OUT,
    JOIN_SHARDED_OUT,
    _batch_shuffle,
    make_probe_join_step,
    resolve_join_ladder,
)
# ONE canonicalizer set for every cache signature: resident and
# full-join programs share the JoinProgramCache, and a drifted
# canonical form would silently fork the keyspace.
from distributed_join_tpu.service.programs import _canon, _schema_of
from distributed_join_tpu.table import Table

RESIDENT_SCHEMA_VERSION = 1

# (Table row-sharded, rows psummed+replicated, digest replicated,
# overflow replicated) — the merge program's output spec; the prep
# program additionally returns the INPUT conservation pair (rows_in,
# digest_in) so the host check never materializes table shards
# (np.asarray on non-addressable shards crashes multi-controller).
MERGE_SHARDED_OUT = (False, True, True, True)
PREP_SHARDED_OUT = (False, True, True, True, True, True)

# make_probe_join_step's own keyword surface, defaults filled — the
# probe-only signature's option basis, derived from the function
# itself so a new knob can never alias two programs (the
# JoinSignature discipline, docs/SERVICE.md).
_PROBE_STEP_DEFAULTS = {
    name: p.default
    for name, p in inspect.signature(
        make_probe_join_step).parameters.items()
    if p.default is not inspect.Parameter.empty
}

# Sizing keys the CapacityLadder resolves that the probe-only step
# actually takes (the hh_* skew capacities are not part of the
# probe-only program).
_PROBE_SIZING_KEYS = (
    "shuffle_capacity_factor", "out_capacity_factor",
    "out_rows_per_rank", "compression_bits",
)


class ResidentError(RuntimeError):
    """A resident-table operation refused loudly: unknown/poisoned
    handle, schema mismatch, capacity overflow, or a conservation
    check failing on a prep/merge pass. Never a wrong answer."""


class StaleGenerationError(ResidentError):
    """Generation fence (docs/FAILURE_SEMANTICS.md, replication
    contract): this holder's image is at a LOWER generation than the
    fleet directory requires — it missed an ``append`` fan-out. A
    stale image would serve rows that silently exclude the missed
    delta, so probe-only work is refused loudly instead; the router
    treats the refusal as a failover signal and retries the request
    on an up-to-date holder."""


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m




@dataclasses.dataclass(frozen=True)
class ResidentSignature:
    """Canonical identity of one resident-subsystem program.

    ``kind`` is ``"prep"`` (build/delta preparation), ``"merge"``
    (the LSM maintenance pass), or ``"probe_join"`` (the serving
    path). Probe-join signatures additionally bind the HANDLE and its
    GENERATION, so the program cache, tuner, and history store all
    key the resident image a program was compiled against — a
    generation bump can never serve a stale executable (and the old
    generation's entries are evicted eagerly on top).
    """

    kind: str
    n_ranks: int
    build_schema: tuple
    build_capacity: int
    probe_schema: Optional[tuple]
    probe_capacity: Optional[int]
    handle: Optional[str]
    generation: Optional[int]
    options: tuple

    def canonical(self) -> dict:
        return dataclasses.asdict(self)

    def digest(self) -> str:
        return hashlib.sha256(
            json.dumps(self.canonical(), sort_keys=True,
                       default=str).encode()
        ).hexdigest()


# -- the in-program sorted-run machinery -------------------------------


def _key_sorted_prefix(table: Table, keys: Sequence[str]) -> Table:
    """Sort a (possibly interleaved-validity) table so its valid rows
    form a key-sorted prefix — the resident run layout. One
    value-carrying ``lax.sort``: keys (invalid rows masked to the
    dtype's max) + a validity tag as sort keys, every payload column
    riding as a value lane (ROOFLINE §1: value operands are ~free).
    The tag breaks sentinel collisions: a VALID row whose key equals
    the sentinel still sorts before every invalid row."""
    ops = []
    for k in keys:
        c = table.columns[k]
        ops.append(jnp.where(table.valid, c,
                             _dtype_sentinel_max(c.dtype)))
    tag = jnp.where(table.valid, jnp.int8(0), jnp.int8(1))
    payload = [n for n in table.column_names if n not in keys]
    vals = [table.columns[n] for n in payload]
    sorted_ = lax.sort((*ops, tag, *vals), num_keys=len(keys) + 1)
    cols = {k: sorted_[i] for i, k in enumerate(keys)}
    for i, n in enumerate(payload):
        cols[n] = sorted_[len(keys) + 1 + i]
    # Preserve the source column order (schema identity is
    # name-sorted anyway; this keeps to_pandas views stable).
    cols = {n: cols[n] for n in table.column_names}
    return Table(cols, sorted_[len(keys)] == jnp.int8(0))


def _run_accounting(comm, table: Table, keys: Sequence[str]):
    """(rows, key_digest): global valid-row count and order-invariant
    key-hash sum — the conservation pair every prep/merge pass must
    carry through exactly (a dropped, duplicated, or value-corrupted
    key row moves one of them)."""
    h = hash_columns([table.columns[k] for k in keys])
    digest = jnp.sum(jnp.where(table.valid, h, jnp.uint64(0)))
    rows = jnp.sum(table.valid.astype(jnp.int64))
    return comm.psum(rows), comm.psum(digest)


def make_resident_prep_step(comm, key="key",
                            resident_rows_per_rank: int = 0,
                            shuffle_capacity_factor: float =
                            DEFAULT_SHUFFLE_CAPACITY_FACTOR,
                            shuffle: str = "padded"):
    """The register/append preparation program: hash-partition the
    build-side shard into ``n_ranks`` buckets, shuffle, and key-sort
    the received rows into a valid-prefix run of
    ``resident_rows_per_rank`` capacity. Returns ``step(build_local)
    -> (resident_local, rows, key_digest, rows_in, digest_in,
    overflow)`` for ``comm.spmd(..., sharded_out=PREP_SHARDED_OUT)``
    — the INPUT pair is measured before the shuffle and the OUTPUT
    pair after the sort, so the caller's host-side equality check
    brackets exactly the data movement (and needs no table
    materialization — multi-controller safe)."""
    n = comm.n_ranks
    if shuffle not in ("padded", "ppermute"):
        raise ValueError(
            f"resident prep supports the padded/ppermute shuffles, "
            f"not {shuffle!r}")
    keys = [key] if isinstance(key, str) else list(key)

    def step(build_local: Table):
        for name, c in build_local.columns.items():
            if c.ndim != 1:
                raise TypeError(
                    f"resident column {name!r} is {c.ndim}-D; "
                    "resident tables cover scalar columns")
        b_rows = build_local.capacity
        in_rows, in_digest = _run_accounting(comm, build_local, keys)
        if n == 1:
            recv, ovf = build_local, jnp.bool_(False)
        else:
            b_cap = _round_up(
                int(math.ceil(b_rows / n * shuffle_capacity_factor)),
                8)
            with telemetry.span("partition"):
                pt = radix_hash_partition(build_local, keys, n)
            with telemetry.span("shuffle"):
                recv, ovf = _batch_shuffle(comm, pt, 0, n, b_cap,
                                           mode=shuffle)
        if recv.capacity > resident_rows_per_rank:
            raise ValueError(
                f"resident capacity {resident_rows_per_rank} below "
                f"the shuffle receive block {recv.capacity}")
        with telemetry.span("sort"):
            run = _key_sorted_prefix(
                recv.pad_to(resident_rows_per_rank), keys)
        rows, digest = _run_accounting(comm, run, keys)
        overflow = comm.psum(ovf.astype(jnp.int32)) > 0
        return run, rows, digest, in_rows, in_digest, overflow

    return step


def make_run_merge_step(comm, key="key"):
    """The LSM maintenance pass: merge one pending sorted run into
    the resident base run. ``step(base_local, run_local) ->
    (merged_local, rows, key_digest, overflow)`` — concat + one
    value-carrying sort over the two PRE-sorted runs (ROOFLINE §6/§8:
    the pre-bucketed regime), sliced back to the base capacity.
    Overflow fires when valid rows exceed the base capacity (rows
    would be silently lost otherwise — the caller refuses instead)."""
    keys = [key] if isinstance(key, str) else list(key)

    def step(base_local: Table, run_local: Table):
        base_cap = base_local.capacity
        merged = Table(
            {
                n: jnp.concatenate([base_local.columns[n],
                                    run_local.columns[n]])
                for n in base_local.column_names
            },
            jnp.concatenate([base_local.valid, run_local.valid]),
        )
        with telemetry.span("merge_sort"):
            sorted_ = _key_sorted_prefix(merged, keys)
        valid_total = jnp.sum(sorted_.valid.astype(jnp.int32))
        ovf = valid_total > base_cap
        out = Table(
            {n: c[:base_cap] for n, c in sorted_.columns.items()},
            sorted_.valid[:base_cap],
        )
        rows, digest = _run_accounting(comm, out, keys)
        overflow = comm.psum(ovf.astype(jnp.int32)) > 0
        return out, rows, digest, overflow

    return step


# -- the registry ------------------------------------------------------


class ResidentTable:
    """One registered build table's host-side handle: the resident
    on-device image plus its LSM state. Mutated only by the registry
    (under the owning service's exec lock)."""

    def __init__(self, name: str, keys: tuple, table: Table,
                 rows: int, key_digest: int, capacity_per_rank: int,
                 row_bytes: int, n_ranks: int):
        self.name = name
        self.keys = keys
        self.table = table              # device Table, row-sharded
        self.rows = rows                # global valid rows
        self.key_digest = key_digest    # order-invariant key-hash sum
        self.capacity_per_rank = capacity_per_rank
        self.row_bytes = row_bytes
        self.n_ranks = n_ranks
        self.generation = 1
        self.pending_runs: list = []    # (device Table, rows, digest)
        self.poisoned: Optional[str] = None
        self.joins_served = 0
        self.warm_joins = 0             # probe-only joins, 0 traces
        self.appends = 0
        self.merges = 0
        # Probe-only signatures cached against the CURRENT generation
        # (evicted on bump so only dependent entries churn).
        self.cached_sigs: set = set()
        # Wire-layer metadata (the daemon stashes the generator spec
        # and the base key column here so probe specs can draw hit
        # keys without regenerating the whole build per request).
        self.wire_spec: Optional[dict] = None
        self.wire_build_keys = None

    @property
    def bytes_resident(self) -> int:
        total = self.capacity_per_rank * self.n_ranks * self.row_bytes
        for run, _, _ in self.pending_runs:
            total += run.capacity * self.row_bytes
        return total

    def stats(self) -> dict:
        return {
            "rows": self.rows,
            "generation": self.generation,
            "capacity_per_rank": self.capacity_per_rank,
            "bytes_resident": self.bytes_resident,
            "pending_runs": len(self.pending_runs),
            "joins_served": self.joins_served,
            "warm_joins": self.warm_joins,
            "appends": self.appends,
            "merges": self.merges,
            "poisoned": self.poisoned,
            "key": list(self.keys),
        }


class ResidentTableRegistry:
    """Named resident build tables over one communicator's mesh.

    Thread-compatibility contract: like :class:`~.programs.
    JoinProgramCache`, the registry itself does not lock — the owning
    :class:`~.server.JoinService` serializes every mutating call on
    its exec lock (one mesh runs one program at a time anyway);
    library users on one thread need nothing.
    """

    def __init__(self, comm, cache=None, *, max_tables: int = 8,
                 capacity_factor: float = 1.5,
                 shuffle_capacity_factor: float =
                 DEFAULT_SHUFFLE_CAPACITY_FACTOR,
                 delta_slot_rows: int = 1024,
                 maintain_runs: int = 4,
                 prep_retries: int = 2):
        self.comm = comm
        self.cache = cache          # JoinProgramCache or None
        self.max_tables = int(max_tables)
        self.capacity_factor = float(capacity_factor)
        self.shuffle_capacity_factor = float(shuffle_capacity_factor)
        self.delta_slot_rows = int(delta_slot_rows)
        self.maintain_runs = int(maintain_runs)
        self.prep_retries = int(prep_retries)
        self._tables: dict = {}
        self._local_programs: dict = {}   # cache=None fallback tier
        self.registered = 0
        self.dropped = 0
        self.refused = 0
        self._lock = threading.Lock()     # protects the name table
                                          # only; programs/devices are
                                          # serialized by the caller

    # -- lookup --------------------------------------------------------

    def __contains__(self, name: str) -> bool:
        return name in self._tables

    def names(self):
        return sorted(self._tables)

    def peek(self, name: str) -> Optional[ResidentTable]:
        """The handle if registered (poisoned or not), else None —
        the bookkeeping view (:meth:`get` is the serving view and
        refuses poisoned handles loudly)."""
        return self._tables.get(name)

    def get(self, name: str) -> ResidentTable:
        handle = self._tables.get(name)
        if handle is None:
            self.refused += 1
            raise ResidentError(
                f"no resident table {name!r} (registered: "
                f"{self.names() or 'none'})")
        if handle.poisoned:
            self.refused += 1
            raise ResidentError(
                f"resident table {name!r} is poisoned "
                f"({handle.poisoned}); drop and re-register")
        return handle

    def stats(self) -> dict:
        tables = {n: h.stats() for n, h in sorted(self._tables.items())}
        return {
            "count": len(self._tables),
            "max_tables": self.max_tables,
            "bytes_resident": sum(h.bytes_resident
                                  for h in self._tables.values()),
            "generation_max": max(
                (h.generation for h in self._tables.values()),
                default=0),
            "probe_joins": sum(h.joins_served
                               for h in self._tables.values()),
            "warm_probe_joins": sum(h.warm_joins
                                    for h in self._tables.values()),
            "registered": self.registered,
            "dropped": self.dropped,
            "refused": self.refused,
            "tables": tables,
        }

    # -- program admission --------------------------------------------

    def _program(self, sig: ResidentSignature, builder,
                 example_args=None, with_aux: bool = False):
        """(program, hit) through the shared JoinProgramCache when the
        registry has one (LRU + disk tiers + trace accounting), else a
        plain local dict (library use)."""
        if self.cache is not None:
            return self.cache.get_keyed(sig, builder,
                                        example_args=example_args,
                                        with_aux=with_aux)
        entry = self._local_programs.get(sig)
        if entry is not None:
            return entry, True
        from distributed_join_tpu.service.programs import CachedProgram

        entry = CachedProgram(sig, builder(), with_aux, "trace")
        self._local_programs[sig] = entry
        return entry, False

    def _prep_program(self, schema: tuple, capacity: int, keys: tuple,
                      resident_rows: int, factor: float):
        sig = ResidentSignature(
            kind="prep", n_ranks=self.comm.n_ranks,
            build_schema=schema, build_capacity=capacity,
            probe_schema=None, probe_capacity=None,
            handle=None, generation=None,
            options=(("resident_rows_per_rank", resident_rows),
                     ("shuffle_capacity_factor", factor)),
        )

        def build():
            step = make_resident_prep_step(
                self.comm, key=list(keys),
                resident_rows_per_rank=resident_rows,
                shuffle_capacity_factor=factor)
            return self.comm.spmd(step, sharded_out=PREP_SHARDED_OUT)

        fn, _ = self._program(sig, build)
        return fn, sig

    def _evict_program(self, sig: ResidentSignature) -> None:
        """Drop a resident-subsystem program whose run failed a
        conservation check: corruption is woven at TRACE time (the
        same discipline as the full join's integrity eviction), so a
        clean re-run must re-trace, never reuse the tainted
        executable."""
        if self.cache is not None:
            self.cache.evict(sig, reason="integrity")
        else:
            self._local_programs.pop(sig, None)

    def _merge_program(self, schema: tuple, base_cap: int,
                       run_cap: int, keys: tuple):
        sig = ResidentSignature(
            kind="merge", n_ranks=self.comm.n_ranks,
            build_schema=schema, build_capacity=base_cap,
            probe_schema=schema, probe_capacity=run_cap,
            handle=None, generation=None, options=(),
        )

        def build():
            step = make_run_merge_step(self.comm, key=list(keys))
            return self.comm.spmd(step,
                                  sharded_out=MERGE_SHARDED_OUT)

        fn, _ = self._program(sig, build)
        return fn, sig

    # -- registration / ingestion -------------------------------------

    def _validate(self, table: Table, keys: tuple) -> None:
        for k in keys:
            if k not in table.columns:
                self.refused += 1
                raise ResidentError(f"key column {k!r} missing from "
                                    "the build table")
            if not jnp.issubdtype(table.columns[k].dtype, jnp.integer):
                self.refused += 1
                raise ResidentError(
                    f"resident key {k!r} must be an integer column "
                    f"(got {table.columns[k].dtype}); string/float "
                    "keys go through the full join")
        for name, c in table.columns.items():
            if c.ndim != 1 or name.endswith("#len"):
                self.refused += 1
                raise ResidentError(
                    f"resident column {name!r} is not a scalar "
                    "column; 2-D/string payloads go through the "
                    "full join")

    def _prep(self, table: Table, keys: tuple, resident_rows: int):
        """Run the prep program (escalating the shuffle factor on
        overflow, the ladder discipline) and conservation-check the
        result against the INPUT pair the program measured before the
        shuffle — replicated scalars only, so no table shard is ever
        materialized host-side (multi-controller safe). Returns
        (run_table, rows, digest, capacity_per_rank)."""
        n = self.comm.n_ranks
        padded = table.pad_to(_round_up(table.capacity, n))
        if hasattr(self.comm, "device_put_sharded"):
            padded = self.comm.device_put_sharded(padded)
        factor = self.shuffle_capacity_factor
        schema = _schema_of(padded)
        for attempt in range(self.prep_retries + 1):
            b_cap = _round_up(int(math.ceil(
                padded.capacity / n / n * factor)), 8) if n > 1 else 0
            rows_needed = max(n * b_cap, padded.capacity // n)
            cap = max(resident_rows, _round_up(rows_needed, 8))
            fn, sig = self._prep_program(schema, padded.capacity,
                                         keys, cap, factor)
            run, rows, digest, rows_in, digest_in, overflow = \
                fn(padded)
            if not bool(overflow):
                rows, expected_rows = int(rows), int(rows_in)
                digest = int(np.asarray(digest))
                expected_digest = int(np.asarray(digest_in))
                if rows != expected_rows or digest != expected_digest:
                    self.refused += 1
                    self._evict_program(sig)
                    raise ResidentError(
                        "prep conservation check failed: "
                        f"{rows} rows / digest {digest:#x} out vs "
                        f"{expected_rows} rows / digest "
                        f"{expected_digest:#x} in — refusing to "
                        "bless a corrupt resident image")
                return run, rows, digest, cap
            factor *= 2.0
        self.refused += 1
        raise ResidentError(
            f"prep shuffle overflowed after {self.prep_retries + 1} "
            f"factor escalations (final {factor:g}); the key "
            "distribution is too skewed for resident registration")

    def register(self, name: str, build: Table, key="key", *,
                 replace: bool = False) -> ResidentTable:
        """Run the build-side 2/3 once and hold the result resident
        under ``name``. Refuses an existing name unless ``replace``."""
        keys = (key,) if isinstance(key, str) else tuple(key)
        if self.comm.n_ranks > 1 \
                and getattr(self.comm, "n_slices", 1) > 1:
            # The prep step (and every probe-only join after it)
            # routes flat GLOBAL collectives — on a multi-slice mesh
            # that drags intra-slice traffic across DCN. Hierarchical
            # resident serving is a named ROADMAP leftover; refuse at
            # the registration chokepoint, never mis-route.
            self.refused += 1
            raise ResidentError(
                "resident tables are served by flat global "
                "collectives; hierarchical (multi-slice) probe-only "
                "serving is not implemented yet — register on a flat "
                "1-D communicator")
        if name in self._tables and not replace:
            self.refused += 1
            raise ResidentError(
                f"resident table {name!r} already exists "
                "(pass replace=True to re-register)")
        if name not in self._tables \
                and len(self._tables) >= self.max_tables:
            self.refused += 1
            raise ResidentError(
                f"{len(self._tables)} resident tables already held "
                f"(max_tables={self.max_tables}); drop one first")
        self._validate(build, keys)
        n = self.comm.n_ranks
        b_local = _round_up(build.capacity, n) // n
        # Headroom for future deltas: capacity_factor over the local
        # rows, floored at what one shuffle receive block needs.
        resident_rows = _round_up(
            int(math.ceil(b_local * self.capacity_factor)), 8)
        run, rows, digest, cap = self._prep(build, keys, resident_rows)
        row_bytes = sum(
            np.dtype(c.dtype).itemsize for c in build.columns.values())
        handle = ResidentTable(name, keys, run, rows, digest, cap,
                               row_bytes, n)
        old = self._tables.get(name)
        with self._lock:
            self._tables[name] = handle
        if old is not None:
            self._evict_generation(old)
        self.registered += 1
        telemetry.event("resident_register", table=name, rows=rows,
                        capacity_per_rank=cap,
                        bytes=handle.bytes_resident)
        return handle

    def append(self, name: str, delta: Table, *,
               maintain: Optional[bool] = None) -> ResidentTable:
        """Land ``delta`` as a small sorted run on ``name``'s LSM
        queue and bump the generation (the visible image changed).
        ``maintain=None`` merges when the queue reaches
        ``maintain_runs``; True forces a merge now; False only
        queues. Joins always see appended rows — the serving path
        merges any pending queue before dispatch (merge-on-read)."""
        handle = self.get(name)
        keys = handle.keys
        self._validate(delta, keys)
        if _schema_of(delta) != _schema_of(handle.table):
            self.refused += 1
            raise ResidentError(
                f"delta schema does not match resident table "
                f"{name!r} — refusing the append")
        # Fixed delta slot so repeat appends share one prep program
        # (and one merge program) regardless of the delta's exact
        # row count.
        n = self.comm.n_ranks
        slot = _round_up(max(delta.capacity, n), self.delta_slot_rows)
        run, rows, digest, _ = self._prep(
            delta.pad_to(slot), keys,
            _round_up(max(slot // n, 8), 8))
        handle.pending_runs.append((run, rows, digest))
        handle.appends += 1
        self._bump_generation(handle)
        telemetry.event("resident_append", table=name,
                        delta_rows=rows, generation=handle.generation,
                        pending_runs=len(handle.pending_runs))
        if maintain or (maintain is None and
                        len(handle.pending_runs) >= self.maintain_runs):
            self.maintain(name)
        return handle

    def maintain(self, name: str) -> int:
        """Merge every pending sorted run into ``name``'s resident
        base (one merge dispatch per run; warm after the first).
        Returns the number of runs merged. A failed conservation
        check or a capacity overflow POISONS the handle — the base
        image may be half-merged, and serving it would be a guess."""
        handle = self.get(name)
        merged = 0
        while handle.pending_runs:
            run, run_rows, run_digest = handle.pending_runs[0]
            fn, msig = self._merge_program(
                _schema_of(handle.table), handle.capacity_per_rank,
                run.capacity, handle.keys)
            out, rows, digest, overflow = fn(handle.table, run)
            if bool(overflow):
                handle.poisoned = (
                    f"maintenance overflow: merged rows exceed the "
                    f"resident capacity {handle.capacity_per_rank}"
                    "/rank")
                self.refused += 1
                raise ResidentError(
                    f"resident table {name!r}: {handle.poisoned} — "
                    "re-register with a larger capacity_factor")
            rows = int(rows)
            digest = int(np.asarray(digest))
            want_rows = handle.rows + run_rows
            want_digest = (handle.key_digest + run_digest) % (1 << 64)
            if rows != want_rows or digest != want_digest:
                handle.poisoned = (
                    f"merge conservation check failed ({rows} rows / "
                    f"digest {digest:#x} vs expected {want_rows} / "
                    f"{want_digest:#x})")
                self.refused += 1
                self._evict_program(msig)
                raise ResidentError(
                    f"resident table {name!r}: {handle.poisoned} — "
                    "refusing to bless a corrupt merge")
            handle.table = out
            handle.rows = rows
            handle.key_digest = digest
            handle.pending_runs.pop(0)
            handle.merges += 1
            merged += 1
        if merged:
            telemetry.event("resident_maintain", table=name,
                            runs_merged=merged, rows=handle.rows,
                            generation=handle.generation)
        return merged

    def drop(self, name: str) -> None:
        handle = self._tables.get(name)
        if handle is None:
            self.refused += 1
            raise ResidentError(f"no resident table {name!r}")
        with self._lock:
            del self._tables[name]
        self._evict_generation(handle)
        self.dropped += 1
        telemetry.event("resident_drop", table=name)

    def _bump_generation(self, handle: ResidentTable) -> None:
        self._evict_generation(handle)
        handle.generation += 1

    def _evict_generation(self, handle: ResidentTable) -> None:
        """Invalidate exactly the probe-only entries compiled against
        ``handle``'s current image (other handles' and other
        generations' entries are untouched)."""
        if self.cache is not None:
            for sig in handle.cached_sigs:
                self.cache.evict(sig, reason="generation")
        else:
            # The cache-less local tier leaks otherwise: old-
            # generation signatures can never match again (the sig
            # embeds the generation) but would stay resident forever.
            for sig in handle.cached_sigs:
                self._local_programs.pop(sig, None)
        handle.cached_sigs = set()

    # -- the serving path ---------------------------------------------

    # NOTE on generation keying: today's probe-only programs close
    # over NO image-derived values (schema, capacity_per_rank, and
    # every option are generation-invariant), so binding the
    # generation into the cache key costs one re-trace per append per
    # probe shape that strictly-shape keying would avoid. The keying
    # is kept anyway as the conservative contract (docs/SERVICE.md):
    # the moment a future program DOES bake image state in (e.g.
    # sizing derived from handle.rows), strict keying is what stops a
    # stale executable from silently serving — and the eager
    # `evict(reason="generation")` keeps the churn visible.
    def probe_signature(self, handle: ResidentTable, probe: Table,
                        opts: dict) -> ResidentSignature:
        merged = {**_PROBE_STEP_DEFAULTS, **opts}
        unknown = set(merged) - set(_PROBE_STEP_DEFAULTS)
        if unknown:
            raise TypeError(
                f"unknown probe-only option(s) {sorted(unknown)}; "
                "the signature covers make_probe_join_step's keywords")
        return ResidentSignature(
            kind="probe_join", n_ranks=self.comm.n_ranks,
            build_schema=_schema_of(handle.table),
            build_capacity=handle.capacity_per_rank * self.comm.n_ranks,
            probe_schema=_schema_of(probe),
            probe_capacity=probe.capacity,
            handle=handle.name, generation=handle.generation,
            options=tuple(sorted(
                (name, _canon(v)) for name, v in merged.items())),
        )

    def workload_signature(self, name: str, probe: Table,
                           opts: dict) -> str:
        """The rung-stable workload identity of a probe-only join —
        GENERATION-FREE on purpose: appends move the data, not the
        workload, so the tuner's per-signature sizing history
        survives delta merges (the program cache still keys the
        generation; see :meth:`probe_signature`). ``with_metrics`` is
        stripped: callers hold it at different layers (the service
        passes it through opts, the registry takes it as a keyword),
        and the history's writer and reader must never key apart."""
        basis = json.dumps(
            {"handle": name, "n_ranks": self.comm.n_ranks,
             "probe": _schema_of(probe),
             "probe_capacity": probe.capacity,
             "opts": sorted((k, repr(v)) for k, v in opts.items()
                            if k != "with_metrics")},
            sort_keys=True, default=str)
        return "res-" + hashlib.sha256(basis.encode()).hexdigest()[:13]

    def join(self, name: str, probe: Table, *, auto_retry: int = 2,
             tuner=None, with_metrics=None, explain: bool = False,
             verify_integrity: bool = False, **opts):
        """One probe-only join against resident table ``name``: merge
        any pending delta runs first (so every join sees every
        append), then partition/shuffle/sort the probe side only and
        merge against the resident runs, through the program cache —
        the warm repeat is a dict lookup + dispatch with zero traces.
        Returns the :class:`~..ops.join.JoinResult` with the usual
        host-side ``retry_report`` (probe-side capacity ladder) and a
        ``resident`` record attribute.

        ``verify_integrity``: in-graph wire digests over the
        probe-side shuffle (``make_probe_join_step(with_integrity=)``)
        verified host-side before returning — the full join's
        integrity contract on the probe-only dispatch. A mismatch is
        the RETRYABLE ``retry_integrity`` rung: the tainted probe-only
        program is evicted and the SAME sizing re-traced (transport
        corruption is transient, capacities are innocent) up to the
        ``auto_retry`` budget, then
        :class:`~..parallel.integrity.IntegrityError` instead of
        corrupt rows. A verified clean result carries
        ``res.integrity_report``."""
        handle = self.get(name)
        # The workload signature is hashed FIRST — on the unpadded
        # probe and unmutated opts, the exact basis JoinService keys
        # its history entries on (resident_join hashes before
        # dispatch) — so the tuner's lookup and the store's writer
        # can never key apart.
        wsig = self.workload_signature(name, probe, opts)
        if opts.pop("skew_threshold", None) is not None or any(
                opts.get(k) is not None for k in
                ("hh_build_capacity", "hh_probe_capacity",
                 "hh_out_capacity")):
            self.refused += 1
            raise ResidentError(
                "the skew sidecar is not part of the probe-only "
                "program; run skewed workloads through the full join")
        opts.pop("hh_slots", None)
        if self.maintain(name):
            handle = self.get(name)
        if with_metrics is None:
            with_metrics = telemetry.enabled()
        n = self.comm.n_ranks
        probe = probe.pad_to(_round_up(probe.capacity, n))
        if hasattr(self.comm, "device_put_sharded"):
            probe = self.comm.device_put_sharded(probe)

        tuned = None
        if tuner is not None:
            tuned = tuner.resolve_resident(
                self.comm, handle.capacity_per_rank, probe,
                signature=wsig, opts=opts)
            opts = tuned.apply(opts)
        ladder = resolve_join_ladder(
            handle.table, probe, n, opts,
            n_slices=getattr(self.comm, "n_slices", 1))
        if tuned is not None:
            ladder.seed_rung(tuned.rung)
        key_opt = list(handle.keys) if len(handle.keys) > 1 \
            else handle.keys[0]
        from distributed_join_tpu.parallel import integrity

        with_aux = bool(with_metrics or verify_integrity)
        for attempt in range(auto_retry + 1):
            rung = ladder.base_rung + attempt
            sizing = {k: v for k, v in ladder.sizing().items()
                      if k in _PROBE_SIZING_KEYS}
            step_opts = dict(opts, key=key_opt,
                             with_metrics=with_metrics,
                             with_integrity=verify_integrity,
                             metrics_static={"retry_attempt_max": rung},
                             **sizing)
            sig = self.probe_signature(handle, probe, step_opts)

            def build(step_opts=step_opts):
                step = make_probe_join_step(self.comm, **step_opts)
                sharded = (JOIN_METRICS_SHARDED_OUT if with_aux
                           else JOIN_SHARDED_OUT)
                return self.comm.spmd(step, sharded_out=sharded)

            fn, hit = self._program(
                sig, build, example_args=(handle.table, probe),
                with_aux=with_aux)
            handle.cached_sigs.add(sig)
            with telemetry.span("resident_join", table=name,
                                generation=handle.generation) as sp:
                res = fn(handle.table, probe)
                if sp is not None:
                    sp.sync_on(res.total)
            overflow = bool(res.overflow)
            report = None
            if verify_integrity and not overflow:
                # Overflow attempts skip verification (clamped rows
                # mismatch by design; the overflow rung handles it) —
                # the full join's discipline, verbatim.
                report = integrity.verify_join_result(res)
            ladder.note(overflow,
                        integrity_ok=None if report is None
                        else report.ok)
            failed = overflow or (report is not None
                                  and not report.ok)
            if attempt == auto_retry or not failed:
                if report is not None and not report.ok:
                    # Budget exhausted on a wire mismatch: the
                    # resident program is as tainted as a retried one
                    # — evict it, then refuse loudly rather than
                    # handing corrupt rows to a probe-only caller.
                    self._evict_program(sig)
                    handle.cached_sigs.discard(sig)
                    raise integrity.IntegrityError(report)
                handle.joins_served += 1
                if hit:
                    handle.warm_joins += 1
                object.__setattr__(res, "retry_report",
                                   ladder.report())
                if report is not None:
                    object.__setattr__(res, "integrity_report",
                                       report)
                object.__setattr__(res, "resident", {
                    "table": name,
                    "generation": handle.generation,
                    "rows": handle.rows,
                    "warm": bool(hit),
                })
                if tuned is not None:
                    object.__setattr__(res, "tuned",
                                       tuned.as_record())
                if explain:
                    from distributed_join_tpu import planning

                    object.__setattr__(res, "plan", planning.
                                       build_probe_plan(
                        self.comm, handle.table, probe,
                        key=key_opt, digest=sig.digest(),
                        with_metrics=with_metrics,
                        **dict(opts, **sizing)))
                telemetry.emit_metrics(getattr(res, "telemetry",
                                               None))
                return res
            if overflow:
                ladder.escalate()
            else:
                # Integrity mismatch: rerun the SAME sizing — the
                # rows were wrong, not too many. The tainted entry is
                # evicted first so the rerun re-traces (injected
                # corruption is woven at trace time; a resident
                # executable that delivered corrupt rows must not
                # keep serving).
                self._evict_program(sig)
                handle.cached_sigs.discard(sig)
                ladder.hold("retry_integrity")
        raise AssertionError("unreachable")
