"""Micro-batching: K small joins as ONE padded SPMD step.

A small join through the service pays the full dispatch cost — host
padding, device placement, program launch, the shuffle collectives'
fixed latency — for a few thousand rows of work. Serving traffic is
full of such queries, and the north star names the fix: "batching of
small joins into one SPMD step". This module implements it:

- :func:`combine` packs K same-schema requests into one build/probe
  pair: each request padded to a uniform per-request slot (so the
  combined shape — and therefore the cached program — depends only on
  (slot, K), not on which requests arrived), plus a ``#batch``
  int32 segment column on both sides;
- the segment column rides as an EXTRA KEY COLUMN (the composite-key
  machinery of ``ops/join.py``), so two rows are join-equal only when
  their keys match AND they belong to the same request — **matches can
  never cross requests**, even under adversarial key collisions
  (tests/test_service.py grades exactly that against per-request
  pandas oracles);
- :func:`split` unpacks the settled result per request: the segment
  column comes back as a key column of the output, so per-request
  match counts (and rows) are one host-side bincount/filter.

The batch id could equally ride the key's high bits; a separate column
keeps ANY key dtype (including packed string keys) batchable and costs
4 bytes/row on the wire.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax.numpy as jnp

from distributed_join_tpu.table import Table

# NOT '__'-prefixed: the join reserves that namespace for its internal
# lanes; '#' keeps the name out of any user schema by convention (the
# strings layer's '#len' companions live there too).
SEGMENT_COLUMN = "#batch"


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@dataclasses.dataclass(frozen=True)
class MicroBatch:
    """One combined build/probe pair and the plan to unpack it."""

    build: Table
    probe: Table
    key: tuple                  # combined key, SEGMENT_COLUMN last
    n_requests: int
    slot_build_rows: int
    slot_probe_rows: int


def _check_uniform(tables: Sequence[Table], side: str) -> None:
    want = {n: (c.dtype, c.shape[1:])
            for n, c in tables[0].columns.items()}
    for i, t in enumerate(tables[1:], start=1):
        got = {n: (c.dtype, c.shape[1:]) for n, c in t.columns.items()}
        if got != want:
            raise ValueError(
                f"micro-batch {side} schemas differ: request 0 has "
                f"{sorted(want)}, request {i} has {sorted(got)} — a "
                "batch shares one compiled program, so every request "
                "must share one schema"
            )
    if SEGMENT_COLUMN in want:
        raise ValueError(
            f"{side} tables already carry {SEGMENT_COLUMN!r} — the "
            "segment column is batching-internal"
        )


def _stack(tables: Sequence[Table], slot: int) -> Table:
    padded = [t.pad_to(slot) for t in tables]
    cols = {
        name: jnp.concatenate([t.columns[name] for t in padded])
        for name in padded[0].column_names
    }
    # Padding rows carry their slot's segment id too, but their valid
    # bit is False — they can never join.
    cols[SEGMENT_COLUMN] = jnp.repeat(
        jnp.arange(len(tables), dtype=jnp.int32), slot)
    return Table(cols, jnp.concatenate([t.valid for t in padded]))


def combine(requests: Sequence, key="key", *,
            slot_build_rows=None, slot_probe_rows=None) -> MicroBatch:
    """Pack ``requests`` — a sequence of ``(build, probe)`` Table
    pairs joining on the same ``key`` — into one :class:`MicroBatch`.

    Slots default to the largest request (rounded up to 8); pass
    ``slot_*_rows`` explicitly to pin the combined shape across calls
    whose largest request varies, so they share one cached program.
    """
    if not requests:
        raise ValueError("micro-batch needs at least one request")
    builds = [b for b, _ in requests]
    probes = [p for _, p in requests]
    _check_uniform(builds, "build")
    _check_uniform(probes, "probe")
    keys = [key] if isinstance(key, str) else list(key)
    for kname in keys:
        if kname not in builds[0].columns \
                or kname not in probes[0].columns:
            raise ValueError(f"key column {kname!r} missing from the "
                             "batched tables")
    b_slot = _round_up(
        slot_build_rows or max(b.capacity for b in builds), 8)
    p_slot = _round_up(
        slot_probe_rows or max(p.capacity for p in probes), 8)
    if any(b.capacity > b_slot for b in builds) \
            or any(p.capacity > p_slot for p in probes):
        raise ValueError(
            f"a request exceeds the batch slot "
            f"(build {b_slot}, probe {p_slot} rows)"
        )
    return MicroBatch(
        build=_stack(builds, b_slot),
        probe=_stack(probes, p_slot),
        key=tuple(keys) + (SEGMENT_COLUMN,),
        n_requests=len(requests),
        slot_build_rows=b_slot,
        slot_probe_rows=p_slot,
    )


def split(res, batch: MicroBatch, with_rows: bool = False) -> list:
    """Unpack a settled batched :class:`JoinResult` per request.

    Returns one dict per request: ``matches`` (that request's match
    count), ``overflow`` (the SHARED flag — output capacity is pooled
    across the batch, so an overflow taints every request; the retry
    ladder has already escalated before a caller sees it), and — with
    ``with_rows`` — the request's output rows as host numpy columns
    (segment column dropped). Host-side by design: settle is where the
    result leaves the device anyway."""
    import numpy as np

    valid = np.asarray(res.table.valid)
    seg = np.asarray(res.table.columns[SEGMENT_COLUMN])
    counts = np.bincount(seg[valid], minlength=batch.n_requests)
    overflow = bool(res.overflow)
    out = []
    for i in range(batch.n_requests):
        entry = {"matches": int(counts[i]), "overflow": overflow}
        if with_rows:
            take = valid & (seg == i)
            entry["rows"] = {
                name: np.asarray(col)[take]
                for name, col in res.table.columns.items()
                if name != SEGMENT_COLUMN
            }
        out.append(entry)
    return out
