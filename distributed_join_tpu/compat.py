"""Version compatibility seams for the jax APIs this framework uses.

The framework is written against the current jax API surface
(``jax.shard_map``, ``jax.typeof``/vma, ``lax.pvary``); deployment
environments pin older toolchains where those names live elsewhere or
do not exist yet. Every call site goes through this module so the
fallback story is in ONE place:

- :func:`shard_map` — ``jax.shard_map`` when present, else the
  ``jax.experimental.shard_map`` original (same keyword signature for
  the subset used here: ``mesh`` / ``in_specs`` / ``out_specs``).
- :func:`typeof` — ``jax.typeof`` when present, else the abstract
  aval. Callers only ever probe ``getattr(typeof(x), "vma", None)``;
  pre-vma toolchains have no ``vma`` attribute on avals, so the probe
  degrades to None exactly as on a non-shard_map trace.
- :func:`pvary` — identity on toolchains without ``lax.pvary``
  (those also do not enforce varying-manual-axes, so identity is
  correct, not merely tolerated).
- :func:`enable_x64` — the scoped x64 context manager:
  ``jax.enable_x64`` when present, else the
  ``jax.experimental.enable_x64`` original (same semantics).
"""

from __future__ import annotations

import jax

try:  # jax >= 0.6
    _shard_map = jax.shard_map
    _legacy = False
except AttributeError:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _shard_map

    _legacy = True


def shard_map(fn, *, mesh, in_specs, out_specs):
    if _legacy:
        # The legacy replication checker mis-tracks psum'd loop
        # carries through lax.scan/fori_loop (the chained-iteration
        # timing protocol) — jax's own guidance is check_rep=False;
        # the modern vma path keeps full checking.
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)
    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs)


def enable_x64(new_val: bool = True):
    """Scoped x64-mode context manager (the Pallas wrappers trace
    their kernels under ``enable_x64(False)``)."""
    cm = getattr(jax, "enable_x64", None)
    if cm is not None:
        return cm(new_val)
    from jax.experimental import enable_x64 as _enable_x64

    return _enable_x64(new_val)


def typeof(x):
    """``jax.typeof`` with a pre-vma fallback; only meant for
    ``getattr(typeof(x), "vma", None)`` probes."""
    tf = getattr(jax, "typeof", None)
    if tf is not None:
        return tf(x)
    return jax.core.get_aval(x)


def pvary(x, axis_name):
    """``lax.pvary`` where it exists (idempotent — already-varying
    inputs pass through); identity on toolchains without vma
    tracking."""
    from jax import lax

    pv = getattr(lax, "pvary", None)
    if pv is None:
        return x
    vma = getattr(typeof(x), "vma", None) or frozenset()
    if axis_name in vma:
        return x
    return pv(x, axis_name)
