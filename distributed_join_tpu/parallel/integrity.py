"""Wire-integrity verification: in-graph shuffle checksums.

The reference trusts its transport — exact-size NCCL/UCX buffers mean
a delivered shuffle is assumed correct (SURVEY.md §2) — and the TPU
port inherited that assumption: PR 1's fault layer can *re-run* a join
but cannot tell whether a completed join delivered the right rows.
This module closes that gap with order-invariant per-(src-rank,
dst-rank) payload digests computed INSIDE the compiled SPMD step:

- every sender digests the rows it routes to each destination
  (:func:`padded_block_digests` over the padded layout,
  :func:`segment_digests` over the ragged bucket layout);
- every receiver digests the rows it believes it received from each
  source, using its own (possibly corrupted) counts/plan — so a
  truncated, duplicated, bit-flipped, or misrouted delivery disagrees
  with what the sender committed to;
- the 2n per-side digests ride the existing :class:`telemetry.Metrics`
  all_gather at step end (``<side>.integrity.sent_to_j`` /
  ``recv_from_j`` names) — no extra collective, no host callback, and
  telemetry-off + integrity-off remains the exact seed program;
- :func:`verify_digests` checks, host-side, that rank s's
  ``sent_to_d`` equals rank d's ``recv_from_s`` for every pair,
  producing a structured :class:`IntegrityReport`;
  ``distributed_inner_join(verify_integrity=True)`` raises
  :class:`IntegrityError` instead of returning corrupt rows.

Digest construction: per-row Murmur3-finalizer hash over every column
(``ops.hashing`` — the same primitives that route the rows), folded
once more through ``fmix64``, then SUMMED over the rows of each
(src, dst) bucket. Addition is commutative, so the digest is invariant
to row order (receivers repack rows) while any changed, missing,
duplicated, or foreign row shifts the sum. Digests are truncated to 63
bits so they travel exactly in the Metrics block's int64 lanes;
cross-batch accumulation wraps identically on both sides, so equality
is preserved end to end (collision odds ~2^-63 per pair).

Coverage contract (docs/FAILURE_SEMANTICS.md "Integrity contract"):
the digests cover the shuffle data plane — every row and byte that
rides ``all_to_all``/``ragged_all_to_all``, variable-width string
planes included. The skew path's heavy-hitter broadcast and the local
join itself are outside the checked channel, and verification is only
meaningful on a non-overflowed result (an overflow clamps rows by
design and already demands a retry).

Host mirrors (``*_np``) reuse :mod:`..out_of_core`'s numpy hash
mirrors so the chaos harness (:mod:`..chaos`) can oracle whole joined
tables without a device in the loop.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from distributed_join_tpu.ops.hashing import (
    fmix32,
    fmix64,
    hash_combine,
)

# Digests travel in the Metrics block's int64 lanes; the top bit is
# masked so unsigned->signed conversion is exact (equality survives
# identical int64 wrap on both sides across batch accumulation).
_FOLD63 = (1 << 63) - 1

_SENT_RE = re.compile(r"^(?P<channel>.+)\.integrity\.sent_to_(?P<dst>\d+)$")


class IntegrityError(RuntimeError):
    """A completed shuffle delivered rows that do not match what their
    senders committed to — corruption crossed the wire. Carries the
    structured :class:`IntegrityReport` as ``.report``. Distinct from
    overflow (a sizing problem a capacity retry fixes): retrying an
    integrity mismatch re-runs the SAME sizing, because the data was
    wrong, not small."""

    def __init__(self, report: "IntegrityReport"):
        self.report = report
        pairs = ", ".join(
            f"{m['channel']}[{m['src']}->{m['dst']}]"
            for m in report.mismatches[:4]
        )
        more = ("" if len(report.mismatches) <= 4
                else f" (+{len(report.mismatches) - 4} more)")
        super().__init__(
            f"wire integrity violated on {len(report.mismatches)} of "
            f"{report.checked_pairs} (src,dst) digest pairs: {pairs}"
            f"{more} — the shuffle delivered rows its senders did not "
            "send; do not trust this result"
        )


@dataclasses.dataclass(frozen=True)
class IntegrityReport:
    """Host-side verdict of one verified join/exchange.

    ``mismatches`` holds one dict per failed (src, dst) pair:
    ``{"channel", "src", "dst", "sent", "recv"}`` where ``channel`` is
    the digest namespace (``build``/``probe`` for the join shuffles).
    ``checked_pairs`` counts pairs compared; 0 means the program
    carried no digests (e.g. the single-rank path has no wire) and the
    report is vacuously ok."""

    ok: bool
    checked_pairs: int
    channels: tuple
    mismatches: tuple

    def as_record(self) -> dict:
        """JSON-shaped record drivers embed under ``"integrity"``."""
        return {
            "ok": self.ok,
            "checked_pairs": self.checked_pairs,
            "channels": list(self.channels),
            "mismatches": [dict(m) for m in self.mismatches],
        }


# -- device-side digests ----------------------------------------------


def _digest_column(col: jax.Array) -> jax.Array:
    """Per-row uint64 hash of one column (leading axis = rows).

    1-D columns reuse the join's own per-dtype hash dispatch; wider
    columns (fixed-width string bytes, packed word planes) fold every
    trailing lane through ``hash_combine`` — lane INDEX matters, so a
    byte moving within a row changes the digest."""
    from distributed_join_tpu.ops.hashing import _hash_one

    if col.ndim == 1:
        return _hash_one(col)
    flat = col.reshape(col.shape[0], -1)
    if flat.dtype == jnp.uint8 and flat.shape[1] % 4 == 0:
        # Byte columns fold 4x fewer lanes as u32 words.
        flat = lax.bitcast_convert_type(
            flat.reshape(col.shape[0], -1, 4), jnp.uint32
        )
    acc = None
    for w in range(flat.shape[1]):
        lane = flat[:, w]
        h = (fmix32(lane).astype(jnp.uint64)
             if lane.dtype.itemsize < 8 else fmix64(lane))
        # Mix the lane index in so transposed/shifted bytes differ.
        h = hash_combine(h, jnp.uint64(w + 1))
        acc = h if acc is None else hash_combine(acc, h)
    return fmix64(acc)


def row_digests(columns: dict) -> jax.Array:
    """(rows,) uint64 — one order-invariant-summable digest per row
    over EVERY column, combined in sorted-name order (both sides of an
    exchange hold the same column set, so the order is shared)."""
    acc = None
    for name in sorted(columns):
        h = _digest_column(columns[name])
        acc = h if acc is None else hash_combine(acc, h)
    return fmix64(acc)


def fold63(digest: jax.Array) -> jax.Array:
    """uint64 digest -> int64 metric lane (top bit masked; see module
    docstring for why equality survives the fold + wrap)."""
    return (digest & jnp.uint64(_FOLD63)).astype(jnp.int64)


def padded_block_digests(columns: dict, counts: jax.Array) -> jax.Array:
    """(n,) int64 digests of a padded (n, capacity, ...) block layout —
    entry j sums the per-row digests of block j's first ``counts[j]``
    rows (sender: rows routed to destination j; receiver: rows believed
    received from source j). Padding slots hold clipped-gather garbage
    and are excluded by the count mask."""
    n, capacity = next(iter(columns.values())).shape[:2]
    flat = {name: c.reshape((n * capacity,) + c.shape[2:])
            for name, c in columns.items()}
    rd = row_digests(flat).reshape(n, capacity)
    lane = jnp.arange(capacity, dtype=jnp.int32)
    valid = lane[None, :] < counts[:, None]
    return fold63(jnp.sum(jnp.where(valid, rd, jnp.uint64(0)), axis=1))


def masked_block_digests(columns: dict, row_valid: jax.Array
                         ) -> jax.Array:
    """(n,) int64 digests of an (n, capacity, ...) block layout under
    an EXPLICIT (n, capacity) validity mask — the segmented-sort
    shuffle's layout, where each peer block interleaves per-(segment)
    valid prefixes so a single per-block count cannot describe it
    (parallel/shuffle.shuffle_segmented). Same order-invariant sum as
    :func:`padded_block_digests`, same verify_digests contract."""
    n, capacity = next(iter(columns.values())).shape[:2]
    flat = {name: c.reshape((n * capacity,) + c.shape[2:])
            for name, c in columns.items()}
    rd = row_digests(flat).reshape(n, capacity)
    return fold63(jnp.sum(jnp.where(row_valid, rd, jnp.uint64(0)),
                          axis=1))


def segment_digests(digests: jax.Array, starts: jax.Array,
                    sizes: jax.Array) -> jax.Array:
    """(n,) int64 digests of n row segments ``[starts[j], starts[j] +
    sizes[j])`` of a flat per-row digest vector (the ragged layouts:
    sender buckets, receiver sender-blocks). Interval sums off one
    exclusive prefix sum; out-of-range segments clamp to empty."""
    rows = digests.shape[0]
    cs = jnp.concatenate(
        [jnp.zeros((1,), jnp.uint64), jnp.cumsum(digests)]
    )
    lo = jnp.clip(starts.astype(jnp.int32), 0, rows)
    hi = jnp.clip(
        starts.astype(jnp.int32) + sizes.astype(jnp.int32), lo, rows
    )
    return fold63(cs[hi] - cs[lo])


def record_pair_digests(digest_tape, sent: jax.Array,
                        recv: jax.Array) -> None:
    """Accumulate one exchange's per-peer digest vectors onto the
    metrics tape (``sent_to_j`` / ``recv_from_j`` under the tape's
    prefix). Sums across the k over-decomposition batches — the digest
    is order-invariant, so the per-(src,dst) check holds over the whole
    step."""
    n = sent.shape[0]
    for j in range(n):
        digest_tape.add(f"sent_to_{j}", sent[j])
        digest_tape.add(f"recv_from_{j}", recv[j])


# -- host-side verification -------------------------------------------


def verify_digests(metrics, channels: Optional[Sequence[str]] = None
                   ) -> IntegrityReport:
    """Check every ``<channel>.integrity.sent_to_d`` against its
    ``recv_from_s`` partner across the gathered per-rank metric block.

    ``metrics`` is a :class:`telemetry.Metrics` or its ``to_dict()``
    form (ONE host transfer either way). The invariant: the digest
    rank s reported for (s -> d) must equal the digest rank d reported
    for (s -> d); any disagreement means rows changed in flight —
    bit-flips, truncation, duplication, or misrouting, attributed to
    the exact (src, dst, channel) that disagreed."""
    d = metrics.to_dict() if hasattr(metrics, "to_dict") else metrics
    per_rank = d["per_rank"]
    n = int(d["n_ranks"])
    found = sorted({
        m.group("channel") for name in per_rank
        for m in (_SENT_RE.match(name),) if m is not None
    })
    if channels is not None:
        found = [c for c in found if c in set(channels)]
    mismatches = []
    checked = 0
    for channel in found:
        for src in range(n):
            for dst in range(n):
                sent = per_rank[
                    f"{channel}.integrity.sent_to_{dst}"][src]
                recv = per_rank[
                    f"{channel}.integrity.recv_from_{src}"][dst]
                checked += 1
                if sent != recv:
                    mismatches.append({
                        "channel": channel, "src": src, "dst": dst,
                        "sent": int(sent), "recv": int(recv),
                    })
    return IntegrityReport(
        ok=not mismatches,
        checked_pairs=checked,
        channels=tuple(found),
        mismatches=tuple(mismatches),
    )


def verify_join_result(res) -> IntegrityReport:
    """Verify a join result produced with ``with_integrity=True`` (its
    metrics block carries the digests as ``res.telemetry``)."""
    metrics = getattr(res, "telemetry", None)
    if metrics is None:
        raise ValueError(
            "result carries no metrics block — build the join with "
            "with_integrity=True (or verify_integrity=True on "
            "distributed_inner_join)"
        )
    return verify_digests(metrics)


# -- numpy mirror (chaos-harness oracle) ------------------------------


def row_digests_np(columns: dict):
    """numpy mirror of :func:`row_digests` — NOT used for wire
    verification (both ends of the wire digest on device); it lets the
    chaos harness compare a fetched join OUTPUT against the pandas
    oracle as an order-invariant multiset, catching payload corruption
    a match-count oracle would miss. Bit-exact with the device digest
    for integer and float32 columns (the f64 caveat of
    ``out_of_core._hash_one_np`` applies — chaos configs stay int)."""
    import numpy as np

    from distributed_join_tpu.parallel.out_of_core import (
        _hash_one_np,
        fmix32_np,
        fmix64_np,
        hash_combine_np,
    )

    acc = None
    for name in sorted(columns):
        col = np.asarray(columns[name])
        if col.ndim == 1:
            h = _hash_one_np(col)
        else:
            flat = col.reshape(col.shape[0], -1)
            if flat.dtype == np.uint8 and flat.shape[1] % 4 == 0:
                flat = flat.reshape(col.shape[0], -1, 4).view(
                    np.uint32).reshape(col.shape[0], -1)
            h = None
            for w in range(flat.shape[1]):
                lane = flat[:, w]
                lh = (fmix32_np(lane).astype(np.uint64)
                      if lane.dtype.itemsize < 8 else fmix64_np(lane))
                lh = hash_combine_np(lh, np.uint64(w + 1))
                h = lh if h is None else hash_combine_np(h, lh)
            h = fmix64_np(h)
        acc = h if acc is None else hash_combine_np(acc, h)
    return fmix64_np(acc)


def table_digest_np(columns: dict) -> int:
    """Order-invariant 63-bit multiset digest of a host table (dict of
    equal-length numpy columns) — the chaos oracle's comparison unit."""
    import numpy as np

    if not columns or next(iter(columns.values())).shape[0] == 0:
        return 0
    rd = row_digests_np(columns)
    return int(np.sum(rd, dtype=np.uint64) & np.uint64(_FOLD63))
