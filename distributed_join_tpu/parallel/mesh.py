"""Device-mesh construction helpers.

The reference binds one MPI rank to one GPU (``cudaSetDevice(local_rank)``,
SURVEY.md §3.1). On TPU the analogous object is a 1-D
``jax.sharding.Mesh`` over the chips: the mesh axis *is* the rank space,
and rows sharded along it are "owned" by a rank exactly as the
reference's per-rank table shards are.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

RANK_AXIS = "ranks"


def make_mesh(
    n_ranks: Optional[int] = None,
    axis_name: str = RANK_AXIS,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """1-D mesh over the first ``n_ranks`` devices (default: all)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_ranks is not None:
        if n_ranks > len(devs):
            raise ValueError(f"asked for {n_ranks} ranks, have {len(devs)} devices")
        devs = devs[:n_ranks]
    return Mesh(np.array(devs), (axis_name,))
