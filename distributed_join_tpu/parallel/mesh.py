"""Device-mesh construction helpers.

The reference binds one MPI rank to one GPU (``cudaSetDevice(local_rank)``,
SURVEY.md §3.1). On TPU the analogous object is a 1-D
``jax.sharding.Mesh`` over the chips: the mesh axis *is* the rank space,
and rows sharded along it are "owned" by a rank exactly as the
reference's per-rank table shards are.

Multi-slice scale-out (8 -> 64 chips, ROADMAP item 5) adds the 2-D
``(slice, chip)`` variant: :func:`make_hierarchical_mesh` groups the
devices by their real slice/process structure when the backend exposes
one (``slice_index`` on multi-slice TPU, ``process_index`` on
multi-host), so the fast mesh axis spans ICI and the slow axis spans
DCN — the topology the hierarchical shuffle
(:func:`..shuffle.shuffle_hierarchical`) routes over. CPU-mesh tests
fake the hierarchy with nested axes: the same 8 virtual devices
reshape to 2x4 and the routing algebra is identical.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

RANK_AXIS = "ranks"
SLICE_AXIS = "slices"


def make_mesh(
    n_ranks: Optional[int] = None,
    axis_name: str = RANK_AXIS,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """1-D mesh over the first ``n_ranks`` devices (default: all)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_ranks is not None:
        if n_ranks > len(devs):
            raise ValueError(f"asked for {n_ranks} ranks, have {len(devs)} devices")
        devs = devs[:n_ranks]
    return Mesh(np.array(devs), (axis_name,))


def device_slice_id(dev) -> int:
    """The slow-tier group a device belongs to: the TPU runtime's
    ``slice_index`` where it exists (multi-slice ICI domains), else
    the owning ``process_index`` (multi-host DCN domains), else 0
    (single-host CPU/TPU — no real slow tier; tests fake one with
    nested axes)."""
    v = getattr(dev, "slice_index", None)
    if v is not None:
        return int(v)
    return int(getattr(dev, "process_index", 0) or 0)


def make_hierarchical_mesh(
    n_slices: int,
    n_ranks: Optional[int] = None,
    devices: Optional[Sequence] = None,
    slice_axis: str = SLICE_AXIS,
    chip_axis: str = RANK_AXIS,
) -> Mesh:
    """2-D ``(slice, chip)`` mesh over the first ``n_ranks`` devices.

    Row ``t`` of the mesh is one slice's chips: the flat rank of
    device ``(t, j)`` is ``t * chips_per_slice + j`` (slice-major),
    which is exactly the rank order the 1-D :func:`make_mesh` would
    assign when the device list arrives slice-grouped — so a table
    row-sharded over the 2-D mesh shards identically to the flat one.

    Device grouping: when the devices expose a REAL slice/process
    structure (``device_slice_id``) with exactly ``n_slices`` equal
    groups, devices are ordered slice-major by it, so the chip axis
    spans ICI and the slice axis spans DCN. Otherwise (the CPU fake
    backend, or a deliberate re-split) the given device order is
    nested as-is — e.g. 8 virtual devices as 2x4 — which keeps the
    routing algebra testable without hardware.

    A slice count that does not divide the rank count is a loud
    structured refusal (a lopsided hierarchy would silently route
    rows to ranks that do not exist).
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_ranks is not None:
        if n_ranks > len(devs):
            raise ValueError(
                f"asked for {n_ranks} ranks, have {len(devs)} devices")
        devs = devs[:n_ranks]
    n = len(devs)
    if n_slices < 1:
        raise ValueError(f"n_slices must be >= 1, got {n_slices}")
    if n % n_slices:
        raise ValueError(
            f"n_slices={n_slices} does not divide the rank count {n}; "
            "a hierarchical mesh needs equal-size slices — pick a "
            "divisor (or drop --slices for the flat 1-D mesh)")
    chips = n // n_slices
    groups: dict = {}
    for d in devs:
        groups.setdefault(device_slice_id(d), []).append(d)
    if (len(groups) == n_slices
            and all(len(g) == chips for g in groups.values())):
        # Real topology: slice-major device order, ICI inside a row.
        devs = [d for sid in sorted(groups) for d in groups[sid]]
    return Mesh(np.array(devs).reshape(n_slices, chips),
                (slice_axis, chip_axis))
