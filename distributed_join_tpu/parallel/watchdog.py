"""Shared hang watchdog — bounded execution for host-side blocking calls.

A distributed join has three places where "it never came back" is a
live failure mode the retry machinery cannot see: backend/bootstrap
init (PJRT client init blocks forever when the TPU relay is down —
observed round 5), the out-of-core batch loop's per-batch scalar fetch
(a deadlocked collective never sequences), and a whole benchmark run
wedged inside any of the above. PR 1 solved the first with
``bootstrap.call_with_deadline``; this module is that watchdog
PROMOTED to a shared, domain-neutral seam (the bootstrap keeps its
``BootstrapError`` wrapper on top):

- :func:`call_with_deadline` runs ``fn()`` on a watchdog worker thread
  and raises a structured :class:`HangError` on timeout, emitting
  ``watchdog_armed`` / ``watchdog_timeout`` telemetry events
  (no-ops without a session — docs/OBSERVABILITY.md). The timed-out
  worker thread cannot be killed (CPython), but it IS detached from
  ``concurrent.futures``' atexit join so a wedged call can no longer
  hang interpreter shutdown.
- :func:`resolve_guard_deadline` is the one resolution of the
  benchmark-level deadline (``--guard-deadline-s`` flag, then the
  ``DJTPU_GUARD_DEADLINE_S`` env var, default None = unguarded —
  exactly the pre-existing behavior).
- :func:`shutdown_bounded` is the bounded worker-pool teardown the
  out-of-core error path uses instead of an unbounded atexit join
  (docs/FAILURE_SEMANTICS.md "Hang watchdog").
"""

from __future__ import annotations

import os
import time
import warnings
from typing import Callable, Optional

ENV_GUARD_DEADLINE = "DJTPU_GUARD_DEADLINE_S"

# How long the bounded teardown waits for a worker thread before
# declaring it wedged and detaching it from the atexit join.
DEFAULT_SHUTDOWN_TIMEOUT_S = 10.0


class HangError(RuntimeError):
    """A watchdogged call did not complete within its deadline — the
    structured form of "it hung". Distinct from an *error* return: the
    underlying work may still be running on its (now detached) worker
    thread, so the caller must treat any shared state it touches as
    poisoned."""

    def __init__(self, message: str, *, what: str = "guarded call",
                 deadline_s: Optional[float] = None):
        super().__init__(message)
        self.what = what
        self.deadline_s = deadline_s

    def record(self) -> dict:
        """JSON-shaped failure record (the watchdog analog of
        ``BootstrapError.record()``)."""
        return {
            "error": "HangError",
            "what": self.what,
            "deadline_s": self.deadline_s,
            "message": str(self),
        }


def _detach_from_atexit(thread) -> None:
    """Best-effort: remove ``thread`` from concurrent.futures' atexit
    join table so a wedged worker cannot hang interpreter shutdown.
    (CPython keeps the registry in a private dict; if the internals
    move, the worst case is the old behavior — a blocked exit.)"""
    try:
        from concurrent.futures import thread as _cft

        _cft._threads_queues.pop(thread, None)
    except Exception:  # pragma: no cover - interpreter-internal drift
        pass


def call_with_deadline(fn: Callable, deadline_s: float,
                       what: str = "guarded call"):
    """Run ``fn()`` under a watchdog thread; raise :class:`HangError`
    if it does not complete within ``deadline_s`` seconds.

    Exceptions raised by ``fn`` propagate unchanged (the watchdog
    bounds TIME, it does not reinterpret failures — callers with a
    domain error, like ``bootstrap.call_with_deadline``, wrap on top).
    On timeout the worker thread stays blocked inside ``fn`` — it is
    detached from the atexit join, and the caller decides whether the
    process can continue at all (a wedged thread may hold backend
    locks; benchmark drivers hard-exit after writing their record).
    """
    import concurrent.futures

    from distributed_join_tpu import telemetry

    telemetry.event("watchdog_armed", what=what,
                    deadline_s=float(deadline_s))
    ex = concurrent.futures.ThreadPoolExecutor(
        1, thread_name_prefix=f"watchdog-{what[:24]}")
    fut = ex.submit(fn)
    try:
        result = fut.result(timeout=deadline_s)
    except concurrent.futures.TimeoutError:
        for t in list(getattr(ex, "_threads", ())):
            _detach_from_atexit(t)
        telemetry.event("watchdog_timeout", what=what,
                        deadline_s=float(deadline_s))
        raise HangError(
            f"{what} did not complete within {deadline_s:g}s",
            what=what, deadline_s=float(deadline_s),
        ) from None
    finally:
        # On fn-raised exceptions the worker has already returned
        # (the raise IS its result), so the idle thread must be
        # released here too — only the timeout path above leaves its
        # (wedged, detached) worker behind.
        if not fut.cancelled() and fut.done():
            ex.shutdown(wait=False)
    return result


def resolve_guard_deadline(args=None) -> Optional[float]:
    """The benchmark-run guard deadline: ``--guard-deadline-s`` when
    the driver passed one, else ``DJTPU_GUARD_DEADLINE_S``, else None
    (unguarded — the historical behavior; a deadline is opt-in because
    a legitimate SF-100 run can take hours)."""
    flag = getattr(args, "guard_deadline_s", None) if args is not None \
        else None
    if flag is not None:
        return float(flag) if flag > 0 else None
    env = os.environ.get(ENV_GUARD_DEADLINE, "")
    if not env:
        return None
    val = float(env)
    return val if val > 0 else None


def shutdown_bounded(executor, what: str,
                     timeout_s: float = DEFAULT_SHUTDOWN_TIMEOUT_S) -> bool:
    """Shut an executor down with a BOUNDED join of its workers.

    ``ThreadPoolExecutor.shutdown(wait=False)`` does not join, but
    concurrent.futures' atexit hook joins every pool thread forever —
    so a worker wedged in a dead backend call turns "the run failed"
    into "the interpreter never exits" (the orphaned-worker risk noted
    in the out-of-core error path). This helper joins each worker for
    its slice of ``timeout_s``; a thread still alive after that is
    reported (``worker_shutdown_timeout`` telemetry event + warning)
    and detached from the atexit join so exit proceeds. Returns True
    when every worker exited cleanly."""
    from distributed_join_tpu import telemetry

    executor.shutdown(wait=False, cancel_futures=True)
    threads = list(getattr(executor, "_threads", ()))
    deadline = time.monotonic() + timeout_s
    clean = True
    for t in threads:
        t.join(max(0.0, deadline - time.monotonic()))
        if t.is_alive():
            clean = False
            _detach_from_atexit(t)
            telemetry.event("worker_shutdown_timeout", pool=what,
                            thread=t.name, timeout_s=float(timeout_s))
            warnings.warn(
                f"{what} worker {t.name!r} did not exit within "
                f"{timeout_s:g}s — detached from interpreter-exit "
                "join; treat its outputs as abandoned",
                stacklevel=2,
            )
    return clean
