"""Distributed layer: device mesh, communicator seam, shuffle, orchestrator.

This is the TPU re-design of the reference's layer 2 + 3 (SURVEY.md §1):
the ``Communicator`` plugin boundary and the ``distributed_inner_join``
orchestrator. Control plane = JAX distributed runtime / process bootstrap
(the reference uses MPI); data plane = XLA collectives over ICI (the
reference uses NCCL/UCX).
"""
