"""Heavy-hitter (skew) handling — BASELINE config 3.

Hash partitioning routes every row of one key to one rank, so a
Zipf-skewed key column (alpha=1.5 concentrates a large fraction of all
rows on a handful of keys) both overloads one rank and — under this
framework's static-shape padded shuffle — forces every (rank, bucket)
pad up to the hottest bucket's size (SURVEY.md §7 hard part #2). The
reference has no skew machinery (its exact-size buffers merely survive
skew without balancing it); this framework does better with the classic
PRPD scheme (partial redistribution / partial duplication), designed
here for static shapes:

  1. detect heavy-hitter keys on device: each rank counts its probe-side
     key runs (one sort + searchsorted), takes its local top-K, and the
     K-slot candidate lists are all-gathered and aggregated; keys whose
     (approximate) global count exceeds ``threshold`` become the
     replicated HH set — a fixed K-slot array, identically computed on
     every rank;
  2. probe rows with HH keys are EXCLUDED from the shuffle and stay on
     their generating rank — they are balanced by construction (the
     generator hashes nothing), and the hot bucket disappears from the
     padded all-to-all;
  3. build rows with HH keys are broadcast (all-gather of a fixed-slot
     block) to every rank — the build side of a hot key is small (unique
     or low-multiplicity build keys), so duplication is cheap;
  4. each rank joins its local HH probe rows against the replicated HH
     build block; results concatenate with the normal path's.

Every row of a key takes exactly one path (the HH set is replicated and
consistent), so no match is lost or duplicated. Detection is
approximate — a key spread thin below every rank's local top-K can be
missed — but classification consistency, not accuracy, is what
correctness needs; a missed moderately-hot key just pays normal-path
padding.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from jax import lax

from distributed_join_tpu.ops.join import _dtype_sentinel_max
from distributed_join_tpu.parallel.communicator import Communicator
from distributed_join_tpu.table import Table


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class HeavyHitters:
    """A fixed-K replicated set of heavy keys. Invalid slots hold the
    key dtype's max sentinel and are masked by ``slot_valid``."""

    keys: jax.Array        # (K,) key dtype
    counts: jax.Array      # (K,) int64 approximate global counts; when
    #                        sampled detection ran (the default), these
    #                        are sampled tallies scaled by ``sample`` —
    #                        ESTIMATES, not exact tallies.
    slot_valid: jax.Array  # (K,) bool


def _zipf_partial_sum(alpha: float, n: int) -> float:
    """sum_{r=1..n} r^-alpha — exact head, midpoint-integral tail
    (relative error < 1e-6 for the n up to 1e8 seen here)."""
    import numpy as np

    m = min(n, 1_000_000)
    s = float(np.sum(np.arange(1, m + 1, dtype=np.float64) ** -alpha))
    if n > m:
        if abs(alpha - 1.0) < 1e-9:
            s += math.log((n + 0.5) / (m + 0.5))
        else:
            s += (
                (m + 0.5) ** (1.0 - alpha) - (n + 0.5) ** (1.0 - alpha)
            ) / (alpha - 1.0)
    return s


def zipf_top_k_mass(alpha: float, n_keys: int, k: int) -> float:
    """Expected fraction of Zipf(``alpha``) draws over ``n_keys`` keys
    that land on the ``k`` most probable keys — the capacity model
    behind the driver's skew auto-policy (round 5): with the HH set
    sized ``k`` slots, ~this fraction of each rank's probe rows takes
    the HH path, so the HH probe/output blocks can be PRE-sized from a
    known alpha instead of overflowing into an auto_retry recompile
    (alpha >= 1.4 puts ~90% of rows in the top-64; the old p_rows/8
    default overflowed by design)."""
    if n_keys <= 0 or k <= 0:
        return 0.0
    return _zipf_partial_sum(alpha, min(k, n_keys)) / _zipf_partial_sum(
        alpha, n_keys
    )


def local_top_keys(keys: jax.Array, valid: jax.Array, k: int):
    """Per-shard top-``k`` keys by frequency: (keys, counts), padded
    slots carrying count 0. ONE single-operand sort; run lengths come
    from forward/backward scans over the change marks (round 1's two
    ``method="sort"`` searchsorteds re-sorted the shard twice more —
    measured 40x the sort itself at 10M rows on v5e). Always returns
    ``k`` slots even when the shard has fewer rows (extra slots pad)."""
    n = keys.shape[0]
    k_eff = min(k, n)  # lax.top_k rejects k > array length
    sentinel = _dtype_sentinel_max(keys.dtype)
    sk = lax.sort((jnp.where(valid, keys, sentinel),))[0]
    n_valid = jnp.sum(valid.astype(jnp.int32))
    iota = jnp.arange(n, dtype=jnp.int32)
    changed = sk != jnp.concatenate([sk[:1], sk[:-1]])
    changed = changed & (iota > 0)
    first = changed | (iota == 0)
    # next run start after i (exclusive) via a reverse cummin.
    nxt_incl = jnp.flip(lax.cummin(jnp.flip(
        jnp.where(changed, iota, jnp.int32(n))
    )))
    nxt = jnp.concatenate([nxt_incl[1:], jnp.full((1,), n, jnp.int32)])
    # Valid rows sort before the sentinel block; clamping the run end to
    # n_valid counts only real rows (a real key == sentinel undercounts
    # into the padding block — harmless for approximate detection).
    run = jnp.minimum(nxt, n_valid) - iota
    score = jnp.where(first & (iota < n_valid), run, 0)
    top_counts, top_idx = lax.top_k(score, k_eff)
    top_keys = jnp.where(top_counts > 0, sk[top_idx], sentinel)
    if k_eff < k:
        pad = k - k_eff
        top_keys = jnp.concatenate(
            [top_keys, jnp.full((pad,), sentinel, dtype=top_keys.dtype)]
        )
        top_counts = jnp.concatenate(
            [top_counts, jnp.zeros((pad,), dtype=top_counts.dtype)]
        )
    return top_keys, top_counts


def global_heavy_hitters(
    comm: Communicator,
    keys: jax.Array,
    valid: jax.Array,
    k: int,
    threshold,
    sample: int = 16,
) -> HeavyHitters:
    """Replicated global top-``k`` keys with aggregated count >
    ``threshold`` (a traced or static int). Aggregation is exact over
    the union of per-rank candidate lists (a key missing from some
    rank's list undercounts — see module docstring).

    ``sample``: detection runs on a 1/``sample`` quasi-random subset —
    a heavy key (count > threshold, typically >=0.1% of rows) appears
    hundreds of times in the sample, so detection stays robust while
    the top-keys sort drops from the full shard to n/sample rows (the
    sort WAS ~half the skew path's fixed overhead at 10M uniform
    rows). Rows are picked by a multiplicative index mix, NOT a fixed
    stride — ``keys[::16]`` would deterministically miss a heavy key
    living only at positions != 0 mod 16 (periodic layouts from
    round-robin re-partitions do exist; review r4). Counts and
    threshold are compared in sampled units; reported counts are
    scaled back up. Small shards (n < 64*k*sample) disable sampling.
    Classification CONSISTENCY across sides/ranks — what correctness
    needs — is unaffected: it comes from the replicated HH set, not
    from who sampled what."""
    n = keys.shape[0]
    if sample > 1 and n >= 64 * k * sample:
        m = n // sample
        # odd multiplier -> positions cycle through all residues of
        # every power-of-two period; near-uniform coverage of any
        # periodic layout. Collisions (gcd(C, n) > 1) repeat a few
        # rows — harmless for approximate counting.
        idx = ((
            jnp.arange(m, dtype=jnp.int64) * jnp.int64(2654435761)
        ) % jnp.int64(n)).astype(jnp.int32)
        keys_d = keys[idx]
        valid_d = valid[idx]
        # A threshold below ``sample`` truncates to 0, which would make
        # EVERY sampled key (count >= 1) HH-eligible and divert up to k
        # arbitrary keys to the HH path — clamp to 1 sampled occurrence.
        thr = jnp.maximum(threshold // sample, 1)
    else:
        sample = 1
        keys_d, valid_d, thr = keys, valid, threshold
    lk, lc = local_top_keys(keys_d, valid_d, k)
    gk = comm.all_gather(lk)                      # (n*k,)
    gc = comm.all_gather(lc)                      # (n*k,) int32
    nk = gk.shape[0]
    sentinel = _dtype_sentinel_max(keys.dtype)
    eq = gk[:, None] == gk[None, :]
    # int64 accumulation: per-rank counts fit int32, but a globally hot
    # key summed over many ranks can pass 2^31 — an int32 wrap here
    # would silently demote the hottest key to the normal path.
    tot = jnp.sum(
        jnp.where(eq, gc[None, :].astype(jnp.int64), 0), axis=1
    )
    iota = jnp.arange(nk)
    dup = jnp.any(eq & (iota[None, :] < iota[:, None]), axis=1)
    real = gk != sentinel
    score = jnp.where(real & ~dup, tot, 0)
    top_counts, top_idx = lax.top_k(score, k)
    slot_valid = top_counts > thr
    hh_keys = jnp.where(slot_valid, gk[top_idx], sentinel)
    return HeavyHitters(hh_keys, top_counts * sample, slot_valid)


# Above this K, mark_heavy's unrolled compare chain would bloat the
# program; the rolled fori_loop costs ~100s of us of device-loop
# overhead PER SLOT (docs/ROOFLINE.md §6), so unrolling is the fast
# path for the default K=64.
_MARK_UNROLL_MAX = 512


def mark_heavy(keys: jax.Array, hh: HeavyHitters) -> jax.Array:
    """Row-wise bool: key is in the HH set. K elementwise passes — no
    (rows, K) materialization (which would be GBs at 10M rows)."""
    K = hh.keys.shape[0]
    if K <= _MARK_UNROLL_MAX:
        acc = keys != keys
        for j in range(K):
            acc = acc | ((keys == hh.keys[j]) & hh.slot_valid[j])
        return acc

    def body(j, acc):
        hk = lax.dynamic_index_in_dim(hh.keys, j, keepdims=False)
        hv = lax.dynamic_index_in_dim(hh.slot_valid, j, keepdims=False)
        return acc | ((keys == hk) & hv)

    # Init derived from `keys` (all-False, same shape) so the carry is
    # rank-varying under shard_map's vma tracking, like the body output.
    return lax.fori_loop(0, K, body, keys != keys)


def extract_prefix(table: Table, sel: jax.Array, capacity: int,
                   kernel_config=None):
    """Stable-compact rows where ``sel`` into a static-capacity Table;
    returns (extracted, count, overflow). ``capacity`` may exceed the
    table's row count (extra slots are padding).

    On TPU the selected row INDICES are packed by the streaming
    log-shift compaction kernel (ops/compact_planes.py — the round-3
    VERDICT's named fix for the HH path re-sorting the full probe),
    then one composed row gather materializes the block at ``capacity``
    rows, so the cost scales with ``capacity``, not the table. Off-TPU
    (and under the interpreter) a 32-bit sort does the same job — the
    kernel carry chain is slow to interpret."""
    from distributed_join_tpu.ops.kernel_config import resolve

    n = sel.shape[0]
    cfg = resolve(kernel_config)
    use_kernel, interpret = cfg.expand_enabled()
    count = jnp.sum(sel.astype(jnp.int32))
    lane = jnp.arange(capacity, dtype=jnp.int32)
    if use_kernel and not interpret and n >= 2 * capacity:
        from distributed_join_tpu.ops.compact_pallas import stream_compact
        from distributed_join_tpu.ops.compact_planes import (
            plane_stream_compact,
        )

        compact = (
            plane_stream_compact
            if cfg.use_plane_compact(interpret) else stream_compact
        )
        pos = jnp.cumsum(sel.astype(jnp.int32)) - 1
        iota64 = jnp.arange(n, dtype=jnp.uint64)
        kw = {"block": cfg.block} if cfg.block else {}
        (packed_idx,) = compact(sel, pos, [iota64], capacity,
                                interpret=interpret, **kw)
        # Slots >= the survivor count are UNDEFINED in the kernel's
        # contract — clamp before gathering; `valid` masks them.
        idx = jnp.clip(packed_idx.astype(jnp.int32), 0, n - 1)
    else:
        # 32-bit stable sort (jnp.argsort under x64 would carry int64
        # lanes).
        _, order = lax.sort(
            ((~sel).astype(jnp.int8), jnp.arange(n, dtype=jnp.int32)),
            num_keys=1, is_stable=True,
        )
        idx = order[jnp.minimum(lane, n - 1)]
    cols = {name: c[idx] for name, c in table.columns.items()}
    valid = (lane < jnp.minimum(count, capacity)) & (lane < n)
    return Table(cols, valid), count, count > capacity


def broadcast_heavy_build(
    comm: Communicator, build: Table, is_hh: jax.Array, capacity: int,
    kernel_config=None,
):
    """All-gather each rank's HH build rows (fixed ``capacity`` slots)
    into one replicated Table of n_ranks*capacity rows."""
    local, count, overflow = extract_prefix(
        build, is_hh & build.valid, capacity,
        kernel_config=kernel_config)
    cols = {n: comm.all_gather(c) for n, c in local.columns.items()}
    valid = comm.all_gather(local.valid)
    return Table(cols, valid), comm.psum(overflow.astype(jnp.int32)) > 0
