"""The Communicator plugin boundary, TPU-native.

The reference's central abstraction (SURVEY.md §2,
``src/communicator.hpp``: virtual ``initialize / send / recv / waitall /
finalize`` with NCCL and UCX implementations, MPI bootstrap) is the seam
`BASELINE.json`'s north star requires us to keep. On TPU the seam moves
up one level of abstraction:

- the reference's per-peer ``send/recv`` pairs + ``waitall`` always
  implement one logical op — an all-to-all exchange — so the TPU
  ``Communicator`` exposes ``all_to_all`` directly and lets XLA lower it
  to ICI DMAs (there is no profitable TPU analog of hand-posted sends);
- ``initialize`` becomes mesh construction + (multi-host) the JAX
  distributed runtime handshake — coordinator over TCP/DCN replaces the
  reference's ``MPI_Bcast`` of the NCCL unique id (SURVEY.md §3.3);
- ``waitall`` disappears: the collective completes inside the compiled
  program with no user-visible handle. (Whether the compiler ALSO
  overlaps it with compute is an empirical question — measured in
  round 2: on the v5e toolchain it does not; see docs/OVERLAP.md.)

Implementations:

- :class:`TpuCommunicator` — ``jax.lax.all_to_all`` under ``shard_map``
  over a 1-D mesh; works identically on a real ICI slice and on the
  CPU fake backend (``--xla_force_host_platform_device_count=N``).
- :class:`LocalCommunicator` — 1 rank; the collective degenerates to
  identity. BASELINE config 1's CPU reference path.

``spmd`` is the entry for running a per-rank function SPMD over the
mesh, with row-sharded inputs/outputs; the orchestrator
(:mod:`distributed_join_tpu.parallel.distributed_join`) is built on it.
"""

from __future__ import annotations

import abc
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_join_tpu import compat
from distributed_join_tpu.parallel.mesh import (
    make_hierarchical_mesh,
    make_mesh,
)


class Communicator(abc.ABC):
    """Abstract communication backend (the reference's plugin boundary)."""

    name: str = "abstract"

    @property
    @abc.abstractmethod
    def n_ranks(self) -> int:
        ...

    @abc.abstractmethod
    def all_to_all(self, x: jax.Array) -> jax.Array:
        """Exchange row-blocks: x has shape (n_ranks * m, ...) per rank;
        block i (rows [i*m, (i+1)*m)) is sent to rank i; the result
        concatenates the blocks received from every rank in rank order.
        Must be called inside :meth:`spmd`. Shape-preserving."""

    def ppermute_all_to_all(self, x: jax.Array) -> jax.Array:
        """:meth:`all_to_all` semantics with an async-schedulable
        lowering where the backend has one (TpuCommunicator chains
        collective-permutes, docs/OVERLAP.md); the default — plain
        all_to_all — is always semantically correct."""
        return self.all_to_all(x)

    @abc.abstractmethod
    def all_gather(self, x: jax.Array) -> jax.Array:
        """Concatenate every rank's ``x`` along axis 0 (rank order),
        replicated to all ranks. Must be called inside :meth:`spmd`.
        The skew path broadcasts heavy-hitter build rows with this —
        the TPU analog of the reference replicating hot partitions."""

    @abc.abstractmethod
    def spmd(self, fn: Callable, *, sharded_out=None) -> Callable:
        """Compile ``fn`` to run SPMD, one instance per rank.

        Array args/outputs are row-sharded over ranks (global view);
        outputs flagged replicated in ``sharded_out`` (a pytree prefix of
        bools, default all-sharded) must be identical on every rank
        (e.g. after a psum).
        """

    def axis_index(self):
        """This rank's index, traced (0 on a single-rank backend)."""
        return jnp.int32(0)

    def ragged_all_to_all(self, operand, output, input_offsets,
                          send_sizes, output_offsets, recv_sizes):
        """Exact-size exchange (the reference's offsets+sizes send/recv
        loop as ONE op): peer i receives ``operand[input_offsets[i] :
        + send_sizes[i]]`` written at ``output_offsets[i]`` of its
        ``output`` buffer. All size/offset vectors are (n_ranks,) int32
        and must be mutually consistent across ranks (see
        parallel/shuffle.ragged_plan). Must be called inside
        :meth:`spmd`. Returns the filled output buffer."""
        return self._ragged_emulate(
            operand, output, input_offsets, send_sizes,
            output_offsets, recv_sizes,
        )

    def pvary(self, x):
        """Mark ``x`` as varying over the rank axis for shard_map's
        vma checker (identity on single-rank backends)."""
        return x

    # -- hierarchical seams (two-level ICI/DCN shuffle) ---------------
    #
    # A flat communicator is the degenerate one-slice hierarchy: the
    # whole mesh is one ICI domain, so the slice-local exchange IS the
    # global all_to_all and the cross-slice exchange is the identity.
    # Multi-slice backends (HierarchicalTpuCommunicator) override all
    # three; docs/HIERARCHY.md has the routing algebra.

    @property
    def n_slices(self) -> int:
        """Slow-tier (DCN) groups in the mesh; 1 = no slow tier."""
        return 1

    @property
    def chips_per_slice(self) -> int:
        """Fast-tier (ICI) ranks per slice."""
        return self.n_ranks

    def all_to_all_chip(self, x: jax.Array) -> jax.Array:
        """:meth:`all_to_all` restricted to THIS rank's slice (the
        fast ICI tier): x has ``chips_per_slice`` leading blocks;
        block j goes to the j-th chip of this slice. Flat backends:
        the slice is the whole mesh."""
        return self.all_to_all(x)

    def all_to_all_slice(self, x: jax.Array) -> jax.Array:
        """:meth:`all_to_all` across slices at a FIXED chip index
        (the slow DCN tier): x has ``n_slices`` leading blocks; block
        t goes to this chip's peer on slice t. Flat backends: one
        slice, identity."""
        return x

    # Emulation of ragged_all_to_all for backends/platforms without the
    # hardware op (XLA:CPU has no ragged-all-to-all thunk). Assembles
    # the output from all-gathered operands with static-shape masked
    # copies — wire-inefficient by construction, but bit-identical in
    # semantics; tests and the virtual-device mesh run through it.
    def _ragged_emulate(self, operand, output, input_offsets, send_sizes,
                        output_offsets, recv_sizes):
        n = self.n_ranks
        me = self.axis_index()
        g_op = self.all_gather(operand[None, ...])        # (n, len, ...)
        g_in = self.all_gather(input_offsets[None, :])    # (n, n)
        g_sz = self.all_gather(send_sizes[None, :])
        g_out = self.all_gather(output_offsets[None, :])
        out = output
        idx = jnp.arange(output.shape[0], dtype=jnp.int32)
        for j in range(n):
            in_off = g_in[j, me]
            sz = g_sz[j, me]
            out_off = g_out[j, me]
            rel = idx - out_off
            take = (rel >= 0) & (rel < sz)
            src = g_op[j][
                jnp.clip(in_off + rel, 0, operand.shape[0] - 1)
            ]
            mask = take.reshape((-1,) + (1,) * (out.ndim - 1))
            out = jnp.where(mask, src, out)
        return out

    # -- small conveniences shared by backends ------------------------

    def psum(self, x):
        return x

    def finalize(self) -> None:
        """Reference parity (``Communicator::finalize``); no-op — XLA
        owns transport/buffer lifetime on TPU."""


class TpuCommunicator(Communicator):
    """XLA-collective backend over a 1-D device mesh (ICI data plane)."""

    name = "tpu"

    def __init__(self, mesh: Mesh | None = None, n_ranks: int | None = None):
        self.mesh = mesh if mesh is not None else make_mesh(n_ranks)
        if len(self.mesh.axis_names) != 1:
            raise ValueError("TpuCommunicator needs a 1-D mesh")
        self.axis_name = self.mesh.axis_names[0]

    @property
    def n_ranks(self) -> int:
        return self.mesh.shape[self.axis_name]

    def all_to_all(self, x: jax.Array) -> jax.Array:
        return lax.all_to_all(
            x, self.axis_name, split_axis=0, concat_axis=0, tiled=True
        )

    def all_gather(self, x: jax.Array) -> jax.Array:
        return lax.all_gather(x, self.axis_name, axis=0, tiled=True)

    def ppermute_all_to_all(self, x: jax.Array) -> jax.Array:
        """``all_to_all`` semantics via a chain of n-1
        ``collective-permute`` steps (plus the local block).

        Same result as :meth:`all_to_all` for x of shape (n, ...);
        the point is the LOWERING: round 2 measured that grouped
        ``all-to-all`` HLO is emitted synchronously on this toolchain
        (zero async pairs, docs/OVERLAP.md), while collective-permute
        lowers as start/done pairs that XLA's latency-hiding
        scheduler can interleave with unrelated compute — the
        reference's stream-pipelined shuffle (SURVEY.md §2
        "Over-decomposition") expressed in XLA terms.

        Step d: every rank s sends block x[(s+d) % n] to rank
        (s+d) % n, so this rank (r) receives sender (r-d) % n's
        block. The d-ordered stack is sender-rotated; one reversed
        dynamic roll restores sender order.
        """
        n = self.n_ranks
        r = self.axis_index()
        parts = []
        for d in range(n):
            piece = lax.dynamic_index_in_dim(
                x, (r + jnp.int32(d)) % n, axis=0, keepdims=False
            )
            if d:
                piece = lax.ppermute(
                    piece, self.axis_name,
                    perm=[(s, (s + d) % n) for s in range(n)],
                )
            parts.append(piece)
        stacked = jnp.stack(parts)      # index d = sender (r-d) % n
        # out[s] = stacked[(r-s) % n]: reverse then roll by r+1
        return jnp.roll(stacked[::-1], r + 1, axis=0)

    def axis_index(self):
        return lax.axis_index(self.axis_name)

    def pvary(self, x):
        return compat.pvary(x, self.axis_name)

    def ragged_all_to_all(self, operand, output, input_offsets,
                          send_sizes, output_offsets, recv_sizes):
        if jax.default_backend() != "tpu":
            # XLA:CPU has no ragged-all-to-all thunk.
            return self._ragged_emulate(
                operand, output, input_offsets, send_sizes,
                output_offsets, recv_sizes,
            )
        dt = operand.dtype
        if dt.itemsize == 8 and jnp.issubdtype(dt, jnp.integer):
            # The TPU x64 rewriter does not implement 64-bit
            # ragged-all-to-all; integer bitcasts ARE implemented, so
            # 64-bit integer operands ride as (rows, ..., 2) uint32.
            u = lax.bitcast_convert_type(operand, jnp.uint32)
            out_u = lax.bitcast_convert_type(output, jnp.uint32)
            res = lax.ragged_all_to_all(
                u, out_u, input_offsets, send_sizes,
                output_offsets, recv_sizes, axis_name=self.axis_name,
            )
            return lax.bitcast_convert_type(res, dt)
        if dt.itemsize == 8:
            # f64: neither the 64-bit op nor an f64 bitcast exists on
            # TPU. The emulation is correct but all-gathers the whole
            # column — MORE wire bytes than the padded shuffle; warn
            # (once per trace) so the regression is never silent.
            import warnings

            warnings.warn(
                "ragged_all_to_all: f64 operands fall back to an "
                "all-gather emulation on TPU, which moves MORE bytes "
                "than the padded shuffle; keep f64 columns on "
                "shuffle='padded'",
                stacklevel=2,
            )
            return self._ragged_emulate(
                operand, output, input_offsets, send_sizes,
                output_offsets, recv_sizes,
            )
        return lax.ragged_all_to_all(
            operand, output, input_offsets, send_sizes,
            output_offsets, recv_sizes, axis_name=self.axis_name,
        )

    def psum(self, x):
        return lax.psum(x, self.axis_name)

    def spmd(self, fn: Callable, *, sharded_out=None) -> Callable:
        shard_spec = P(self.axis_name)
        if sharded_out is None:
            out_specs = shard_spec
        else:
            out_specs = jax.tree.map(
                lambda rep: P() if rep else shard_spec,
                sharded_out,
            )
        mapped = compat.shard_map(
            fn, mesh=self.mesh, in_specs=shard_spec, out_specs=out_specs
        )
        return jax.jit(mapped)

    def device_put_sharded(self, tree):
        """Place a pytree of host arrays row-sharded over the mesh.

        Multi-host (``jax.process_count() > 1``): every process passes
        the same GLOBAL value (deterministic generators make this free)
        and keeps only its addressable shards — the multi-controller
        contract. Device-backed leaves are pulled to host first;
        ``device_put`` requires addressable-only sources there.
        """
        sharding = NamedSharding(self.mesh, P(self.axis_name))
        if jax.process_count() > 1:
            import numpy as np

            tree = jax.tree.map(np.asarray, tree)
        return jax.tree.map(lambda a: jax.device_put(a, sharding), tree)


class HierarchicalTpuCommunicator(TpuCommunicator):
    """XLA-collective backend over a 2-D ``(slice, chip)`` mesh — the
    8->64-chip scale-out topology (ROADMAP item 5, docs/HIERARCHY.md).

    The flat rank space is slice-major: rank ``r`` lives at
    ``(r // chips_per_slice, r % chips_per_slice)``, so a row-sharded
    table shards identically to the 1-D mesh over the same device
    order. Global collectives run over BOTH axes (multi-axis
    ``lax.all_to_all``/``all_gather`` concatenate slice-major — flat
    rank order, so every existing call site is semantics-identical);
    the hierarchical seams expose the per-tier collectives the
    two-level shuffle routes through: :meth:`all_to_all_chip` inside
    a slice over ICI, :meth:`all_to_all_slice` across slices over
    DCN.
    """

    name = "tpu-hier"

    def __init__(self, mesh: Mesh | None = None,
                 n_slices: int | None = None,
                 n_ranks: int | None = None):
        if mesh is None:
            mesh = make_hierarchical_mesh(n_slices or 1, n_ranks)
        if len(mesh.axis_names) != 2:
            raise ValueError(
                "HierarchicalTpuCommunicator needs a 2-D (slice, "
                f"chip) mesh, got axes {mesh.axis_names}")
        self.mesh = mesh
        self.slice_axis, self.chip_axis = mesh.axis_names
        # Both axes, slice-major — every inherited collective
        # (all_to_all/all_gather/psum/axis_index) runs globally over
        # the tuple and sees the flat rank space.
        self.axis_name = (self.slice_axis, self.chip_axis)

    @property
    def n_ranks(self) -> int:
        return self.n_slices * self.chips_per_slice

    @property
    def n_slices(self) -> int:
        return self.mesh.shape[self.slice_axis]

    @property
    def chips_per_slice(self) -> int:
        return self.mesh.shape[self.chip_axis]

    def all_to_all_chip(self, x: jax.Array) -> jax.Array:
        return lax.all_to_all(
            x, self.chip_axis, split_axis=0, concat_axis=0, tiled=True
        )

    def all_to_all_slice(self, x: jax.Array) -> jax.Array:
        return lax.all_to_all(
            x, self.slice_axis, split_axis=0, concat_axis=0,
            tiled=True
        )

    def axis_index(self):
        return (lax.axis_index(self.slice_axis)
                * jnp.int32(self.chips_per_slice)
                + lax.axis_index(self.chip_axis))

    def pvary(self, x):
        return compat.pvary(
            compat.pvary(x, self.slice_axis), self.chip_axis)

    def ppermute_all_to_all(self, x: jax.Array) -> jax.Array:
        """The 1-D ppermute chain is a flat-mesh lowering; on the
        hierarchical mesh the async-schedulable story is the
        two-level shuffle itself, so this degrades to the grouped
        global all_to_all (always semantically correct)."""
        return self.all_to_all(x)


class LocalCommunicator(Communicator):
    """Single-rank backend: collectives are identities. This is the
    reference's 1-rank path (BASELINE config 1)."""

    name = "local"

    @property
    def n_ranks(self) -> int:
        return 1

    def all_to_all(self, x: jax.Array) -> jax.Array:
        return x

    def all_gather(self, x: jax.Array) -> jax.Array:
        return x

    def spmd(self, fn: Callable, *, sharded_out=None) -> Callable:
        return jax.jit(fn)

    def device_put_sharded(self, tree):
        return jax.tree.map(jax.device_put, tree)


def make_communicator(name: str, n_ranks: int | None = None,
                      n_slices: int | None = None) -> Communicator:
    """Factory keyed by the reference driver's ``--communicator`` flag.

    The reference accepts {NCCL, UCX}; this framework adds ``tpu`` (the
    north-star flag) and ``local``. NCCL/UCX are recognized but rejected
    with an explanatory error — there is no NCCL/UCX on TPU hardware.

    ``n_slices`` (the drivers' ``--slices K``) > 1 builds the 2-D
    hierarchical mesh (docs/HIERARCHY.md); 1/None keeps the flat 1-D
    mesh — deliberately NOT a one-row hierarchical mesh, so the
    degenerate hierarchy lowers byte-identically to the seed programs.
    """
    lname = name.lower()
    if lname == "tpu":
        if n_slices is not None and n_slices > 1:
            return HierarchicalTpuCommunicator(n_slices=n_slices,
                                               n_ranks=n_ranks)
        return TpuCommunicator(n_ranks=n_ranks)
    if lname == "local":
        if n_slices is not None and n_slices > 1:
            raise ValueError(
                "the local (1-rank) communicator has no multi-slice "
                "topology; --slices needs --communicator=tpu")
        return LocalCommunicator()
    if lname in ("nccl", "ucx"):
        raise ValueError(
            f"communicator {name!r} is the reference's GPU backend; "
            "this framework targets TPU — use --communicator=tpu"
        )
    raise ValueError(f"unknown communicator {name!r}")
