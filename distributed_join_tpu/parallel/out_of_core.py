"""Out-of-core key-range batching — tables bigger than HBM.

The reference's over-decomposition has a second job beyond pipelining:
only ``1/k`` of the *shuffled* data is resident at once (SURVEY.md §5
"Long-context"). But its inputs still live wholly in device memory, and
so do ours inside one compiled step. For tables that exceed HBM
entirely (TPC-H SF-100 lineitem is ~600M rows), this module batches the
*key space* on the host: rows are split into ``n_batches`` by key hash
(the same Murmur3 finalizer the device kernels use — numpy mirror
below), and each co-partitioned batch pair runs through the compiled
distributed join independently. Matching keys share a hash, hence a
batch, so batch joins are independent and their totals sum.

This is the framework's answer to the reference's "tables larger than
per-chip HBM" axis; the host loop costs one H2D transfer per batch,
overlapped with device compute by :func:`batched_join_host`'s staging
thread.
"""

from __future__ import annotations

import time
import warnings
from typing import Callable, Optional, Tuple

import numpy as np

from distributed_join_tpu import telemetry
from distributed_join_tpu.parallel.communicator import Communicator
from distributed_join_tpu.table import Table


def fmix64_np(x: np.ndarray) -> np.ndarray:
    """numpy mirror of ops.hashing.fmix64 (same constants) so host-side
    batching agrees with device-side bucket routing."""
    k = x.astype(np.uint64)
    k ^= k >> np.uint64(33)
    k *= np.uint64(0xFF51AFD7ED558CCD)
    k ^= k >> np.uint64(33)
    k *= np.uint64(0xC4CEB9FE1A85EC53)
    k ^= k >> np.uint64(33)
    return k


def hash_combine_np(seed: np.ndarray, h: np.ndarray) -> np.ndarray:
    """numpy mirror of ops.hashing.hash_combine."""
    magic = np.uint64(0x9E3779B97F4A7C15)
    return seed ^ (
        h + magic + (seed << np.uint64(6)) + (seed >> np.uint64(2))
    )


def fmix32_np(x: np.ndarray) -> np.ndarray:
    """numpy mirror of ops.hashing.fmix32."""
    h = x.astype(np.uint32)
    h ^= h >> np.uint32(16)
    h *= np.uint32(0x85EBCA6B)
    h ^= h >> np.uint32(13)
    h *= np.uint32(0xC2B2AE35)
    h ^= h >> np.uint32(16)
    return h


def _hash_one_np(col: np.ndarray) -> np.ndarray:
    """numpy mirror of ops.hashing._hash_one's per-dtype dispatch.

    Bit-exact with the device hash for integer and float32 keys. For
    float64 the mirror follows the same arithmetic decomposition, but
    np.log2/np.exp2 and XLA's may differ in the last ulp near powers of
    two, so cross-layer agreement is NOT guaranteed for f64 — batch
    correctness only needs host-internal consistency (both sides of a
    key batch by the same host hash), which always holds."""
    dt = col.dtype
    if dt in (np.dtype(np.int64), np.dtype(np.uint64)):
        return fmix64_np(col)
    if dt in (np.dtype(t) for t in
              (np.int32, np.uint32, np.int16, np.uint16, np.int8, np.uint8)):
        return fmix32_np(col).astype(np.uint64)
    if dt == np.dtype(np.float64):
        # Mirrors the device's arithmetic f64 decomposition (hashing.py
        # _hash_one): |x| = m * 2**e with m in [1, 2), mantissa scaled
        # to 52 bits; sign folded into the exponent hash.
        a = np.abs(col)
        with np.errstate(divide="ignore"):
            e = np.where(a > 0, np.floor(np.log2(a)), 0.0)
        m = np.where(a > 0, a / np.exp2(e), 0.0)
        mi = (m * (2.0 ** 52)).astype(np.int64).astype(np.uint64)
        ebits = e.astype(np.int32) ^ (col < 0).astype(np.int32) << 30
        return hash_combine_np(fmix64_np(mi), fmix32_np(ebits).astype(np.uint64))
    if dt == np.dtype(np.float32):
        return fmix32_np(col.view(np.uint32)).astype(np.uint64)
    raise TypeError(f"unhashable key dtype {dt}")


def hash_columns_np(cols) -> np.ndarray:
    """numpy mirror of ops.hashing.hash_columns (composite keys batch by
    the combined hash); per-dtype dispatch matches the device exactly."""
    acc = _hash_one_np(cols[0])
    for c in cols[1:]:
        acc = hash_combine_np(acc, _hash_one_np(c))
    return acc


def key_batch_ids(keys, n_batches: int) -> np.ndarray:
    """Batch id per row; ``keys`` is one array or a list of composite
    key columns. Uses the UPPER hash bits so batching composes with the
    device kernels' ``hash % n_buckets`` routing (lower bits): the two
    partitions stay independent, and every key pair that joins lands in
    the same batch on both sides."""
    cols = keys if isinstance(keys, (list, tuple)) else [keys]
    h = hash_columns_np([np.asarray(c) for c in cols])
    # Re-mix before taking the upper bits: 32-bit key dtypes hash via
    # fmix32 widened to uint64, whose top 32 bits are all zero — without
    # this pass every row of such a column would land in batch 0.
    h = fmix64_np(h)
    return ((h >> np.uint64(40)) % np.uint64(n_batches)).astype(np.int64)


def _host_columns(table: Table) -> dict:
    mask = np.asarray(table.valid)
    return {n: np.asarray(c)[mask] for n, c in table.columns.items()}


def _pad_host(cols: dict, cap: int) -> Table:
    """Host columns -> fixed-capacity HOST Table (prefix-valid). Leaves
    stay numpy so the subsequent ``device_put_sharded`` performs the one
    and only H2D transfer, sharded — materializing on the default
    device here would bounce every batch through one chip's HBM."""
    m = next(iter(cols.values())).shape[0]
    out = {}
    for name, c in cols.items():
        buf = np.zeros((cap,) + c.shape[1:], dtype=c.dtype)
        buf[:m] = c
        out[name] = buf
    return Table(out, np.arange(cap) < m)


def batched_join_host(
    build_batches,
    probe_batches,
    comm: Communicator,
    key: str = "key",
    warmup: bool = True,
    stats: Optional[dict] = None,
    on_batch_result: Optional[Callable] = None,
    manifest_path: Optional[str] = None,
    batch_retries: int = 0,
    batch_retry_backoff_s: float = 1.0,
    on_batch_failure: str = "raise",
    verify_integrity: bool = False,
    batch_deadline_s: Optional[float] = None,
    **join_opts,
) -> Tuple[int, bool]:
    """Join pre-binned HOST batches (lists of numpy column dicts, e.g.
    from :func:`..utils.tpch_host.generate_tpch_host_batches`) with
    one-batch-ahead H2D staging; returns (total_matches, any_overflow).

    Failure semantics (docs/FAILURE_SEMANTICS.md):

    - ``manifest_path``: a per-batch progress manifest
      (:class:`..faults.JoinManifest`) written atomically after each
      batch's exact total is known. A killed run re-invoked with the
      same arguments resumes from the first incomplete batch — batches
      are key-disjoint, so the resumed sum is bit-exact with the
      uninterrupted run. A manifest written by a DIFFERENT batching
      (row counts, capacities, key, rank count) is refused.
    - ``batch_retries``: per-batch dispatch retries before a batch is
      declared failed (transient launch/collective failures), with
      exponential backoff starting at ``batch_retry_backoff_s`` —
      back-to-back re-dispatches would burn the whole budget inside a
      still-live transient outage.
    - ``on_batch_failure``: "raise" (default) propagates a batch's
      final failure; "continue" degrades gracefully — the batch id is
      recorded in ``stats['failed_batches']`` (and the manifest's
      failure log) and the join returns PARTIAL totals over the
      batches that did complete, instead of crashing an hours-long
      out-of-core run on one bad batch.
    - ``stats`` additionally receives ``resumed_batches`` (ids skipped
      via the manifest) and ``failed_batches``.
    - ``verify_integrity``: each batch's join carries the in-graph
      wire digests (parallel/integrity.py; the compiled program's aux
      Metrics block) and is verified at its settle point. A mismatch
      is a batch failure under the same contract as a dispatch
      failure: ``"raise"`` propagates the
      :class:`..integrity.IntegrityError`; ``"continue"`` abandons the
      batch (its corrupt total is NOT counted) and records it in
      ``failed_batches`` + the manifest's failure log — never folded
      silently into the returned total.
    - ``batch_deadline_s``: bound each batch's result fetch under the
      shared hang watchdog (:mod:`..watchdog`): a deadlocked
      collective (or a wedged relay) surfaces as a structured
      ``HangError`` batch failure — same degradation contract —
      instead of blocking the loop forever. Worker teardown on the
      error path is also bounded (``watchdog.shutdown_bounded``), so
      an orphaned stage/fetch worker reports a
      ``worker_shutdown_timeout`` event rather than hanging the
      interpreter at exit.

    This is the out-of-core hot path (VERDICT r1 weak #5: the r1 loop
    was fully serial). The pipeline, per loop iteration:

      1. batch b's join is DISPATCHED (async under JAX);
      2. batch b+1's pad + H2D transfer starts on the staging thread;
      3. batch b's RESULT leaves the device on a second worker thread
         (round 5 — the D2H side of VERDICT r4 weak #2): when
         ``on_batch_result`` is given it runs there, in batch order,
         overlapping batch b+1's compute the same way the staging
         thread overlaps H2D. Backpressure: before dispatching batch
         b+1 the loop waits for batch b-1's fetch (or, with no
         consumer, fetches b-1's match count) — batch b+2 cannot
         stage until b-1 has finished and its buffers are freeable,
         which bounds device residency at ~3 batches of inputs + ~2
         output blocks regardless of ``n_batches`` (without
         backpressure, a fast host would stage EVERY batch while
         batch 0 still computes and OOM at exactly the scale this
         path exists for). Size ``n_batches`` so three batches of
         inputs and two output blocks fit HBM.

    The reference overlaps comm/compute with CUDA streams + helper
    threads (SURVEY.md §2 "Over-decomposition"); here a single staging
    THREAD does the same job: measured phase timings showed
    ``jax.device_put`` of host batches is effectively synchronous (at
    SF-10 the phase sums equaled the elapsed time — zero overlap), so
    batch b+1's pad+transfer runs on a worker thread while this thread
    waits on batch b-1's result. numpy copies and the transfer both
    release the GIL, so the overlap is real even on a 1-CPU host.

    Every batch runs through ONE compiled join (capacities = max batch
    rows, rank-rounded), so there is exactly one XLA compile.

    Timing note: with ``warmup`` the already-staged batch 0 is reused
    as the measured loop's first input, so its H2D falls outside
    ``stats['elapsed_s']`` — an undercount of at most 1/n_batches of
    the staging cost (vs double-staging batch 0, which overcounted).
    """
    from distributed_join_tpu.parallel.distributed_join import (
        make_distributed_join,
    )
    from distributed_join_tpu.parallel.faults import (
        JoinManifest,
        batch_config_fingerprint,
    )

    if len(build_batches) != len(probe_batches):
        raise ValueError("build/probe batch counts differ")
    if on_batch_failure not in ("raise", "continue"):
        raise ValueError(
            f"on_batch_failure must be 'raise' or 'continue', "
            f"got {on_batch_failure!r}"
        )
    n_batches = len(build_batches)
    n = comm.n_ranks

    def _cap(batches):
        c = max(next(iter(b.values())).shape[0] for b in batches)
        return max(-(-c // n) * n, n)

    bcap, pcap = _cap(build_batches), _cap(probe_batches)

    manifest = None
    completed: dict = {}
    if manifest_path is not None:
        manifest = JoinManifest(
            manifest_path,
            batch_config_fingerprint(build_batches, probe_batches,
                                     n, key, bcap, pcap),
        )
        # An overflowed batch's recorded TOTAL is exact, but its
        # materialized rows were truncated — and the natural resume
        # after an overflowing run is "re-invoke with bigger
        # capacities against the same manifest" (join sizing options
        # are deliberately NOT in the fingerprint). So overflowed
        # entries count as incomplete and re-run; record_batch
        # overwrites them.
        completed = {b: v for b, v in manifest.completed.items()
                     if not v["overflow"]}
        if completed and on_batch_result is not None:
            warnings.warn(
                "resuming from a manifest: on_batch_result will not "
                f"be called for already-completed batches "
                f"{sorted(completed)} — the consumer's stream covers "
                "only batches run in THIS invocation, though the "
                "returned total covers all of them",
                stacklevel=2,
            )
    # Batches still to run, in order; capacities stay computed over ALL
    # batches so a resumed run compiles the identical program.
    pending = [b for b in range(n_batches) if b not in completed]
    failed: set = set()

    # fetch_s: time actually spent pulling results (on the fetch
    # worker when a consumer is installed — HIDDEN behind compute);
    # fetch_wait_s: time the MAIN loop blocked on a fetch — the
    # UNHIDDEN remainder, the number that shows whether the overlap
    # worked. Only the fetch worker writes fetch_s; only the main
    # thread writes the others — no lock needed. The same increments
    # flow into the telemetry session (``out_of_core.<key>`` counters
    # + per-batch spans, docs/OBSERVABILITY.md) with the JSON keys
    # preserved verbatim — `stats` consumers never notice telemetry.
    phase = {"pad_s": 0.0, "put_s": 0.0, "dispatch_s": 0.0,
             "fetch_s": 0.0, "fetch_wait_s": 0.0}

    def _phase_add(key, dt):
        phase[key] += dt
        telemetry.counter_add("out_of_core." + key, dt)

    def stage(b):
        with telemetry.span("stage", batch=b):
            t0 = time.perf_counter()
            bt = _pad_host(build_batches[b], bcap)
            pt = _pad_host(probe_batches[b], pcap)
            t1 = time.perf_counter()
            out = comm.device_put_sharded((bt, pt))
            _phase_add("pad_s", t1 - t0)
            _phase_add("put_s", time.perf_counter() - t1)
        return out

    from concurrent.futures import ThreadPoolExecutor

    # with_metrics=False: the per-batch dispatch loop stays the seed
    # program even under an active telemetry session — out-of-core
    # observability is host-side by design (phase counters, per-batch
    # spans/events above), and an aux device block nobody fetches
    # would still be computed every batch. verify_integrity is the
    # exception: its digests ARE fetched, at each batch's settle.
    fn = make_distributed_join(comm, key=key, with_metrics=False,
                               with_integrity=verify_integrity,
                               **join_opts)
    pool = ThreadPoolExecutor(max_workers=1)
    fetch_pool = ThreadPoolExecutor(max_workers=1)

    # {pending index -> IntegrityReport} verified on the fetch worker
    # (reused by _settle so each batch's digests are checked once).
    verified_reports: dict = {}

    def _fetch(i, b, res):
        # Runs ON the fetch worker, in batch order (1 worker). The
        # consumer's D2H pulls overlap the NEXT batch's device compute
        # — mirror image of the staging thread. numpy materialization
        # and the transfer both release the GIL.
        if verify_integrity and getattr(res, "telemetry", None) is not None:
            # Verify BEFORE the consumer sees a single row: a wire-
            # corrupted batch must be abandoned, not persisted by a
            # materializing consumer and only flagged at settle. The
            # overflow fetch + digest transfer ride this worker, so
            # the overlap the fetch thread exists for is preserved.
            # (Overflowed batches skip the check — clamped rows
            # mismatch by design and the flag already demands a
            # retry; the consumer contract for flagged batches is
            # unchanged.)
            if not bool(res.overflow):
                from distributed_join_tpu.parallel import integrity

                rep = integrity.verify_digests(res.telemetry)
                verified_reports[i] = rep
                if not rep.ok:
                    telemetry.event(
                        "batch_integrity_mismatch", batch=b,
                        mismatches=len(rep.mismatches))
                    return  # _settle fails the batch under contract
        with telemetry.span("fetch", batch=b):
            tf = time.perf_counter()
            on_batch_result(b, res)
            _phase_add("fetch_s", time.perf_counter() - tf)

    # Per-batch remaining FAILED-attempt budget: one pool of
    # batch_retries + 1, shared between the warmup dispatch and the
    # measured loop so neither double-charges (or double-logs).
    # Successes are free — the measured loop re-dispatching a batch
    # warmup already ran clean is by design, not a retry.
    tries_left: dict = {}

    def _dispatch(b, bt, pt):
        """fn(bt, pt) under batch ``b``'s failure budget, with
        exponential backoff between attempts (faults.retry_with_backoff
        — the same transient-failure loop as the bootstrap handshake);
        returns the JoinResult or None when the batch is abandoned
        (on_batch_failure='continue', budget exhausted). Failures are
        appended to the manifest's forensic log."""
        from distributed_join_tpu.parallel.faults import (
            retry_with_backoff,
        )

        last = None
        res = None
        tries_left.setdefault(b, batch_retries + 1)
        budget = tries_left[b]
        if budget > 0:
            try:
                res, attempts = retry_with_backoff(
                    lambda: fn(bt, pt), max_attempts=budget,
                    backoff_s=batch_retry_backoff_s,
                )
            except Exception as exc:  # noqa: BLE001 - retry seam
                last = exc
                attempts = getattr(exc, "_retry_attempts", [])
            # Only FAILED attempts charge the budget + forensic log.
            fails = [a for a in attempts if a["error"] is not None]
            tries_left[b] -= len(fails)
            if manifest is not None:
                base = batch_retries + 1 - budget
                for k, a in enumerate(fails):
                    manifest.record_failure(b, a["error"], base + k)
            if res is not None:
                return res
        if on_batch_failure == "continue":
            failed.add(b)
            return None
        # last=None (budget pre-exhausted, e.g. by warmup) only
        # happens under 'continue', which returned above.
        raise last

    def _fetch_scalars(i):
        """The one host sync per batch: total + overflow flag, under
        the hang watchdog when a batch deadline is configured (a
        deadlocked collective never sequences this fetch — HangError
        is a batch failure, not an eternity)."""
        if batch_deadline_s is None:
            return int(totals[i]), bool(overflows[i])
        from distributed_join_tpu.parallel.watchdog import (
            call_with_deadline,
        )

        return call_with_deadline(
            lambda: (int(totals[i]), bool(overflows[i])),
            batch_deadline_s,
            what=f"out-of-core batch {pending[i]} result fetch",
        )

    def _settle(i):
        """Force pending[i]'s total to host (the device sync), verify
        its wire digests when asked, and persist its manifest record.
        A failure HERE (result fetch, hang, integrity mismatch) is a
        batch failure too — same degradation contract as dispatch."""
        if totals[i] is None or isinstance(totals[i], int):
            return
        b = pending[i]
        try:
            totals[i], overflows[i] = _fetch_scalars(i)
            if (verify_integrity and not overflows[i]
                    and metrics_refs[i] is not None):
                from distributed_join_tpu.parallel import integrity

                rep = verified_reports.get(i)
                if rep is None:
                    rep = integrity.verify_digests(metrics_refs[i])
                if not rep.ok:
                    # A corrupt batch total must never fold into the
                    # returned sum — surface or abandon, per contract.
                    raise integrity.IntegrityError(rep)
        except Exception as exc:  # noqa: BLE001 - degradation seam
            if manifest is not None:
                manifest.record_failure(
                    b, f"{type(exc).__name__}: {exc}", batch_retries)
            if on_batch_failure != "continue":
                raise
            totals[i], overflows[i] = None, None
            failed.add(b)
            telemetry.event("batch_failed", batch=b,
                            error=f"{type(exc).__name__}: {exc}")
            return
        telemetry.event("batch_complete", batch=b, total=totals[i],
                        overflow=overflows[i])
        if manifest is not None:
            manifest.record_batch(b, totals[i], overflows[i])

    nxt = None
    if warmup and pending:
        nxt = stage(pending[0])
        # Compile + run under the same per-batch retry/degradation
        # contract as the measured loop (a transient failure here must
        # not crash a run that opted into batch_retries / 'continue');
        # result discarded, the staged inputs are reused as the
        # measured loop's first batch. The attempt budget is SHARED
        # with the measured loop: a batch warmup exhausts is failed
        # here and not re-dispatched.
        res = _dispatch(pending[0], *nxt)
        if res is not None:
            try:
                if batch_deadline_s is None:
                    int(res.total)
                else:
                    from distributed_join_tpu.parallel.watchdog import (
                        call_with_deadline,
                    )

                    call_with_deadline(
                        lambda: int(res.total), batch_deadline_s,
                        what="out-of-core warmup result fetch",
                    )
            except Exception as exc:  # noqa: BLE001 - degradation seam
                # Same contract as _settle: an async device failure
                # that only surfaces at the scalar fetch is a batch
                # failure, not a run crash, when the caller opted into
                # 'continue'. The measured loop re-dispatches the
                # batch and clears it from `failed` on recovery.
                if manifest is not None:
                    manifest.record_failure(
                        pending[0], f"{type(exc).__name__}: {exc}",
                        batch_retries)
                if on_batch_failure != "continue":
                    raise
                failed.add(pending[0])

    # Warmup staged the first pending batch before t0: reset the phase
    # counters so the breakdown covers exactly the [t0, end) window it
    # is reported against (otherwise pad_s/put_s over-count by one
    # batch). The telemetry counters are NOT reset — a session covers
    # the whole run, warmup included; the event below marks where the
    # measured window begins so the two accountings reconcile.
    for k_ in phase:
        phase[k_] = 0.0
    telemetry.event("out_of_core_measured_window",
                    n_batches=n_batches, pending=len(pending),
                    resumed=sorted(completed))
    t0 = time.perf_counter()
    fut = None
    if pending:
        fut = (pool.submit(lambda: nxt) if nxt is not None
               else pool.submit(stage, pending[0]))
    # All four lists are positionally aligned with `pending`;
    # totals[i] is a device scalar until _settle(i) fetches it, None
    # for a failed/abandoned batch. metrics_refs holds only the small
    # aux Metrics block (verify_integrity) — never the output table,
    # so backpressure still bounds device residency.
    totals, overflows, fetch_futs, metrics_refs = [], [], [], []
    try:
        for i, b in enumerate(pending):
            bt, pt = fut.result()
            td = time.perf_counter()
            res = _dispatch(b, bt, pt)
            _phase_add("dispatch_s", time.perf_counter() - td)
            if res is not None:
                # A batch marked failed at the warmup FETCH (dispatch
                # succeeded, async failure at the scalar sync) that
                # this dispatch just recovered must not stay in the
                # failure record — its total is counted.
                failed.discard(b)
            totals.append(res.total if res is not None else None)
            overflows.append(res.overflow if res is not None else None)
            metrics_refs.append(
                getattr(res, "telemetry", None)
                if res is not None else None
            )
            fetch_futs.append(
                fetch_pool.submit(_fetch, i, b, res)
                if (on_batch_result is not None and res is not None)
                else None
            )
            if i + 1 < len(pending):
                # Stage the next batch on the worker thread,
                # overlapping both this batch's device work and the
                # backpressure wait.
                fut = pool.submit(stage, pending[i + 1])
                if i >= 1:
                    # Backpressure (see docstring): batch i-1 must be
                    # done before a third batch's buffers exist.
                    tf = time.perf_counter()
                    if fetch_futs[i - 1] is not None:
                        # In-order consumption: i-1's consumer must
                        # have returned before i+1 dispatches.
                        fetch_futs[i - 1].result()
                    # The DEVICE sync cannot be delegated to the
                    # consumer — one that merely reduces (or keeps
                    # device references) returns before i-1's join
                    # finished, which would let the staging worker
                    # race ahead and OOM (review r5). A scalar fetch,
                    # not block_until_ready — the only sync that also
                    # holds under this environment's RPC relay. The
                    # manifest record rides the same sync point, so
                    # durability costs no extra synchronization.
                    _settle(i - 1)
                    _phase_add("fetch_wait_s",
                               time.perf_counter() - tf)
        tf = time.perf_counter()
        for f in fetch_futs:
            if f is not None:
                f.result()  # drain (+ surface consumer exceptions)
        for i in range(len(pending)):
            _settle(i)
        total = sum(t for t in totals if t is not None)
        overflow = any(bool(o) for o in overflows if o is not None)
        _phase_add("fetch_wait_s", time.perf_counter() - tf)
    finally:
        # Also on error: an orphaned worker (wedged in a dead backend
        # put/fetch) would hang the interpreter at exit via
        # ThreadPoolExecutor's atexit join. Bounded teardown instead:
        # join each worker briefly, then report a
        # worker_shutdown_timeout event and detach it from the atexit
        # join (watchdog.shutdown_bounded).
        from distributed_join_tpu.parallel.watchdog import (
            shutdown_bounded,
        )

        shutdown_bounded(pool, "out_of_core.stage")
        shutdown_bounded(fetch_pool, "out_of_core.fetch")
    # Fold in the batches a prior (killed) run already completed —
    # totals only: overflowed entries were filtered back into
    # `pending` above, so `completed` carries no overflow.
    total += sum(v["total"] for v in completed.values())
    if failed and stats is None:
        warnings.warn(
            f"on_batch_failure='continue': batches {sorted(failed)} "
            "were abandoned and the returned total is PARTIAL — pass "
            "a stats dict to receive failed_batches programmatically",
            stacklevel=2,
        )
    if stats is not None:
        stats["elapsed_s"] = time.perf_counter() - t0
        stats["build_capacity"] = bcap
        stats["probe_capacity"] = pcap
        stats["resumed_batches"] = sorted(completed)
        stats["failed_batches"] = sorted(failed)
        stats.update(phase)
    return total, overflow


def keyrange_batched_join(
    build: Table,
    probe: Table,
    comm: Communicator,
    key: str = "key",
    n_batches: int = 4,
    on_batch_result: Optional[Callable] = None,
    warmup: bool = True,
    stats: Optional[dict] = None,
    manifest_path: Optional[str] = None,
    batch_retries: int = 0,
    batch_retry_backoff_s: float = 1.0,
    on_batch_failure: str = "raise",
    verify_integrity: bool = False,
    batch_deadline_s: Optional[float] = None,
    **join_opts,
) -> Tuple[int, bool]:
    """Join arbitrarily large host-resident tables in ``n_batches``
    device-sized pieces; returns (total_matches, any_overflow).
    ``manifest_path``/``batch_retries``/``on_batch_failure``/
    ``verify_integrity``/``batch_deadline_s`` are the checkpoint/
    resume + per-batch recovery/verification knobs of
    :func:`batched_join_host` (binning is deterministic — the same
    tables and ``n_batches`` always rebuild the same batches, which is
    what makes resuming against the manifest sound).

    ``on_batch_result(batch_index, JoinResult)`` can materialize or
    reduce each batch's output; it runs on a dedicated fetch worker
    thread, in batch order, overlapped with the next batch's compute
    (round 5 — see :func:`batched_join_host`), with at most two
    batches' outputs alive at once.
    ``warmup`` runs (and discards) batch 0 once first so the 30-100s
    remote XLA compile stays out of the measured loop; ``stats`` (if a
    dict) receives ``elapsed_s`` — the post-warmup batch-loop wall time
    including H2D staging, the honest out-of-core figure a caller
    should report instead of timing around this whole call.
    Implementation: bins the host copies of ``build``/``probe`` into
    per-batch column blocks and delegates to :func:`batched_join_host`
    (one compile, one-ahead staged H2D, per-batch backpressure).
    """
    keys = [key] if isinstance(key, str) else list(key)
    hb, hp = _host_columns(build), _host_columns(probe)
    bb = key_batch_ids([hb[k] for k in keys], n_batches)
    pb = key_batch_ids([hp[k] for k in keys], n_batches)

    def _bin(cols, ids):
        # Column-at-a-time, releasing each source column as it is
        # binned: peak host overhead is one column plus the index
        # arrays (int32 = half a column-width in total — but only
        # below 2^31 rows; a silent int32 wrap would route rows into
        # wrong batches with wrong data), not a second full copy of
        # the dataset (this path exists for near-RAM tables). The
        # batch masks are resolved to index arrays ONCE, not per
        # (column, batch).
        idx_dt = np.int32 if len(ids) < 2**31 else np.int64
        idx = [np.flatnonzero(ids == b).astype(idx_dt)
               for b in range(n_batches)]
        out = [{} for _ in range(n_batches)]
        for nm in list(cols):
            c = cols.pop(nm)
            for b in range(n_batches):
                out[b][nm] = c[idx[b]]
        return out

    return batched_join_host(
        _bin(hb, bb), _bin(hp, pb), comm, key=key,
        warmup=warmup, stats=stats, on_batch_result=on_batch_result,
        manifest_path=manifest_path, batch_retries=batch_retries,
        batch_retry_backoff_s=batch_retry_backoff_s,
        on_batch_failure=on_batch_failure,
        verify_integrity=verify_integrity,
        batch_deadline_s=batch_deadline_s,
        **join_opts,
    )
