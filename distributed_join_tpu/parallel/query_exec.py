"""Execute a :class:`~..planning.query.QueryPlan` as ONE SPMD program.

The composition property this module banks on: every
:func:`~.distributed_join.make_join_step` step is a *per-rank*
function over collectives — legal anywhere inside ``shard_map``. So a
multi-operator plan lowers by calling the per-op steps sequentially
inside one wrapping function and compiling THAT once with
``comm.spmd``: intermediates are ordinary traced Tables that never
leave the device or the program, each operator's own partition+shuffle
re-shards them by the next key, and XLA schedules the whole chain as a
single executable. One trace, one cache entry, one dispatch per query
— the multi-operator generalization of the seed's "whole pipeline is
ONE compiled SPMD program" stance.

``distributed_query`` is the convenience wrapper mirroring
``distributed_inner_join``: pad + shard the base tables, resolve the
program through an optional :class:`~..service.programs.
JoinProgramCache` (keyed on :class:`QuerySignature`, whose digest
folds the PLAN digest — a repeated query dispatches warm with zero new
traces), and on overflow escalate every operator's capacity factors
together up to ``auto_retry`` times (the whole program is one
executable; per-operator rungs would key a combinatorial signature
space for no measured benefit).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Mapping, Optional

import jax
import jax.numpy as jnp

from distributed_join_tpu import telemetry
from distributed_join_tpu.parallel.distributed_join import (
    DEFAULT_OUT_CAPACITY_FACTOR,
    DEFAULT_SHUFFLE_CAPACITY_FACTOR,
    make_join_step,
)
from distributed_join_tpu.table import Table

__all__ = [
    "QueryResult",
    "QuerySignature",
    "make_query_step",
    "make_distributed_query",
    "distributed_query",
]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class QueryResult:
    """The terminal operator's output plus the chain's aggregate
    health: ``total`` is the final op's count (groups emitted when the
    plan ends in a fused aggregate, matches otherwise), ``op_totals``
    the per-operator counts in plan order, ``overflow`` the OR across
    every operator — any tripped capacity anywhere in the chain raises
    it, so a retry re-runs the WHOLE query (one program, one rung).
    Host-side attributes attached by :func:`distributed_query` (not
    pytree fields): ``plan_digest``, ``cache_hit``, ``retry_attempts``,
    and ``telemetry`` (per-operator Metrics tuple) when instrumented.
    """

    table: Table
    total: jax.Array
    overflow: jax.Array
    op_totals: tuple


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _op_steps(comm, plan, defaults, with_metrics, metrics_static):
    from distributed_join_tpu.ops import aggregate as agg_ops

    steps = []
    for op in plan.ops:
        opts = dict(defaults)
        opts.update(op.opts())
        if op.aggregate is not None:
            opts["aggregate"] = agg_ops.AggregateSpec.from_wire(
                op.aggregate)
        key = list(op.keys) if len(op.keys) > 1 else op.keys[0]
        steps.append(make_join_step(
            comm, key=key, join_type=op.join_type,
            with_metrics=with_metrics,
            metrics_static=metrics_static, **opts))
    return steps


def make_query_step(comm, plan, *, defaults: Optional[dict] = None,
                    with_metrics: bool = False,
                    metrics_static: Optional[dict] = None):
    """Per-rank step for the whole plan: ``step(*tables) ->
    QueryResult`` (or ``(QueryResult, metrics_tuple)`` when
    instrumented — one Metrics block per operator, in plan order).
    ``tables`` arrive in ``plan.tables`` order. Compile with
    :func:`make_distributed_query` / ``comm.spmd``; the matching
    ``sharded_out`` is :func:`query_sharded_out`."""
    defaults = dict(defaults or {})
    op_steps = _op_steps(comm, plan, defaults, with_metrics,
                         metrics_static)
    names = tuple(plan.tables)
    ops = plan.ops
    # Per-operator named scopes: label each operator's region of the
    # lowered program ``query.<op_id>`` so device profiles and the
    # stage profiler's spans attribute wall time to operators. Gated
    # at BUILD time on instrumentation — with telemetry off and
    # with_metrics off the emitted program is the byte-identical seed
    # lowering (the tracing-off parity lock).
    op_scopes = bool(with_metrics or telemetry.enabled())

    def step(*tables):
        if len(tables) != len(names):
            raise TypeError(
                f"query step takes {len(names)} tables "
                f"{list(names)}, got {len(tables)}")
        env = dict(zip(names, tables))
        op_totals = []
        metrics = []
        overflow = jnp.bool_(False)
        res = None
        for op, op_step in zip(ops, op_steps):
            if op_scopes:
                with jax.named_scope(f"query.{op.op_id}"):
                    out = op_step(env[op.build], env[op.probe])
            else:
                out = op_step(env[op.build], env[op.probe])
            if with_metrics:
                res, m = out
                metrics.append(m)
            else:
                res = out
            env[op.op_id] = res.table
            op_totals.append(res.total)
            overflow = overflow | res.overflow
        result = QueryResult(
            table=res.table, total=res.total, overflow=overflow,
            op_totals=tuple(op_totals))
        return (result, tuple(metrics)) if with_metrics else result

    return step


def query_sharded_out(plan, with_metrics: bool = False):
    """The ``comm.spmd`` out-spec for :func:`make_query_step`'s
    return: the result table row-sharded, every psummed count and the
    overflow flag (and the gathered Metrics blocks) replicated."""
    res = QueryResult(table=False, total=True, overflow=True,
                      op_totals=(True,) * len(plan.ops))
    return (res, (True,) * len(plan.ops)) if with_metrics else res


def make_distributed_query(comm, plan, with_metrics=None,
                           metrics_static: Optional[dict] = None,
                           **defaults):
    """Compile the plan over ``comm``'s ranks: a jitted
    ``fn(*tables) -> QueryResult`` taking row-sharded global Tables
    (capacities divisible by n_ranks) in ``plan.tables`` order —
    the whole chain as ONE program. ``with_metrics=None`` resolves
    from the telemetry session; instrumented results carry the
    per-operator Metrics tuple as ``res.telemetry``. ``defaults``
    are join knobs applied to every operator (per-op plan options
    win)."""
    if with_metrics is None:
        with_metrics = telemetry.enabled()
    step = make_query_step(comm, plan, defaults=defaults,
                           with_metrics=with_metrics,
                           metrics_static=metrics_static)
    compiled = comm.spmd(
        step, sharded_out=query_sharded_out(plan, with_metrics))
    if not with_metrics:
        return compiled

    def fn(*tables):
        res, metrics = compiled(*tables)
        object.__setattr__(res, "telemetry", metrics)
        return res

    return fn


# -- cache identity ----------------------------------------------------


@dataclasses.dataclass(frozen=True)
class QuerySignature:
    """Cache identity of one compiled query program: the PLAN digest
    (operators, keys, join types, per-op options — the canonical
    record), the padded base-table avals, the executor-level defaults
    (escalation rung included), and the mesh. Satisfies the
    ``get_keyed`` contract (``digest()``/``canonical()``/name-sorted
    ``options``)."""

    n_ranks: int
    plan_digest: str
    tables: tuple            # (name, schema triples, capacity) per base
    options: tuple           # name-sorted (knob, value) pairs
    n_slices: int = 1

    @classmethod
    def of(cls, comm, plan, tables, **options) -> "QuerySignature":
        from distributed_join_tpu.service.programs import _schema_of

        entries = []
        for name in plan.tables:
            t = tables[name]
            entries.append((
                name, _schema_of(t),
                int(next(iter(t.columns.values())).shape[0])))
        return cls(
            n_ranks=int(comm.n_ranks),
            plan_digest=plan.digest(),
            tables=tuple(entries),
            options=tuple(sorted(options.items())),
            n_slices=int(getattr(comm, "n_slices", 1)),
        )

    def canonical(self) -> dict:
        return dataclasses.asdict(self)

    def digest(self) -> str:
        blob = json.dumps(self.canonical(), sort_keys=True,
                          default=str)
        return hashlib.sha256(blob.encode()).hexdigest()


# -- the one-shot convenience ------------------------------------------


def distributed_query(tables: Mapping[str, Table], plan, comm,
                      auto_retry: int = 0, program_cache=None,
                      with_metrics=None, **defaults) -> QueryResult:
    """Run the whole plan: pad each base table to rank-divisible
    capacity, shard over the mesh, compile (or cache-resolve) the ONE
    program, dispatch. On overflow — anywhere in the chain — double
    every operator's ``shuffle_capacity_factor``/
    ``out_capacity_factor`` and retry, up to ``auto_retry`` times;
    each rung keys its own signature, so a retried sizing seen before
    also dispatches warm. The result carries ``plan_digest``,
    ``cache_hit`` (first attempt resolved resident/persisted) and
    ``retry_attempts`` as host-side attributes."""
    if program_cache is not None and program_cache.comm is not comm:
        raise ValueError(
            "program_cache was built for a different communicator")
    if with_metrics is None:
        with_metrics = telemetry.enabled()

    n = comm.n_ranks
    missing = [name for name in plan.tables if name not in tables]
    if missing:
        raise ValueError(
            f"plan references base tables {missing} not supplied "
            f"(have {sorted(tables)})")
    padded = {
        name: tables[name].pad_to(
            _round_up(tables[name].capacity, n))
        for name in plan.tables
    }
    if hasattr(comm, "device_put_sharded"):
        padded = comm.device_put_sharded(padded)
    args = tuple(padded[name] for name in plan.tables)

    shuffle_f = float(defaults.pop("shuffle_capacity_factor",
                                   DEFAULT_SHUFFLE_CAPACITY_FACTOR))
    out_f = float(defaults.pop("out_capacity_factor",
                               DEFAULT_OUT_CAPACITY_FACTOR))

    first_hit = None
    res = None
    for attempt in range(auto_retry + 1):
        scale = 2 ** attempt
        sizing = dict(defaults,
                      shuffle_capacity_factor=shuffle_f * scale,
                      out_capacity_factor=out_f * scale)
        if program_cache is not None:
            sig = QuerySignature.of(
                comm, plan, padded, with_metrics=bool(with_metrics),
                rung=attempt, **sizing)

            def builder():
                return make_distributed_query(
                    comm, plan, with_metrics=False,
                    metrics_static={"retry_attempt_max": attempt},
                    **sizing) if not with_metrics else _raw_spmd(
                        comm, plan, attempt, sizing)

            entry, hit = program_cache.get_keyed(
                sig, builder, example_args=args,
                with_aux=bool(with_metrics))
            fn = entry
        else:
            fn = make_distributed_query(
                comm, plan, with_metrics=with_metrics,
                metrics_static={"retry_attempt_max": attempt},
                **sizing)
            hit = False
        if first_hit is None:
            first_hit = hit
        res = fn(*args)
        if not bool(res.overflow):
            break
    object.__setattr__(res, "plan_digest", plan.digest())
    object.__setattr__(res, "cache_hit", bool(first_hit))
    object.__setattr__(res, "retry_attempts", attempt)
    return res


def _raw_spmd(comm, plan, attempt, sizing):
    """The UNWRAPPED ``(res, metrics)`` program for cache admission
    with aux: ``CachedProgram`` owns the telemetry re-attachment."""
    step = make_query_step(
        comm, plan, defaults=sizing, with_metrics=True,
        metrics_static={"retry_attempt_max": attempt})
    return comm.spmd(
        step, sharded_out=query_sharded_out(plan, True))
