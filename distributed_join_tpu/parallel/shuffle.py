"""Two-phase all-to-all table shuffle.

The reference's shuffle (SURVEY.md §2 "All-to-all shuffle of a cuDF
table", §3.1) is: exchange per-bucket row counts (metadata all-to-all),
allocate exact-size receive buffers, then per column per peer post
send/recv of the bucket slice. XLA has no dynamic receive sizes, so the
TPU formulation pads each bucket to a static per-destination capacity:

  phase 1: ``all_to_all`` of the (n_ranks,) int32 count vector;
  phase 2: ``all_to_all`` of each column laid out (n_ranks, capacity).

The received block flattens into a validity-masked Table (padding rows
carry the mask, not a sentinel). Overflow — a bucket bigger than the
static capacity — is detected on device and reported so the caller can
retry with a larger pad or engage the skew path (BASELINE config 3).

Bandwidth note: padding inflates bytes on the wire by ~1/load-factor.
For uniform keys capacity_factor ~1.2-1.5 keeps that small; the skew
path exists precisely because one hot bucket would otherwise set the pad
for everyone (SURVEY.md §7 hard part #2).
"""

from __future__ import annotations

from typing import Tuple

import jax

from distributed_join_tpu.ops.partition import PartitionedTable, unpad
from distributed_join_tpu.parallel.communicator import Communicator
from distributed_join_tpu.table import Table


def shuffle_padded(
    comm: Communicator, padded_columns, counts: jax.Array, capacity: int
) -> Tuple[Table, jax.Array]:
    """Shuffle a pre-padded (n_ranks, capacity) block; returns the
    received rows as a masked Table plus the received counts."""
    recv_counts = comm.all_to_all(counts)
    recv_cols = {n: comm.all_to_all(c) for n, c in padded_columns.items()}
    return unpad(recv_cols, recv_counts, capacity), recv_counts


def shuffle_partitioned(
    comm: Communicator, pt: PartitionedTable, capacity: int
) -> Tuple[Table, jax.Array]:
    """Shuffle a table already partitioned into exactly n_ranks buckets.

    Returns (received table, overflow flag). The received table holds
    every row of the global table whose key hashes to this rank, padding
    masked off.
    """
    if pt.n_buckets != comm.n_ranks:
        raise ValueError(
            f"partitioned into {pt.n_buckets} buckets but {comm.n_ranks} ranks"
        )
    padded, counts, overflow, _ = pt.to_padded(capacity)
    table, _ = shuffle_padded(comm, padded, counts, capacity)
    return table, overflow
