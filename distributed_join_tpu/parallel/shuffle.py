"""Two-phase all-to-all table shuffle.

The reference's shuffle (SURVEY.md §2 "All-to-all shuffle of a cuDF
table", §3.1) is: exchange per-bucket row counts (metadata all-to-all),
allocate exact-size receive buffers, then per column per peer post
send/recv of the bucket slice. XLA has no dynamic receive sizes, so the
TPU formulation pads each bucket to a static per-destination capacity:

  phase 1: ``all_to_all`` of the (n_ranks,) int32 count vector;
  phase 2: ``all_to_all`` of each column laid out (n_ranks, capacity).

The received block flattens into a validity-masked Table (padding rows
carry the mask, not a sentinel). Overflow — a bucket bigger than the
static capacity — is detected on device and reported so the caller can
retry with a larger pad or engage the skew path (BASELINE config 3).

Bandwidth note: padding inflates bytes on the wire by ~1/load-factor.
For uniform keys capacity_factor ~1.2-1.5 keeps that small; the skew
path exists precisely because one hot bucket would otherwise set the pad
for everyone (SURVEY.md §7 hard part #2).

:func:`shuffle_ragged` removes the pad bytes entirely — the reference's
exact-size exchange (counts first, then exactly ``count`` rows per
peer), expressed with ``lax.ragged_all_to_all``. Phase 1 becomes an
``all_gather`` of each rank's count vector: the full (n, n) count
matrix is what lets every rank compute, consistently and without more
communication, its send/recv sizes, where its blocks land in every
receiver's buffer, and a deterministic clamp when a receiver's static
output capacity would overflow. The hardware op only exists on TPU
(XLA:CPU has no ragged-all-to-all thunk), so non-TPU backends run a
bit-identical emulation — see ``Communicator.ragged_all_to_all``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from distributed_join_tpu.ops.partition import PartitionedTable, unpad
from distributed_join_tpu.parallel.communicator import Communicator
from distributed_join_tpu.table import Table


def shuffle_padded(
    comm: Communicator, padded_columns, counts: jax.Array, capacity: int,
    via: str = "all_to_all", tape=None, digest_tape=None,
) -> Tuple[Table, jax.Array]:
    """Shuffle a pre-padded (n_ranks, capacity) block; returns the
    received rows as a masked Table plus the received counts.

    ``via='ppermute'`` moves the data blocks over a chain of
    collective-permutes instead of one grouped all-to-all — same
    bytes and result, but an async-schedulable lowering (see
    Communicator.ppermute_all_to_all / docs/OVERLAP.md).

    ``tape`` (a ``telemetry.MetricsTape`` view, or None) receives the
    wire accounting: ``rows_shuffled``/``rows_received`` are ACTUAL
    rows (the count vectors), ``wire_bytes`` the data-plane bytes the
    collective moves — for this padded layout the full static
    ``n_ranks x capacity`` block per column, pad included, because
    that IS what rides the wire (the ~1/load-factor inflation the
    module docstring describes, now measurable per run). Metadata
    (the count exchange) is not billed; see docs/OBSERVABILITY.md.

    ``digest_tape`` (a ``MetricsTape`` view, or None) receives the
    wire-integrity digests (parallel/integrity.py): per destination
    the digest of the rows this rank ROUTED there (computed on the
    pre-exchange block) and per source the digest of the rows it
    BELIEVES it received (the post-exchange block under its own —
    possibly corrupted — received counts); the host-side pair check
    is ``integrity.verify_digests``."""
    a2a = (
        comm.ppermute_all_to_all if via == "ppermute" else comm.all_to_all
    )
    recv_counts = comm.all_to_all(counts)
    recv_cols = {n: a2a(c) for n, c in padded_columns.items()}
    if digest_tape is not None:
        from distributed_join_tpu.parallel import integrity

        integrity.record_pair_digests(
            digest_tape,
            integrity.padded_block_digests(padded_columns, counts),
            integrity.padded_block_digests(recv_cols, recv_counts),
        )
    if tape is not None:
        tape.add("rows_shuffled", jnp.sum(counts.astype(jnp.int64)))
        tape.add("rows_received",
                 jnp.sum(recv_counts.astype(jnp.int64)))
        tape.add("wire_bytes",
                 sum(c.size * c.dtype.itemsize
                     for c in padded_columns.values()))
    return unpad(recv_cols, recv_counts, capacity), recv_counts


def shuffle_padded_compressed(
    comm: Communicator, padded_columns, counts: jax.Array, capacity: int,
    bits: int, block: int = 256, via: str = "all_to_all", tape=None,
    digest_tape=None,
) -> Tuple[Table, jax.Array, jax.Array]:
    """Padded shuffle with the FoR+bitpack codec on the wire.

    The reference's ``--compression`` path: compress each partition
    buffer before the all-to-all, decompress after (SURVEY.md §2
    "nvcomp compression"). Here every integer column's per-destination
    block is encoded row-wise (one frame stream per destination, so
    the all-to-all's leading-axis redistribution never splits a codec
    block across destinations), the uint32 word + int64 frame planes
    ride the collective, and receivers decode.

    Static shapes force the codec's compile-time ``bits`` contract
    (ops/compression.py): a block whose frame-of-reference residual
    exceeds ``bits`` cannot pack losslessly, so the returned
    ``compression_overflow`` flag fires and the caller must retry
    wider (``distributed_inner_join``'s auto_retry doubles bits up to
    32) — rows are never silently corrupted. Measured economics:
    break-even wire bandwidth is ~5-7 GB/s
    (results/compression_for_bitpack.json) — below ICI, so this is an
    opt-in for slow (DCN-class) links, off by default.

    Returns ``(received table, received counts, compression_overflow)``.
    """
    from distributed_join_tpu.ops.compression import (
        Packed,
        for_bitpack_decode,
        for_bitpack_encode,
    )

    a2a = (
        comm.ppermute_all_to_all if via == "ppermute" else comm.all_to_all
    )
    recv_counts = comm.all_to_all(counts)
    n_ranks = counts.shape[0]
    lane = jnp.arange(capacity, dtype=jnp.int32)
    row_valid = lane[None, :] < counts[:, None]
    c_ovf = jnp.bool_(False)
    recv_cols = {}
    # Wire accounting (static — every buffer here is capacity-shaped):
    # raw_bytes is what the UNcompressed padded shuffle would move,
    # sent_bytes what actually rides; the difference is the codec's
    # saving at this bits width (negative = expansion, reportable).
    raw_bytes = 0
    sent_bytes = 0
    for name, col in padded_columns.items():
        compressible = _codec_eligible(name, col)
        raw_bytes += col.size * col.dtype.itemsize
        if not compressible:
            # uint8 string payload planes etc. ride raw.
            sent_bytes += col.size * col.dtype.itemsize
            recv_cols[name] = a2a(col)
            continue

        # Padding slots hold clipped-gather garbage (possibly a mix of
        # a neighboring bucket's rows and the table's zero pad rows) —
        # a block mixing 0 with large-magnitude real values would blow
        # the residual span for data whose REAL residuals are tiny.
        # Fill them with the bucket's last valid row instead (residual
        # 0 against a real frame, the codec's own padding trick).
        fill = col[jnp.arange(n_ranks), jnp.maximum(counts - 1, 0)]
        col = jnp.where(row_valid, col, fill[:, None])

        def _enc(row):
            p = for_bitpack_encode(row, bits, block)
            return p.words, p.frames, p.overflow

        words, frames, ovf = jax.vmap(_enc)(col)
        c_ovf = c_ovf | jnp.any(ovf)
        sent_bytes += (words.size * words.dtype.itemsize
                       + frames.size * frames.dtype.itemsize)
        rwords, rframes = a2a(words), a2a(frames)

        def _dec(w, f, dt=col.dtype):
            return for_bitpack_decode(
                Packed(w, f, None, None, n=capacity, bits=bits,
                       block=block),
                dtype=dt,
            )

        recv_cols[name] = jax.vmap(_dec)(rwords, rframes)
    if digest_tape is not None:
        # Sender digests run on the ORIGINAL block (valid slots are
        # untouched by the codec's pad-fill trick); receiver digests
        # on the decoded block. A lossy encode (residual wider than
        # ``bits``) would mismatch, but it also fires c_ovf — and
        # verification is only consulted on non-overflowed results.
        from distributed_join_tpu.parallel import integrity

        integrity.record_pair_digests(
            digest_tape,
            integrity.padded_block_digests(padded_columns, counts),
            integrity.padded_block_digests(recv_cols, recv_counts),
        )
    if tape is not None:
        tape.add("rows_shuffled", jnp.sum(counts.astype(jnp.int64)))
        tape.add("rows_received",
                 jnp.sum(recv_counts.astype(jnp.int64)))
        tape.add("wire_bytes", sent_bytes)
        tape.add("wire_bytes_saved", raw_bytes - sent_bytes)
    return unpad(recv_cols, recv_counts, capacity), recv_counts, c_ovf


def shuffle_segmented(
    comm: Communicator, padded_fine, fine_counts: jax.Array,
    seg_cap: int, segments: int, via: str = "all_to_all",
    tape=None, digest_tape=None,
):
    """Padded shuffle of a FINE-partitioned block for the
    segmented-sort pipeline (ops/segmented.py, docs/ROOFLINE.md §9):
    ``padded_fine`` holds ``(n_ranks * segments, seg_cap, ...)``
    blocks (destination-major, segment-minor — the contiguous layout
    ``radix_hash_partition(sub_buckets=)``'s fine ordering yields) and
    ``fine_counts`` the matching ``(n_ranks * segments,)`` int32
    counts.

    Each destination's ``segments * seg_cap`` slots ride the wire as
    ONE block — the same collectives, byte-for-byte, as a flat padded
    shuffle of capacity ``segments * seg_cap`` — plus the fine count
    matrix as the (unbilled) metadata exchange, so the receiver can
    mask every (source, segment) prefix. ``via`` selects all_to_all /
    ppermute / hierarchical routing; the hierarchical route moves both
    phases RAW (the DCN codec's pad-fill framing assumes one valid
    prefix per destination block, which the fine layout breaks — the
    caller refuses that combination loudly).

    Returns ``(recv_cols, recv_counts)``: column arrays shaped
    ``(n_src, segments, seg_cap, ...)`` and the received fine counts
    ``(n_src, segments)``. Overflow stays the caller's ``to_padded``
    verdict (a fine bucket exceeding ``seg_cap``).

    ``tape`` billing mirrors :func:`shuffle_padded`: ``wire_bytes`` is
    the full static block (pad included — that IS what rides),
    rows are the fine-count sums. ``digest_tape`` records the same
    per-(src, dst) pair digests as the flat shuffles, computed under
    the fine-count mask (integrity.masked_block_digests) — coarse
    per-peer channels, so ``verify_digests`` reads them unchanged.
    """
    n = comm.n_ranks
    s = segments
    hier = via == "hierarchical" and comm.n_slices > 1
    if hier:
        def route(x):
            return _hier_route(comm, x)

        route_meta = route
    else:
        route = (comm.ppermute_all_to_all if via == "ppermute"
                 else comm.all_to_all)
        route_meta = comm.all_to_all
    recv_counts = route_meta(fine_counts.reshape(n, s))
    recv_cols = {}
    block_bytes = 0
    for name, col in padded_fine.items():
        block = col.reshape((n, s * seg_cap) + col.shape[2:])
        block_bytes += block.size * block.dtype.itemsize
        recv = route(block)
        recv_cols[name] = recv.reshape((n, s, seg_cap)
                                       + col.shape[2:])
    # Hierarchical routing moves every block TWICE (intra-slice ICI
    # hop, then the raw cross-slice DCN hop) — bill both tiers, with
    # the per-tier counters the exact wire gate and the DCN telemetry
    # read, exactly like shuffle_hierarchical's raw path.
    wire_bytes = 2 * block_bytes if hier else block_bytes
    if digest_tape is not None:
        from distributed_join_tpu.parallel import integrity

        lane = jnp.arange(seg_cap, dtype=jnp.int32)
        sent_mask = (lane[None, :]
                     < fine_counts[:, None]).reshape(n, s * seg_cap)
        recv_mask = (lane[None, None, :]
                     < recv_counts[:, :, None]).reshape(n, s * seg_cap)
        integrity.record_pair_digests(
            digest_tape,
            integrity.masked_block_digests(
                {nm: c.reshape((n, s * seg_cap) + c.shape[2:])
                 for nm, c in padded_fine.items()}, sent_mask),
            integrity.masked_block_digests(
                {nm: c.reshape((n, s * seg_cap) + c.shape[3:])
                 for nm, c in recv_cols.items()}, recv_mask),
        )
    if tape is not None:
        tape.add("rows_shuffled",
                 jnp.sum(fine_counts.astype(jnp.int64)))
        tape.add("rows_received",
                 jnp.sum(recv_counts.astype(jnp.int64)))
        tape.add("wire_bytes", wire_bytes)
        if hier:
            tape.add("wire_bytes_ici", block_bytes)
            tape.add("wire_bytes_dcn", block_bytes)
    return recv_cols, recv_counts


def _hier_route(comm: Communicator, x: jax.Array) -> jax.Array:
    """Two-level routing of an ``(n_ranks, ...)`` destination-major
    block: intra-slice all-to-all over ICI, then cross-slice exchange
    over DCN. Returns the ``(n_ranks, ...)`` block received, leading
    axis in SENDER-rank order — the same contract as one global
    ``all_to_all`` of the block, in two tier-local hops.

    Algebra (s slices x c chips, rank r = (r//c, r%c), docs/
    HIERARCHY.md): phase 1 regroups the n = s*c destination blocks by
    destination CHIP — block j of the intra-slice exchange carries
    ``[dest (t, j) for every slice t]`` — so after the ICI hop, chip j
    holds (from each of its c slice-mates) everything destined to chip
    j of ANY slice, indexed ``(src_chip, dest_slice)``. Phase 2
    transposes to destination-slice-major and exchanges over the slice
    axis; the received ``(src_slice, src_chip)`` nesting IS flat
    sender-rank order (slice-major), so one reshape finishes."""
    s, c = comm.n_slices, comm.chips_per_slice
    z = comm.all_to_all_slice(_hier_phase1(comm, x))
    return z.reshape((s * c,) + x.shape[1:])


def _hier_phase1(comm: Communicator, x: jax.Array) -> jax.Array:
    """Phase 1 of :func:`_hier_route` alone: the intra-slice ICI hop,
    returning the ``(dest_slice, src_chip, ...)`` block phase 2
    exchanges — split out so the DCN codec can encode exactly the
    cross-slice payload and nothing else."""
    s, c = comm.n_slices, comm.chips_per_slice
    tail = x.shape[1:]
    y = x.reshape((s, c) + tail).swapaxes(0, 1)
    y = comm.all_to_all_chip(y)
    return y.swapaxes(0, 1)


def shuffle_hierarchical(
    comm: Communicator, padded_columns, counts: jax.Array,
    capacity: int, dcn_bits: Optional[int] = None, block: int = 256,
    tape=None, digest_tape=None,
) -> Tuple[Table, jax.Array, jax.Array]:
    """Two-level shuffle of a pre-padded ``(n_ranks, capacity)`` block
    over a hierarchical ``(slice, chip)`` mesh: slice-local buckets
    ride one intra-slice all-to-all over ICI (fast, always raw), the
    remote buckets then cross slices over DCN — with the FoR+bitpack
    codec from :func:`shuffle_padded_compressed` applied ONLY to that
    cross-slice payload when ``dcn_bits`` is set (the codec's measured
    ~5-7 GB/s break-even sits ABOVE DCN and BELOW ICI, so compression
    flips from NO-GO to win exactly at the tier that needs it —
    docs/ROOFLINE.md, docs/HIERARCHY.md).

    Returns ``(received table, received counts, codec_overflow)`` —
    the received block is bit-identical to :func:`shuffle_padded` of
    the same input (sender-rank order), and ``codec_overflow`` fires
    when a cross-slice residual exceeds ``dcn_bits`` (the caller's
    ladder widens bits, exactly like the compressed flat shuffle;
    always False with the codec off).

    ``tape`` gains the per-tier wire accounting next to the usual
    totals: ``wire_bytes_ici`` (phase 1 — the full static block, pad
    included, exactly what rides ICI), ``wire_bytes_dcn`` (phase 2 —
    codec planes when on, the full block otherwise) and
    ``wire_bytes_saved`` (the codec's saving vs shipping phase 2
    raw); ``wire_bytes`` stays the two-tier sum so every existing
    efficiency indicator keeps reading. ``digest_tape`` records the
    same per-(src, dst) pair digests as the flat padded shuffle —
    end-to-end over both hops, so a corrupted EITHER tier (including
    a misrouted cross-slice exchange) fails verification.
    """
    s, c = comm.n_slices, comm.chips_per_slice
    n = s * c
    if counts.shape[0] != n:
        raise ValueError(
            f"hierarchical shuffle needs {n} destination buckets, "
            f"got {counts.shape[0]}")
    recv_counts = _hier_route(comm, counts)
    lane = jnp.arange(capacity, dtype=jnp.int32)
    row_valid = lane[None, :] < counts[:, None]
    c_ovf = jnp.bool_(False)
    recv_cols = {}
    ici_bytes = 0
    dcn_raw = 0
    dcn_sent = 0
    for name, col in padded_columns.items():
        col_bytes = col.size * col.dtype.itemsize
        ici_bytes += col_bytes
        dcn_raw += col_bytes
        compressible = dcn_bits is not None and _codec_eligible(name,
                                                                col)
        if not compressible:
            dcn_sent += col_bytes
            recv_cols[name] = _hier_route(comm, col)
            continue
        # Same pad-fill trick as shuffle_padded_compressed: padding
        # slots hold clipped-gather garbage whose span would blow the
        # residual width; fill each bucket's pad with its last valid
        # row BEFORE routing (phase 1 moves rows verbatim, so the
        # fill arrives intact at the codec seam).
        fill = col[jnp.arange(n), jnp.maximum(counts - 1, 0)]
        col = jnp.where(row_valid, col, fill[:, None])
        staged = _hier_phase1(comm, col)      # (s, c, capacity)
        from distributed_join_tpu.ops.compression import (
            Packed,
            for_bitpack_decode,
            for_bitpack_encode,
        )

        # One frame stream PER DESTINATION SLICE (rows flattened
        # chip-major), so the slice exchange never splits a codec
        # block across destinations — the flat compressed shuffle's
        # per-destination discipline, one tier up.
        flat = staged.reshape(s, c * capacity)

        def _enc(row):
            p = for_bitpack_encode(row, dcn_bits, block)
            return p.words, p.frames, p.overflow

        words, frames, ovf = jax.vmap(_enc)(flat)
        c_ovf = c_ovf | jnp.any(ovf)
        dcn_sent += (words.size * words.dtype.itemsize
                     + frames.size * frames.dtype.itemsize)
        rwords = comm.all_to_all_slice(words)
        rframes = comm.all_to_all_slice(frames)

        def _dec(w, f, dt=col.dtype):
            return for_bitpack_decode(
                Packed(w, f, None, None, n=c * capacity,
                       bits=dcn_bits, block=block),
                dtype=dt,
            )

        decoded = jax.vmap(_dec)(rwords, rframes)
        recv_cols[name] = decoded.reshape(n, capacity)
    if digest_tape is not None:
        # End-to-end pair digests across both hops (sender commitment
        # on the pre-routing block, receiver belief on the assembled
        # sender-order block) — the same verify_digests contract as
        # the flat shuffles, so a corruption on EITHER tier mismatches.
        from distributed_join_tpu.parallel import integrity

        integrity.record_pair_digests(
            digest_tape,
            integrity.padded_block_digests(padded_columns, counts),
            integrity.padded_block_digests(recv_cols, recv_counts),
        )
    if tape is not None:
        tape.add("rows_shuffled", jnp.sum(counts.astype(jnp.int64)))
        tape.add("rows_received",
                 jnp.sum(recv_counts.astype(jnp.int64)))
        tape.add("wire_bytes", ici_bytes + dcn_sent)
        tape.add("wire_bytes_ici", ici_bytes)
        tape.add("wire_bytes_dcn", dcn_sent)
        if dcn_bits is not None:
            tape.add("wire_bytes_saved", dcn_raw - dcn_sent)
    return (unpad(recv_cols, recv_counts, capacity), recv_counts,
            c_ovf)


def _codec_eligible(name: str, col) -> bool:
    """The FoR+bitpack wire's column eligibility — one rule shared by
    the flat compressed shuffle and the hierarchical DCN tier: 2-D
    integer columns of >= 4-byte lanes, excluding the packed
    string-key word columns (big-endian byte packs whose per-block
    spans exceed any packable width — they would overflow at every
    bits, so they ride raw by construction)."""
    from distributed_join_tpu.utils.strings import _WORD_PREFIX

    return (
        col.ndim == 2
        and jnp.issubdtype(col.dtype, jnp.integer)
        and col.dtype.itemsize >= 4
        and not name.startswith(_WORD_PREFIX)
    )


def ragged_plan(comm: Communicator, counts: jax.Array, out_capacity: int,
                capacity_per_bucket: int | None = None):
    """Phase 1 of the exact-size shuffle: from each rank's (n,) count
    vector, build the consistent transfer plan every rank needs.

    Returns ``(send_sizes, recv_sizes, output_offsets, total_recv,
    overflow)`` where entry i of ``output_offsets`` is where THIS
    rank's block starts in rank i's output buffer. Sizes are clamped
    deterministically (identically on every rank, from the shared
    count matrix) so no write can pass ``out_capacity``; any clamping
    raises the overflow flag on the affected receiver.

    ``capacity_per_bucket`` unifies the capacity CONTRACT with the
    padded shuffle (VERDICT r2 weak #4): when given, the overflow flag
    also fires whenever any single (sender, destination) bucket
    exceeds it — exactly the padded mode's condition — even though the
    pooled buffer could still hold the rows. Rows are still
    transferred whenever they fit (no behavior change on the data
    path, extra varwidth columns included — ADVICE r5); only the flag
    is conservative, so ``auto_retry`` fires under the same conditions
    in every shuffle mode instead of one mode silently accepting a
    layout another would reject.
    """
    send_sizes, recv_sizes, output_offsets, total_recv, overflow, _, _ = \
        _ragged_plan_matrices(comm, counts, out_capacity,
                              capacity_per_bucket)
    return send_sizes, recv_sizes, output_offsets, total_recv, overflow


def _ragged_plan_matrices(comm, counts, out_capacity,
                          capacity_per_bucket=None):
    """ragged_plan + the full (start, allowed) matrices the
    variable-width plane exchange needs, + the receiver-local ACTUAL
    row-clamp flag (distinct from the possibly-conservative overflow
    flag: a ``capacity_per_bucket`` trip fires the flag without
    clamping a single row, and data-destructive recovery like the
    extra-varwidth zeroing must key on the clamp, not the flag —
    ADVICE r5)."""
    n = comm.n_ranks
    me = comm.axis_index()
    # Full count matrix: M[j, i] = rows rank j sends to rank i.
    M = comm.all_gather(counts).reshape(n, n)
    # Receiver-side packing: rank i's buffer concatenates blocks from
    # senders in rank order. start[j, i] = exclusive prefix down col i.
    start = jnp.cumsum(M, axis=0) - M
    allowed = jnp.clip(out_capacity - start, 0, M)
    row_clamped = jnp.any(allowed[:, me] < M[:, me])
    overflow = row_clamped
    if capacity_per_bucket is not None:
        overflow = overflow | jnp.any(M > capacity_per_bucket)
    send_sizes = comm.pvary(allowed[me, :].astype(jnp.int32))
    recv_sizes = comm.pvary(allowed[:, me].astype(jnp.int32))
    output_offsets = comm.pvary(start[me, :].astype(jnp.int32))
    total_recv = jnp.sum(recv_sizes)
    return (send_sizes, recv_sizes, output_offsets, total_recv,
            comm.pvary(overflow), (start, allowed),
            comm.pvary(row_clamped))


def shuffle_ragged(
    comm: Communicator,
    pt: PartitionedTable,
    out_capacity: int,
    bucket_start: int = 0,
    capacity_per_bucket: int | None = None,
    varwidth=None,
    tape=None,
    digest_tape=None,
) -> Tuple[Table, jax.Array]:
    """Exact-size shuffle of ``n_ranks`` buckets starting at
    ``bucket_start``: wire bytes = actual rows, not padded capacity.

    Returns (received table with a valid-prefix mask, overflow flag).
    The received rows pack contiguously in sender-rank order; rows a
    clamped transfer dropped are reported via the flag, never silently
    presented as success.

    ``varwidth`` names 2-D uint8 string column(s) — a name or a
    sequence of names — to ship BYTE-exactly (the reference's
    offsets+chars children exchange, SURVEY.md §2): each of a column's
    width/4 u32 word-planes ships as its own ragged slice of exactly
    ``ceil(len/4)`` words per row, so wire bytes for the column drop
    from ``rows * max_len`` to ``sum(ceil(len/4) * 4)``. The
    plane-prefix layout requires each bucket's rows ordered by THAT
    column's "<name>#len" companion DESCENDING:

    - the FIRST name's order is the caller's contract
      (radix_hash_partition's ``order_within``) — its planes land
      row-aligned at the receiver and reconstruction is free (the
      skipped tail slots stay zero, which IS the fixed-width
      zero-padded representation);
    - every FURTHER column is sorted into its own per-bucket
      length-descending order on the sender (a within-bucket
      permutation; bucket offsets are unchanged) and un-permuted at
      the receiver, which reconstructs the identical permutation from
      the received "#len" companion — the same stable
      (bucket, len desc) sort on both sides, no extra wire bytes
      (round 5; VERDICT r4 weak #5 lifted the one-column limit).
      Under an ACTUALLY clamped transfer the dropped rows differ
      between the row exchange (bucket tail) and a resorted column
      (shortest rows), so per-row alignment of the extra columns
      cannot hold — they are delivered ALL-ZERO on the clamping
      receiver (never silently misaligned; the flag demands a retry).
      A flag-only trip of the conservative ``capacity_per_bucket``
      contract clamps nothing, so the columns arrive intact
      (ADVICE r5: zeroing on the flag destroyed correctly delivered
      data).

    Debug mode (``faults.validate_plans()`` / ``DJTPU_VALIDATE_PLANS``
    at trace time): the transfer plan is cross-rank validated before
    the exchange — see :func:`..faults.validate_ragged_plan`.

    ``tape`` (``telemetry.MetricsTape`` view, or None): wire
    accounting from the PLAN vectors — ``rows_shuffled`` =
    sum(send_sizes) (the clamped plan totals, i.e. rows actually
    transferred), ``rows_received`` = total_recv, ``wire_bytes`` =
    fixed-width row bytes x rows sent plus the varwidth columns'
    exact u32-plane prefix bytes, with the bytes the byte-exact wire
    avoided (vs shipping those columns fixed-width for the same rows)
    in ``wire_bytes_saved``.
    """
    n = comm.n_ranks
    vw = ((varwidth,) if isinstance(varwidth, str)
          else tuple(varwidth or ()))
    counts = pt.counts[bucket_start : bucket_start + n].astype(jnp.int32)
    offsets = pt.offsets[bucket_start : bucket_start + n].astype(jnp.int32)
    (send_sizes, recv_sizes, output_offsets, total_recv, overflow,
     (start, allowed), row_clamped) = _ragged_plan_matrices(
        comm, counts, out_capacity,
        capacity_per_bucket=capacity_per_bucket,
    )
    from distributed_join_tpu.parallel import faults

    if faults.plan_validation_enabled():
        # Inconsistent vectors silently corrupt (emulation) or hang
        # (TPU hardware op); the validation token must stay live in an
        # output or XLA dead-code-eliminates the check.
        tok = faults.validate_ragged_plan(
            comm, send_sizes, recv_sizes, output_offsets, out_capacity,
        )
        overflow = overflow | comm.pvary(tok > 0)
    if tape is not None:
        rows_sent = jnp.sum(send_sizes.astype(jnp.int64))
        row_bytes = sum(
            int(c.size // c.shape[0]) * c.dtype.itemsize
            for name, c in pt.source.columns.items() if name not in vw
        )
        tape.add("rows_shuffled", rows_sent)
        tape.add("rows_received", total_recv.astype(jnp.int64))
        tape.add("wire_bytes", rows_sent * row_bytes)
    # One gather per column materializes the bucket-sorted layout the
    # input offsets point into (no padding, unlike to_padded). The
    # varwidth columns go LAST: the extra ones need their received
    # "#len" companion to reconstruct the sender-side permutation.
    sorted_table = pt.table
    out_cols = {}
    for name, col in sorted_table.columns.items():
        if name in vw:
            continue
        out = jnp.zeros((out_capacity,) + col.shape[1:], col.dtype)
        out_cols[name] = comm.ragged_all_to_all(
            col, out, offsets, send_sizes, output_offsets, recv_sizes
        )
    sorted_vw = varwidth_sort_plan(pt, vw)
    for i, name in enumerate(vw):
        if i == 0:
            # Partition-ordered by this column's len (caller contract).
            out_cols[name] = _varwidth_exchange(
                comm, sorted_table.columns[name],
                sorted_table.columns[name + "#len"],
                offsets, counts, start, allowed, out_capacity,
                tape=tape,
            )
            continue
        col_s, lens_s = sorted_vw[name]
        raw = _varwidth_exchange(
            comm, col_s, lens_s, offsets, counts, start,
            allowed, out_capacity, tape=tape,
        )
        unsorted = _receiver_unsort(
            comm, raw, out_cols[name + "#len"], start, total_recv
        )
        # Under an actual clamp the row exchange drops each bucket's
        # partition-order tail while this length-sorted column drops
        # its SHORTEST rows — different row sets, so the unsort would
        # attach surviving rows to other rows' bytes. Deliver the
        # column EMPTY on this receiver instead (all-zero bytes): the
        # flag already demands a retry, and a caller peeking at
        # partial results must never read silently misaligned strings
        # (review r5). Keyed on the receiver-local ROW CLAMP, not the
        # overflow flag: a conservative capacity_per_bucket trip
        # clamps nothing and must leave delivered data intact
        # (ADVICE r5 / ragged_plan's contract).
        out_cols[name] = jnp.where(row_clamped, 0, unsorted)
    if digest_tape is not None:
        # Wire-integrity digests (parallel/integrity.py). Sender side:
        # per-destination segment sums over the bucket-sorted layout's
        # TRUE local counts — committed before any plan/count exchange
        # could lie. Receiver side: segment sums over the assembled
        # output under the boundaries the receiver PLANS with
        # (start/allowed derive from the gathered count matrix), so a
        # consistently corrupted metadata exchange — which
        # validate_ragged_plan cannot see — still disagrees with the
        # sender's commitment. Rows align across columns here: the row
        # exchange packs in partition order per sender block and the
        # extra varwidth columns were just unsorted back to it. An
        # ACTUAL clamp breaks alignment by design, but it also raises
        # the overflow flag, and verification is only consulted on
        # non-overflowed results.
        from distributed_join_tpu.parallel import integrity

        me = comm.axis_index()
        rd_sent = integrity.row_digests(sorted_table.columns)
        rd_recv = integrity.row_digests(out_cols)
        integrity.record_pair_digests(
            digest_tape,
            integrity.segment_digests(rd_sent, offsets, counts),
            integrity.segment_digests(rd_recv, start[:, me],
                                      allowed[:, me]),
        )
    valid = jnp.arange(out_capacity, dtype=jnp.int32) < total_recv
    return Table(out_cols, valid), overflow


def varwidth_sort_plan(pt: PartitionedTable, names) -> dict:
    """Length-sorted layouts for every varwidth column BEYOND the
    first: {name: (col[perm], lens[perm])} with perm the within-bucket
    length-descending permutation. Batch-independent (the permutation
    covers all k*n buckets at once), so the sort happens ONCE per join
    step here and memoizes on the PartitionedTable — the per-batch
    shuffle_ragged calls reuse it instead of re-sorting k times
    (review r5).

    Cache lifetime contract (ADVICE r5): entries memoize on the
    PartitionedTable instance via ``object.__setattr__`` and key on
    nothing else, so a ``pt`` must not outlive the data its ``order``/
    ``source`` refer to — in practice, the trace (or eager call
    sequence) it was built in; ``radix_hash_partition`` returns a
    fresh ``pt`` per step, which upholds this. What is cached also
    differs by caller mode: under TRACING the gathered wide column is
    cached too (a trace-local intermediate the k per-batch shuffles
    share — composing bucket order with the length permutation gathers
    it once instead of twice per use); for EAGER callers only the
    cheap int32 composed permutation and sorted lengths are cached and
    the wide gather re-runs per call, because caching it would pin a
    full-width sorted copy of every extra string column in memory for
    the pt's lifetime."""
    names = tuple(names or ())[1:]
    if not names:
        return {}
    cache = getattr(pt, "_varwidth_sort_cache", None)
    if cache is None:
        cache = {}
        object.__setattr__(pt, "_varwidth_sort_cache", cache)
    out = {}
    for name in names:
        ent = cache.get(name)
        if ent is None:
            lens_sorted = pt.source.columns[name + "#len"][pt.order]
            perm = _within_bucket_len_order(pt.offsets, lens_sorted)
            order2 = pt.order[perm]
            lens2 = lens_sorted[perm]
            tracing = isinstance(order2, jax.core.Tracer)
            col2 = pt.source.columns[name][order2] if tracing else None
            cache[name] = (order2, lens2, col2)
            ent = cache[name]
        order2, lens2, col2 = ent
        if col2 is None:
            col2 = pt.source.columns[name][order2]
        out[name] = (col2, lens2)
    return out


def _within_bucket_len_order(all_offsets, lens):
    """Permutation putting each bucket's rows in length-DESCENDING
    order, buckets staying in place (stable sort keyed on
    (bucket, -len) — bucket blocks are contiguous, so only rows within
    a bucket move)."""
    from jax import lax

    rows = lens.shape[0]
    idx = jnp.arange(rows, dtype=jnp.int32)
    bid = (
        jnp.searchsorted(
            all_offsets.astype(jnp.int32), idx, side="right"
        ).astype(jnp.int32) - 1
    )
    _, _, perm = lax.sort(
        (bid, -lens.astype(jnp.int32), idx), num_keys=2, is_stable=True
    )
    return perm


def _receiver_unsort(comm, raw, recv_lens, start, total_recv):
    """Undo the sender's within-bucket length sort: the receiver holds
    the same lengths (the '#len' companion rode the ROW exchange, in
    partition order, per sender block), so the identical stable
    (block, len desc) sort reconstructs the sender's permutation with
    zero extra wire bytes. ``raw``'s row i (block-major, len-desc
    within each sender block) belongs at row ``perm[i]``."""
    from jax import lax

    me = comm.axis_index()
    out_capacity = raw.shape[0]
    idx = jnp.arange(out_capacity, dtype=jnp.int32)
    rb = (
        jnp.searchsorted(start[:, me], idx, side="right").astype(
            jnp.int32
        ) - 1
    )
    valid = idx < total_recv
    # Invalid tail rows take key -1: below any real length, so they
    # sort after their block's real rows and consume raw's zero tail.
    key_len = jnp.where(valid, recv_lens.astype(jnp.int32), -1)
    _, _, perm = lax.sort(
        (rb, -key_len, idx), num_keys=2, is_stable=True
    )
    return jnp.zeros_like(raw).at[perm].set(raw)


def _varwidth_exchange(comm, col, lens, offsets, counts, start, allowed,
                       out_capacity: int, tape=None):
    """Byte-exact exchange of one bucket-sorted (rows, L) uint8 column
    whose buckets are ordered by ``lens`` descending. Plane ``w`` of
    the u32 view is alive for exactly the first
    ``k[b, w] = #(len > 4w)`` rows of each bucket."""
    from jax import lax

    n = comm.n_ranks
    me = comm.axis_index()
    rows, L = col.shape
    assert L % 4 == 0, f"varwidth column width {L} must be 4-aligned"
    W = L // 4
    w32 = lax.bitcast_convert_type(
        col.reshape(rows, W, 4), jnp.uint32
    )                                                   # (rows, W)
    # k[b, w]: rows of bucket b alive at plane w — a prefix count,
    # read off a cumulative sum at the bucket boundaries.
    alive = (
        lens[:, None].astype(jnp.int32)
        > (4 * jnp.arange(W, dtype=jnp.int32))[None, :]
    )
    cs = jnp.concatenate(
        [jnp.zeros((1, W), jnp.int32),
         jnp.cumsum(alive.astype(jnp.int32), axis=0)]
    )                                                   # (rows+1, W)
    ends = jnp.minimum(offsets + counts, rows)
    k = cs[ends] - cs[jnp.minimum(offsets, rows)]       # (n, W)
    # Row-level clamping drops each bucket's TAIL — the shortest rows,
    # whose plane contributions are also the tail of every plane
    # prefix — so min(k, allowed_rows) keeps sender/receiver plans
    # consistent with the row exchange.
    gk = comm.all_gather(k).reshape(n, n, W)
    k_allowed = jnp.minimum(gk, allowed[:, :, None])
    if tape is not None:
        # Exact prefix bytes on the wire for this column vs shipping
        # the same (clamped) rows at fixed width — the byte-exact
        # wire's whole point, now a counter.
        exact = 4 * jnp.sum(k_allowed[me].astype(jnp.int64))
        fixed = jnp.sum(allowed[me].astype(jnp.int64)) * L
        tape.add("wire_bytes", exact)
        tape.add("varwidth_bytes", exact)
        tape.add("wire_bytes_saved", fixed - exact)
    out_planes = []
    for w in range(W):
        out = jnp.zeros((out_capacity,), jnp.uint32)
        out_planes.append(comm.ragged_all_to_all(
            w32[:, w], out, offsets,
            comm.pvary(k_allowed[me, :, w].astype(jnp.int32)),
            comm.pvary(start[me, :].astype(jnp.int32)),
            comm.pvary(k_allowed[:, me, w].astype(jnp.int32)),
        ))
    out32 = jnp.stack(out_planes, axis=1)               # (out_cap, W)
    return lax.bitcast_convert_type(out32, jnp.uint8).reshape(
        out_capacity, L
    )


def shuffle_partitioned(
    comm: Communicator, pt: PartitionedTable, capacity: int
) -> Tuple[Table, jax.Array]:
    """Shuffle a table already partitioned into exactly n_ranks buckets.

    Returns (received table, overflow flag). The received table holds
    every row of the global table whose key hashes to this rank, padding
    masked off.
    """
    if pt.n_buckets != comm.n_ranks:
        raise ValueError(
            f"partitioned into {pt.n_buckets} buckets but {comm.n_ranks} ranks"
        )
    padded, counts, overflow, _ = pt.to_padded(capacity)
    table, _ = shuffle_padded(comm, padded, counts, capacity)
    return table, overflow
