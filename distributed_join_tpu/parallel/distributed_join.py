"""The distributed inner-join orchestrator.

TPU re-design of the reference's ``distributed_inner_join``
(SURVEY.md §2/§3.1): partition both tables -> all-to-all shuffle ->
local join, with over-decomposition batching. Two deliberate departures
from the reference's shape:

- The whole pipeline is ONE compiled SPMD program (``jit(shard_map)``).
  The reference hand-pipelines comm of batch b+1 against the join of
  batch b on CUDA streams with helper threads. Round 2 MEASURED what
  XLA does with the unrolled batch loop on the v5e toolchain: the
  all-to-alls lower as SYNCHRONOUS HLO ops scheduled back to back —
  no async start/done pairs, no comm/compute interleaving (the
  compiled-schedule artifacts and what explicit overlap would take
  are in docs/OVERLAP.md). Over-decomposition here therefore buys
  memory capping, not overlap.
- That second purpose — capping resident shuffled data at 1/k of the
  table (the reference's answer to tables bigger than device memory,
  SURVEY.md §5 "Long-context") — is preserved: each batch materializes
  only its own shuffle buffers and join output block. The overlap that
  IS real and measured lives in the host staging thread of
  parallel/out_of_core.py.

Bucket arithmetic: with n ranks and over-decomposition factor k, rows
hash into ``bucket = h % (k*n)``; ``dest = bucket % n`` and
``batch = bucket // n``, so a sorted-by-bucket layout is batch-major and
each batch's n destination buckets are contiguous — one partition sort
serves all k batches. Matching keys share h, hence share (dest, batch):
batches join independently.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax.numpy as jnp

from distributed_join_tpu import telemetry
from distributed_join_tpu.ops.join import (
    JOIN_TYPES,
    JoinResult,
    patch_string_lengths,
    sort_merge_inner_join,
)
from distributed_join_tpu.ops.partition import radix_hash_partition
from distributed_join_tpu.parallel.communicator import Communicator
from distributed_join_tpu.parallel.shuffle import (
    shuffle_hierarchical,
    shuffle_padded,
    shuffle_padded_compressed,
    shuffle_ragged,
)
from distributed_join_tpu.table import Table


DEFAULT_SHUFFLE_CAPACITY_FACTOR = 1.6
DEFAULT_OUT_CAPACITY_FACTOR = 1.2
DEFAULT_HH_SLOTS = 64
HH_BUILD_SLOTS_PER_HH = 32  # default hh_build_capacity = slots * this
SHUFFLE_MODES = ("padded", "ragged", "ppermute", "hierarchical")
SORT_MODES = ("flat", "segmented")
# Residual width the hierarchical DCN codec starts at when the caller
# set dcn_codec on/auto but no compression_bits — the flat driver's
# own --compression-bits default; the ladder widens it on overflow.
DEFAULT_DCN_CODEC_BITS = 16

# The one sharded_out spec for a JoinResult: table row-sharded, the
# psummed total/overflow replicated.
JOIN_SHARDED_OUT = JoinResult(table=False, total=True, overflow=True)
# The metrics-emitting step returns (JoinResult, Metrics); the Metrics
# block is replicated by construction (one in-program all_gather).
JOIN_METRICS_SHARDED_OUT = (JOIN_SHARDED_OUT, True)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _varwidth_cols(table: Table) -> list:
    """ALL 2-D uint8 columns with a '<name>#len' companion and
    4-aligned width — the columns the ragged shuffle ships
    byte-exactly (round 5 lifted the old one-per-table limit: the
    first rides the partition's order_within; further ones are
    within-bucket length-sorted by the shuffle itself, see
    shuffle.shuffle_ragged)."""
    return [
        name for name, c in table.columns.items()
        if (c.ndim == 2 and c.dtype == jnp.uint8
            and c.shape[1] % 4 == 0
            and name + "#len" in table.columns)
    ]


def _batch_shuffle(comm, pt, batch: int, n_ranks: int, capacity: int,
                   mode: str = "padded",
                   compression_bits: Optional[int] = None,
                   varwidth=None, tape=None, digest_tape=None,
                   dcn_codec_on: bool = False):
    if mode == "hierarchical":
        padded, counts, overflow, _ = pt.to_padded(
            capacity, bucket_start=batch * n_ranks, n_buckets=n_ranks
        )
        if comm.n_slices == 1:
            # Degenerate hierarchy: one slice = one ICI domain = the
            # flat padded path, byte-identically (no phase-2 identity
            # hop, no codec — there is no cross-slice payload to
            # compress). Lowering-locked in tests/test_hierarchy.py.
            table, _ = shuffle_padded(comm, padded, counts, capacity,
                                      tape=tape,
                                      digest_tape=digest_tape)
            return table, overflow
        dcn_bits = ((compression_bits or DEFAULT_DCN_CODEC_BITS)
                    if dcn_codec_on else None)
        table, _, c_ovf = shuffle_hierarchical(
            comm, padded, counts, capacity, dcn_bits=dcn_bits,
            tape=tape, digest_tape=digest_tape)
        return table, overflow | c_ovf
    if mode == "ragged":
        # Exact-size exchange: receive buffer = the same total rows the
        # padded layout would flatten to, but wire bytes = actual rows.
        # capacity_per_bucket aligns the overflow contract with padded
        # mode: auto_retry fires under identical conditions.
        return shuffle_ragged(
            comm, pt, n_ranks * capacity, bucket_start=batch * n_ranks,
            capacity_per_bucket=capacity, varwidth=varwidth, tape=tape,
            digest_tape=digest_tape,
        )
    padded, counts, overflow, _ = pt.to_padded(
        capacity, bucket_start=batch * n_ranks, n_buckets=n_ranks
    )
    via = "ppermute" if mode == "ppermute" else "all_to_all"
    if compression_bits is not None:
        table, _, c_ovf = shuffle_padded_compressed(
            comm, padded, counts, capacity, bits=compression_bits,
            via=via, tape=tape, digest_tape=digest_tape,
        )
        return table, overflow | c_ovf
    table, _ = shuffle_padded(comm, padded, counts, capacity, via=via,
                              tape=tape, digest_tape=digest_tape)
    return table, overflow


def _batch_shuffle_segmented(comm, pt, batch: int, n_ranks: int,
                             segments: int, seg_cap: int, mode: str,
                             tape=None, digest_tape=None):
    """One batch of the segmented-sort exchange: the fine-partitioned
    table pads per (destination, segment) fine bucket and rides the
    wire as one block per destination (parallel/shuffle.
    shuffle_segmented). Returns ``(recv_cols (n, s, seg_cap, ...),
    recv_fine_counts (n, s), overflow)`` — overflow fires when any
    fine bucket exceeds ``seg_cap``, the flat capacity contract one
    level down (the same ladder escalation relieves it)."""
    from distributed_join_tpu.parallel.shuffle import shuffle_segmented

    padded, counts, overflow, _ = pt.to_padded(
        seg_cap, bucket_start=batch * n_ranks * segments,
        n_buckets=n_ranks * segments,
    )
    if mode == "hierarchical" and comm.n_slices == 1:
        # Degenerate hierarchy: one slice = the flat padded route,
        # exactly like _batch_shuffle's degenerate branch.
        mode = "padded"
    via = {"padded": "all_to_all", "ppermute": "ppermute",
           "hierarchical": "hierarchical"}[mode]
    recv_cols, recv_counts = shuffle_segmented(
        comm, padded, counts, seg_cap, segments, via=via,
        tape=tape, digest_tape=digest_tape,
    )
    return recv_cols, recv_counts, overflow


def make_join_step(
    comm: Communicator,
    key: str = "key",
    join_type: str = "inner",
    over_decomposition: int = 1,
    shuffle_capacity_factor: float = DEFAULT_SHUFFLE_CAPACITY_FACTOR,
    out_capacity_factor: float = DEFAULT_OUT_CAPACITY_FACTOR,
    out_rows_per_rank: Optional[int] = None,
    build_payload: Optional[Sequence[str]] = None,
    probe_payload: Optional[Sequence[str]] = None,
    skew_threshold: Optional[float] = None,
    hh_slots: int = DEFAULT_HH_SLOTS,
    hh_build_capacity: Optional[int] = None,
    hh_probe_capacity: Optional[int] = None,
    hh_out_capacity: Optional[int] = None,
    shuffle: str = "padded",
    compression_bits: Optional[int] = None,
    dcn_codec: str = "auto",
    sort_mode: str = "flat",
    sort_segments: Optional[int] = None,
    aggregate=None,
    kernel_config=None,
    with_metrics: bool = False,
    with_integrity: bool = False,
    metrics_static: Optional[dict] = None,
):
    """The raw per-rank join step (partition -> shuffle -> local join).

    ``join_type`` (docs/QUERY.md): ``inner`` (default — the exact seed
    program, byte-for-byte) or one of ``left``/``right``/``full_outer``
    /``semi``/``anti`` (ops.join.JOIN_TYPES). Probe is the preserved
    ("left") side. Typed emission is purely local to each bucket's
    sort-merge — hash partitioning already co-locates every key's rows
    from both sides — so all shuffle modes and over-decomposition
    compose unchanged. Three shapes refuse by name: the skew sidecar
    (broadcast heavy-hitter build rows would emit unmatched once per
    rank), aggregate pushdown (no NULL-row emission in the fused
    reduction), and ``sort_mode='segmented'``.

    ``sort_mode`` ("flat"/"segmented"): "flat" is the exact existing
    pipeline, byte-for-byte. "segmented" is the segmented-sort path
    (ops/segmented.py, docs/ROOFLINE.md §9): sub-bucket hash bits ride
    the sender's existing partition sort as extra key bits, the padded
    wire carries static per-(source, segment) fine blocks, and the
    receiver sorts all segments as one batched short-run ``lax.sort``
    (the §6 run-length regime) with the scan/compact/expand stages
    batched per segment — each segment owns its share of the output
    capacity and the shared ladder relieves any segment overflow.
    ``sort_segments`` overrides the segment count per (batch, rank)
    receive (default: ``ops.segmented.resolve_sort_segments`` from the
    table shapes — THE shared resolution the plan mirrors). The result
    is the same row multiset as the flat path (graded bit-exact in
    tests/test_sortpath.py); row order is segment-major.
    Unsupported combinations refuse loudly, never fall back: the
    ragged exchange (dynamic boundaries — no static segments), the
    compressed wire and the hierarchical DCN codec (the codec's
    per-destination frame streams assume one valid prefix per block),
    aggregate pushdown (the fused reduction rides the flat sorts),
    and ``kernel_config`` (it tunes the flat Pallas pipeline the
    batched XLA formulation never runs). A one-segment resolution or
    a single-bucket (n*k == 1) mesh lowers to the flat path.

    ``aggregate`` (an :class:`~..ops.aggregate.AggregateSpec`, or
    None): the FUSED join+aggregate pipeline (docs/AGGREGATION.md).
    The step then reduces in the merged/compacted domain — segment
    scans ride the join's own sorts — and NEVER runs the output
    row-gathers that dominate materialization (docs/ROOFLINE.md
    §1-§3): only the columns the reduction reads are partitioned and
    shuffled, the local result is a per-group PARTIALS block of
    ``ops.aggregate.resolve_groups_capacity`` rows, and the returned
    ``JoinResult.table`` holds finalized per-group aggregates (group
    keys, aggregate outputs, carries; ``.valid`` marks real groups)
    instead of joined rows. ``total`` stays the row count the
    materializing join WOULD have produced — free from the run
    algebra, and the oracle/accounting anchor. Group keys equal to the
    join keys ("key mode") are co-located by hash partitioning, so
    per-rank partials are final — no second exchange; probe-side
    group-bys ("probe mode") exchange only the tiny per-group partials
    (one groups-sized padded collective — hierarchical routing on a
    multi-slice mesh — billed under the ``partials.*`` counters), so
    wire bytes collapse from O(output rows) to O(groups). A partials
    block too small for the distinct groups raises the overflow flag
    (rows are dropped loudly, never wrong sums); the ladder's
    out-capacity escalation grows the derived block. Shapes the fused
    pipeline cannot cover (the skew sidecar, string/2-D keys, explicit
    payload lists, build-side group-bys...) refuse with a named
    :class:`~..ops.aggregate.AggregatePushdownUnsupported` — callers
    fall back to the materializing join.

    ``shuffle``: "padded" (capacity-padded all_to_all, the default),
    "ragged" (exact-size ``lax.ragged_all_to_all`` — wire bytes equal
    actual rows), "ppermute" (padded blocks over a collective-permute
    chain whose lowering the scheduler can overlap with compute;
    docs/OVERLAP.md), or "hierarchical" (the two-level ICI/DCN
    shuffle over a multi-slice mesh: slice-local buckets ride one
    intra-slice all-to-all, remote buckets cross slices — with the
    FoR+bitpack codec on exactly that slow tier when ``dcn_codec``
    resolves on; docs/HIERARCHY.md). On a one-slice communicator the
    hierarchical mode lowers byte-identically to "padded" (the
    degenerate hierarchy, lowering-locked).

    ``dcn_codec`` ("off"/"auto"/"on", hierarchical mode only): the
    cross-slice codec knob. "auto" (default) resolves statically
    against the cost model — on exactly when the configured DCN
    bandwidth sits below the codec's measured ~5-7 GB/s break-even
    (planning.cost.resolve_dcn_codec). The residual width is
    ``compression_bits`` (default 16 when unset); a cross-slice
    residual overflow raises the overflow flag and the ladder widens
    bits, exactly like the flat compressed shuffle.

    ``compression_bits``: when set, integer columns ride the padded/
    ppermute shuffle FoR+bitpacked at this width (the reference's
    ``--compression`` / nvcomp path; shuffle.shuffle_padded_compressed).
    A residual wider than ``bits`` raises the overflow flag —
    ``auto_retry`` widens up to 32 — never corrupts rows. Opt-in only:
    measured break-even wire bandwidth (~5-7 GB/s,
    results/compression_for_bitpack.json) is below ICI.

    ONE capacity contract across all modes: the unit of capacity is
    the per-(sender, destination) bucket,
    ``ceil(rows/(k*n)) * shuffle_capacity_factor``, and the overflow
    flag fires whenever any bucket exceeds it — so ``auto_retry``
    fires under identical conditions whichever mode is selected.
    Ragged mode's receive buffer additionally pools to
    ``n_ranks x capacity`` and clamps deterministically at the pooled
    bound (rows a clamp drops are always flagged); its flag is
    CONSERVATIVE relative to what its pooling could physically hold —
    the price of mode-independent retry semantics. The ragged hardware
    op exists only on TPU; other backends transparently run the
    bit-identical emulation (Communicator.ragged_all_to_all).

    Returns ``step(build_local, probe_local) -> JoinResult`` meant to run
    inside ``comm.spmd`` (collectives are unresolved outside it). Exposed
    separately from :func:`make_distributed_join` so harnesses can wrap
    extra structure around the step before compiling — e.g. ``bench.py``
    chains K dependent steps in one ``lax.fori_loop`` for honest timing
    over this environment's RPC relay.

    Static capacities (the XLA dynamic-shape answer, SURVEY.md §7):
    - shuffle pad per (batch, destination) bucket =
      ceil(local_rows / (k * n)) * shuffle_capacity_factor;
    - join output block per batch = probe rows per batch *
      out_capacity_factor (or out_rows_per_rank / k if given).
    Overflow of either capacity is reported, never silently dropped
    rows presented as success.

    Skew handling (BASELINE config 3; :mod:`..parallel.skew`): pass
    ``skew_threshold`` — a key becomes a heavy hitter when its global
    probe count exceeds ``skew_threshold * local_probe_rows``. HH probe
    rows skip the shuffle and stay local, compacted into an
    ``hh_probe_capacity`` block (default 1/8 of local probe rows —
    streaming-kernel packed on TPU, so the HH join's cost scales with
    the block, not the full probe; round-3 VERDICT #2); HH build rows
    are broadcast (``hh_build_capacity`` slots per rank, default
    ``hh_slots * 32``) and joined locally into an extra output block
    of ``hh_out_capacity`` rows (default 1/4 of local probe rows).
    Heavy-hitter mass above these (Zipf alpha >= ~1.4 puts ~90% of
    probe rows in the top keys) overflows and is caught by the flag /
    ``auto_retry`` doubling; size them explicitly for known-heavy
    workloads.

    Telemetry (docs/OBSERVABILITY.md): ``with_metrics=True`` makes the
    step return ``(JoinResult, telemetry.Metrics)`` — device-side
    counters (rows partitioned/shuffled, wire bytes, per-bucket
    overflow margin, match count) accumulated on a
    :class:`~..telemetry.metrics.MetricsTape` and cross-rank gathered
    once at step end; ``metrics_static`` merges caller-known constants
    (e.g. the retry attempt index) into the same vector. The default
    (``False``) compiles the exact seed program — no aux output, no
    extra collective (tests/test_telemetry.py locks the treedef and
    program count). Stage spans (`partition`/`shuffle`/`join`) are
    emitted whenever a telemetry session is active; inside this traced
    step they time TRACING and carry the pipeline structure into the
    Chrome trace, while their ``jax.named_scope`` lines the same names
    up against real device timings in an XLA profile.

    Wire integrity (docs/FAILURE_SEMANTICS.md "Integrity contract"):
    ``with_integrity=True`` additionally computes order-invariant
    per-(src-rank, dst-rank) payload digests inside each shuffle
    (parallel/integrity.py) and ships them in the SAME aux Metrics
    block — the step returns ``(JoinResult, Metrics)`` exactly as
    ``with_metrics`` does, with no further collective (the digests
    ride the step-end all_gather). Verify host-side with
    ``integrity.verify_digests``; with both switches off this is still
    the exact seed program.
    """
    n = comm.n_ranks
    k = over_decomposition
    if k < 1:
        raise ValueError("over_decomposition must be >= 1")
    if join_type not in JOIN_TYPES:
        raise ValueError(
            f"unknown join_type {join_type!r}; expected one of "
            f"{JOIN_TYPES}")
    if join_type != "inner":
        # The typed variants ride the same hash partitioning — every
        # key's build AND probe rows land in one bucket, so unmatched
        # rows are locally visible — but three shapes would break that
        # locality (or double-emit) and refuse by name, mirroring the
        # aggregate-pushdown discipline:
        if skew_threshold is not None:
            raise ValueError(
                f"join_type={join_type!r} does not combine with the "
                "skew sidecar: broadcast heavy-hitter build rows are "
                "replicated on every rank, so an unmatched heavy "
                "build row would emit once PER RANK — run typed joins "
                "without skew_threshold")
        if aggregate is not None:
            raise ValueError(
                f"join_type={join_type!r} does not combine with "
                "aggregate pushdown: the fused reduction counts "
                "matches in the merged domain and has no NULL-row "
                "emission — aggregate over a materialized typed join "
                "instead")
        if sort_mode == "segmented":
            raise ValueError(
                f"join_type={join_type!r} is not part of the "
                "segmented-sort path (the batched short-run "
                "formulation emits matches only) — use "
                "sort_mode='flat'")
    if shuffle not in SHUFFLE_MODES:
        # Validate for EVERY config — the single-rank path never
        # reaches the shuffle, and a typo'd mode must not silently
        # report success.
        raise ValueError(f"unknown shuffle mode {shuffle!r}")
    if compression_bits is not None and shuffle == "ragged":
        raise ValueError(
            "compression applies to the padded/ppermute shuffles; the "
            "ragged exchange already sends exact rows (combining the "
            "two is unimplemented)"
        )
    from distributed_join_tpu.planning.cost import resolve_dcn_codec

    if shuffle == "hierarchical":
        if compression_bits is not None and dcn_codec == "off":
            raise ValueError(
                "dcn_codec='off' contradicts compression_bits="
                f"{compression_bits}: the hierarchical mode's codec "
                "rides ONLY the cross-slice tier (the flat-tier codec "
                "is a measured NO-GO on ICI) — drop the bits or the "
                "knob")
        dcn_on = resolve_dcn_codec(dcn_codec)
    else:
        # The knob is hierarchical-only; still validate the VALUE so
        # a typo'd config fails loudly everywhere.
        resolve_dcn_codec(dcn_codec)
        dcn_on = False
        if n > 1 and getattr(comm, "n_slices", 1) > 1:
            raise ValueError(
                f"shuffle {shuffle!r} routes one GLOBAL collective "
                "over a multi-slice mesh, dragging intra-slice "
                "traffic across DCN — use shuffle='hierarchical' "
                "(or a flat 1-D communicator)")
    nb = k * n

    if sort_mode not in SORT_MODES:
        raise ValueError(
            f"unknown sort_mode {sort_mode!r}; pick one of {SORT_MODES}")
    if sort_segments is not None and int(sort_segments) < 1:
        raise ValueError("sort_segments must be >= 1")
    if sort_mode == "flat" and sort_segments is not None:
        raise ValueError(
            "sort_segments applies to sort_mode='segmented' only — "
            "the flat pipeline never reads it, and silently ignoring "
            "it would cache one byte-identical program per value "
            "(the signature binds every keyword); drop the knob or "
            "pass sort_mode='segmented'")
    if sort_mode == "segmented":
        if shuffle == "ragged":
            raise ValueError(
                "sort_mode='segmented' needs static per-(source, "
                "segment) receive boundaries; the ragged exchange "
                "packs exact-size blocks whose boundaries only exist "
                "at run time — use shuffle='padded'/'ppermute' (or "
                "sort_mode='flat')")
        if compression_bits is not None:
            raise ValueError(
                "sort_mode='segmented' does not combine with the "
                "compressed wire: the codec's per-destination frame "
                "streams assume one valid prefix per block, which "
                "the fine-bucket layout breaks — drop "
                "compression_bits (or use sort_mode='flat')")
        if (shuffle == "hierarchical" and dcn_on
                and getattr(comm, "n_slices", 1) > 1):
            # Topology-gated like resolve_dcn_bits: one slice has no
            # cross-slice payload, so the degenerate hierarchy is
            # codec-free and segments fine.
            raise ValueError(
                "sort_mode='segmented' does not combine with the "
                "hierarchical DCN codec (same per-block framing "
                "problem as compression_bits) — pass dcn_codec='off' "
                "(or sort_mode='flat')")
        if kernel_config is not None:
            raise ValueError(
                "sort_mode='segmented' ignores kernel_config (the "
                "knob tunes the flat Pallas expand/compact pipeline; "
                "the segmented path is the batched XLA formulation) "
                "— drop the knob (silently ignoring it would cache "
                "one byte-identical program per value)")

    keys = [key] if isinstance(key, str) else list(key)

    if aggregate is not None:
        from distributed_join_tpu.ops import aggregate as agg_ops

        if not isinstance(aggregate, agg_ops.AggregateSpec):
            raise TypeError(
                "aggregate must be an ops.aggregate.AggregateSpec "
                f"(got {type(aggregate).__name__}); build one with "
                "AggregateSpec.of(group_by, aggs, ...)")
        if sort_mode == "segmented":
            raise agg_ops.AggregatePushdownUnsupported(
                "aggregate pushdown unsupported under "
                "sort_mode='segmented': the fused reduction rides "
                "the flat pipeline's own sorts — run aggregates with "
                "sort_mode='flat'")
        if skew_threshold is not None:
            raise agg_ops.AggregatePushdownUnsupported(
                "aggregate pushdown unsupported: the skew sidecar "
                "joins heavy hitters through a separate output block "
                "the fused reduction does not cover — run skewed "
                "workloads through the materializing join")
        if build_payload is not None or probe_payload is not None:
            raise agg_ops.AggregatePushdownUnsupported(
                "aggregate pushdown unsupported: explicit payload "
                "lists conflict with the pushdown's own wire-column "
                "resolution (ops.aggregate.wire_columns resolves "
                "exactly the columns the reduction reads)")
        if kernel_config is not None:
            raise agg_ops.AggregatePushdownUnsupported(
                "aggregate pushdown unsupported: kernel_config tunes "
                "the materializing expand/compact gathers the fused "
                "reduction never runs — drop the knob (silently "
                "ignoring it would cache one program per value)")
        return _make_join_agg_step(
            comm, aggregate, keys=keys, k=k,
            shuffle_capacity_factor=shuffle_capacity_factor,
            out_capacity_factor=out_capacity_factor,
            out_rows_per_rank=out_rows_per_rank,
            shuffle=shuffle, compression_bits=compression_bits,
            dcn_on=dcn_on, with_metrics=with_metrics,
            with_integrity=with_integrity,
            metrics_static=metrics_static)

    def step(build_local: Table, probe_local: Table):
        # The integrity digests ride the same Metrics slot, so either
        # switch materializes the tape (and the aux output).
        tape = telemetry.MetricsTape() if (with_metrics
                                           or with_integrity) else None
        if tape is not None:
            for mname, mval in (metrics_static or {}).items():
                tape.add(mname, int(mval))
        for kname in keys:
            bdt = build_local.columns[kname].dtype
            pdt = probe_local.columns[kname].dtype
            if bdt != pdt:
                # Hash routing is dtype-dependent: a mismatch would
                # shuffle equal keys apart and silently lose matches.
                raise TypeError(
                    f"key {kname!r} dtype mismatch: build {bdt} vs probe {pdt}"
                )

        # String keys: pack 2-D byte key columns into uint64 words ONCE,
        # before hashing/partitioning — every stage downstream (hash,
        # partition sort, shuffle, local join) then sees an ordinary
        # composite scalar key, and the key bytes ride the wire packed
        # (not duplicated; the build side's dead '#len' companion is
        # dropped before the shuffle). Reconstructed exactly on exit.
        from distributed_join_tpu.utils.strings import (
            prepare_string_key_join,
        )

        (build_local, probe_local, keys_eff, bpay, ppay,
         str_spec) = prepare_string_key_join(
            build_local, probe_local, keys, build_payload,
            probe_payload,
        )
        sk_names = tuple(
            nm for _, wns, _ in str_spec for nm in wns
        )
        b_rows, p_rows = build_local.capacity, probe_local.capacity
        b_cap = _round_up(int(math.ceil(b_rows / nb * shuffle_capacity_factor)), 8)
        p_cap = _round_up(int(math.ceil(p_rows / nb * shuffle_capacity_factor)), 8)
        if out_rows_per_rank is not None:
            out_cap = _round_up(int(math.ceil(out_rows_per_rank / k)), 8)
        else:
            # received probe rows per batch can reach n * p_cap
            out_cap = _round_up(
                int(math.ceil(p_rows / k * out_capacity_factor)), 8
            )

        parts = []
        total = jnp.int64(0)
        overflow = jnp.bool_(False)

        if skew_threshold is not None:
            from distributed_join_tpu.ops.hashing import hash_columns
            from distributed_join_tpu.parallel import skew

            with telemetry.span("skew"):
                # Detect/mark heavy hitters on the uint64 key-tuple
                # hash: classification only needs to be CONSISTENT
                # across sides and ranks (hash collisions merely
                # over-classify a key as heavy, which stays correct —
                # the HH join matches on the real composite key).
                bh = hash_columns(
                    [build_local.columns[k] for k in keys_eff])
                ph = hash_columns(
                    [probe_local.columns[k] for k in keys_eff])
                hh = skew.global_heavy_hitters(
                    comm,
                    ph,
                    probe_local.valid,
                    hh_slots,
                    threshold=jnp.int32(int(skew_threshold * p_rows)),
                )
                is_hh_b = skew.mark_heavy(bh, hh)
                is_hh_p = skew.mark_heavy(ph, hh)
                hh_build, ovf_hb = skew.broadcast_heavy_build(
                    comm, build_local, is_hh_b,
                    hh_build_capacity or hh_slots * HH_BUILD_SLOTS_PER_HH,
                    kernel_config=kernel_config,
                )
                # HH probe rows stay local, COMPACTED into a
                # right-sized block first (round-3 VERDICT #2:
                # narrowing validity on the full-capacity arrays made
                # the HH join re-sort all p_rows to join a
                # typically-tiny subset — the whole HH path then cost
                # ~90% of the join even with zero heavy keys).
                # Overflowing the block raises the flag; auto_retry
                # doubles it like every other capacity.
                hh_probe_cap = _round_up(
                    hh_probe_capacity or max(p_rows // 8, 1024), 8
                )
                hh_probe, _, ovf_hp = skew.extract_prefix(
                    probe_local, probe_local.valid & is_hh_p,
                    hh_probe_cap, kernel_config=kernel_config,
                )
                hh_res = sort_merge_inner_join(
                    hh_build, hh_probe, keys_eff,
                    hh_out_capacity or max(p_rows // 4, 1024),
                    build_payload=bpay, probe_payload=ppay,
                    kernel_config=kernel_config,
                    _internal=sk_names,
                )
                parts.append(hh_res.table)
                total = total + hh_res.total.astype(jnp.int64)
                overflow = overflow | ovf_hb | ovf_hp | hh_res.overflow
                if tape is not None:
                    tape.add("skew.hh_matches",
                             hh_res.total.astype(jnp.int64))
                # The normal path sees neither side's HH rows.
                build_local = Table(build_local.columns,
                                    build_local.valid & ~is_hh_b)
                probe_local = Table(probe_local.columns,
                                    probe_local.valid & ~is_hh_p)

        seg = 1
        if sort_mode == "segmented" and nb > 1:
            from distributed_join_tpu.ops import segmented as seg_ops

            # THE shared resolution (plan mirrors it): one segment
            # count for both sides — segments must be the same hash
            # classes on build and probe or matches would cross them.
            seg = seg_ops.resolve_sort_segments(
                sort_segments, max(b_rows, p_rows), n, k,
                shuffle_capacity_factor)
        if nb == 1:
            # Single rank, single batch: the partition is one all-rows
            # bucket and the shuffle is an identity — both pure row
            # permutations. Skip them entirely (the join handles masked
            # validity natively); this is the reference's 1-rank path,
            # which also partitions into nranks=1 buckets and joins.
            with telemetry.span("join"):
                res = sort_merge_inner_join(
                    build_local, probe_local, keys_eff, out_cap,
                    build_payload=bpay, probe_payload=ppay,
                    kernel_config=kernel_config, join_type=join_type,
                    _internal=sk_names,
                )
            parts.append(res.table)
            total = total + res.total.astype(jnp.int64)
            overflow = overflow | res.overflow
        elif seg > 1:
            # The segmented-sort pipeline (ops/segmented.py,
            # docs/ROOFLINE.md §9): fine partition (sub-bucket bits on
            # the SAME partition sort), per-segment padded wire,
            # batched short-run sorts + per-segment merge at the
            # receiver. One resolution level below the flat contract:
            # capacities are per fine bucket / per segment, and every
            # overflow folds into the same shared flag the ladder
            # relieves.
            b_cap_s = seg_ops.segment_capacity(
                b_rows, n, k, seg, shuffle_capacity_factor)
            p_cap_s = seg_ops.segment_capacity(
                p_rows, n, k, seg, shuffle_capacity_factor)
            out_cap_s = seg_ops.segmented_out_capacity(
                p_rows, k, seg, out_capacity_factor, out_rows_per_rank)
            with telemetry.span("partition"):
                ptb = radix_hash_partition(build_local, keys_eff, nb,
                                           sub_buckets=seg)
                ptp = radix_hash_partition(probe_local, keys_eff, nb,
                                           sub_buckets=seg)
            tb = tape.scoped("build") if tape is not None else None
            tp = tape.scoped("probe") if tape is not None else None
            dtb = tape.scoped("build.integrity") if with_integrity \
                else None
            dtp = tape.scoped("probe.integrity") if with_integrity \
                else None
            if tape is not None:
                # The static segmentation rides the metrics block so
                # EXPLAIN's segment-count prediction grades against a
                # device-reported value, like the wire bytes.
                tape.add("sort_segments", seg)
                for t, pt, cap in ((tb, ptb, b_cap_s),
                                   (tp, ptp, p_cap_s)):
                    t.add("rows_partitioned",
                          jnp.sum(pt.counts.astype(jnp.int64)))
                    # Headroom under the FINE capacity contract.
                    t.record_min(
                        "overflow_margin_min",
                        jnp.int64(cap)
                        - jnp.max(pt.counts).astype(jnp.int64))
            for b in range(k):
                with telemetry.span("shuffle", batch=b):
                    rb_cols, rb_counts, ovf_b = \
                        _batch_shuffle_segmented(
                            comm, ptb, b, n, seg, b_cap_s, shuffle,
                            tape=tb, digest_tape=dtb)
                    rp_cols, rp_counts, ovf_p = \
                        _batch_shuffle_segmented(
                            comm, ptp, b, n, seg, p_cap_s, shuffle,
                            tape=tp, digest_tape=dtp)
                with telemetry.span("join", batch=b):
                    bcols, bval = seg_ops.runs_from_blocks(
                        rb_cols, rb_counts)
                    pcols, pval = seg_ops.runs_from_blocks(
                        rp_cols, rp_counts)
                    table, t_batch, ovf_j = \
                        seg_ops.batched_sort_merge_inner_join(
                            bcols, bval, pcols, pval, keys_eff,
                            out_cap_s, build_payload=bpay,
                            probe_payload=ppay, _internal=sk_names)
                parts.append(table)
                total = total + t_batch
                overflow = overflow | ovf_b | ovf_p | ovf_j
        else:
            # Byte-exact string wire (ragged mode): order each bucket
            # by the FIRST string column's length desc so its u32
            # planes ship as ragged prefixes (shuffle_ragged's
            # varwidth); further string columns are length-ordered
            # within the shuffle itself.
            vb = _varwidth_cols(build_local) if shuffle == "ragged" \
                else []
            vp = _varwidth_cols(probe_local) if shuffle == "ragged" \
                else []
            with telemetry.span("partition"):
                ptb = radix_hash_partition(
                    build_local, keys_eff, nb,
                    order_within=vb[0] + "#len" if vb else None)
                ptp = radix_hash_partition(
                    probe_local, keys_eff, nb,
                    order_within=vp[0] + "#len" if vp else None)
            tb = tape.scoped("build") if tape is not None else None
            tp = tape.scoped("probe") if tape is not None else None
            dtb = tape.scoped("build.integrity") if with_integrity \
                else None
            dtp = tape.scoped("probe.integrity") if with_integrity \
                else None
            if tape is not None:
                for t, pt, cap in ((tb, ptb, b_cap), (tp, ptp, p_cap)):
                    t.add("rows_partitioned",
                          jnp.sum(pt.counts.astype(jnp.int64)))
                    # Tightest per-(sender, destination)-bucket
                    # headroom under the shuffle capacity contract —
                    # how close this sizing came to an overflow.
                    t.record_min(
                        "overflow_margin_min",
                        jnp.int64(cap)
                        - jnp.max(pt.counts).astype(jnp.int64))
            for b in range(k):
                with telemetry.span("shuffle", batch=b):
                    recv_build, ovf_b = _batch_shuffle(
                        comm, ptb, b, n, b_cap, mode=shuffle,
                        compression_bits=compression_bits, varwidth=vb,
                        tape=tb, digest_tape=dtb,
                        dcn_codec_on=dcn_on)
                    recv_probe, ovf_p = _batch_shuffle(
                        comm, ptp, b, n, p_cap, mode=shuffle,
                        compression_bits=compression_bits, varwidth=vp,
                        tape=tp, digest_tape=dtp,
                        dcn_codec_on=dcn_on)
                with telemetry.span("join", batch=b):
                    res = sort_merge_inner_join(
                        recv_build, recv_probe, keys_eff, out_cap,
                        build_payload=bpay, probe_payload=ppay,
                        kernel_config=kernel_config, join_type=join_type,
                        _internal=sk_names,
                    )
                parts.append(res.table)
                total = total + res.total.astype(jnp.int64)
                overflow = overflow | ovf_b | ovf_p | res.overflow
        out = Table(
            {
                name: jnp.concatenate([t.columns[name] for t in parts])
                for name in parts[0].column_names
            },
            jnp.concatenate([t.valid for t in parts]),
        )
        if str_spec:
            from distributed_join_tpu.utils.strings import (
                rebuild_string_keys,
            )

            out = patch_string_lengths(
                rebuild_string_keys(out, str_spec, keys), keys,
                join_type)
        if tape is not None:
            # Local (pre-psum) match count: the gathered per-rank
            # vector sums to the global total, giving per-rank match
            # distribution for free.
            tape.add("matches", total)
            metrics = tape.gathered(comm)
        total = comm.psum(total)
        overflow = comm.psum(overflow.astype(jnp.int32)) > 0
        result = JoinResult(out, total=total, overflow=overflow)
        return (result, metrics) if tape is not None else result

    return step


def _make_join_agg_step(comm, spec, *, keys, k,
                        shuffle_capacity_factor, out_capacity_factor,
                        out_rows_per_rank, shuffle, compression_bits,
                        dcn_on, with_metrics, with_integrity,
                        metrics_static):
    """The FUSED join+aggregate step (``make_join_step(aggregate=)``;
    docs/AGGREGATION.md): partition + shuffle ONLY the columns the
    reduction reads, reduce each batch in the merged domain with
    :func:`~..ops.aggregate.local_join_aggregate` (segment scans ride
    the join's own sorts — zero output gathers), then settle the
    per-group partials: key mode is final per rank (hash co-location),
    probe mode pays one cross-batch combine plus the groups-sized
    cross-rank partials exchange (padded; hierarchical routing on a
    multi-slice mesh; billed under the ``partials.*`` counters).
    Returns the same ``step(build, probe) -> JoinResult`` shape as the
    materializing step — ``table`` holds finalized groups, ``total``
    the would-be join row count, ``overflow`` any shuffle-bucket or
    partial-groups capacity trip (rows dropped loudly, never wrong
    sums)."""
    from distributed_join_tpu.ops import aggregate as agg_ops

    n = comm.n_ranks
    nb = k * n
    partials_mode = "hierarchical" if shuffle == "hierarchical" \
        else "padded"

    def step(build_local: Table, probe_local: Table):
        tape = telemetry.MetricsTape() if (with_metrics
                                           or with_integrity) else None
        if tape is not None:
            for mname, mval in (metrics_static or {}).items():
                tape.add(mname, int(mval))
        for kname in keys:
            bc = build_local.columns[kname]
            pc = probe_local.columns[kname]
            if bc.ndim != 1:
                raise agg_ops.AggregatePushdownUnsupported(
                    f"aggregate pushdown unsupported: join key "
                    f"{kname!r} is a 2-D (string) column; the fused "
                    "reduction covers scalar keys — run string-key "
                    "workloads through the materializing join")
            if bc.dtype != pc.dtype:
                raise TypeError(
                    f"key {kname!r} dtype mismatch: build {bc.dtype} "
                    f"vs probe {pc.dtype}")
        bschema = agg_ops.table_schema(build_local)
        pschema = agg_ops.table_schema(probe_local)
        mode = agg_ops.resolve_agg_mode(spec, keys, bschema, pschema)
        wire_b, wire_p = agg_ops.wire_columns(spec, mode, keys,
                                              bschema, pschema)
        build_w = build_local.select(wire_b)
        probe_w = probe_local.select(wire_p)
        lanes_schema = agg_ops.partial_lane_schema(spec, bschema,
                                                   pschema)
        group_names = list(keys) if mode == "key" \
            else list(spec.group_keys)

        b_rows, p_rows = build_w.capacity, probe_w.capacity
        # Capacity arithmetic VERBATIM from the materializing step —
        # planning.build_plan mirrors it, and the ladder's escalation
        # relieves the same contract.
        b_cap = _round_up(
            int(math.ceil(b_rows / nb * shuffle_capacity_factor)), 8)
        p_cap = _round_up(
            int(math.ceil(p_rows / nb * shuffle_capacity_factor)), 8)
        if out_rows_per_rank is not None:
            out_cap = _round_up(
                int(math.ceil(out_rows_per_rank / k)), 8)
        else:
            out_cap = _round_up(
                int(math.ceil(p_rows / k * out_capacity_factor)), 8)
        groups_cap = agg_ops.resolve_groups_capacity(spec, out_cap)

        total = jnp.int64(0)
        overflow = jnp.bool_(False)
        parts = []
        if nb == 1:
            with telemetry.span("join_agg"):
                partials, t, _g, ovf = agg_ops.local_join_aggregate(
                    build_w, probe_w, keys, spec, mode, groups_cap)
            parts.append(partials)
            total = total + t
            overflow = overflow | ovf
        else:
            with telemetry.span("partition"):
                ptb = radix_hash_partition(build_w, keys, nb)
                ptp = radix_hash_partition(probe_w, keys, nb)
            tb = tape.scoped("build") if tape is not None else None
            tp = tape.scoped("probe") if tape is not None else None
            dtb = tape.scoped("build.integrity") if with_integrity \
                else None
            dtp = tape.scoped("probe.integrity") if with_integrity \
                else None
            if tape is not None:
                for t_, pt, cap in ((tb, ptb, b_cap), (tp, ptp, p_cap)):
                    t_.add("rows_partitioned",
                           jnp.sum(pt.counts.astype(jnp.int64)))
                    t_.record_min(
                        "overflow_margin_min",
                        jnp.int64(cap)
                        - jnp.max(pt.counts).astype(jnp.int64))
            for b in range(k):
                with telemetry.span("shuffle", batch=b):
                    recv_build, ovf_b = _batch_shuffle(
                        comm, ptb, b, n, b_cap, mode=shuffle,
                        compression_bits=compression_bits,
                        tape=tb, digest_tape=dtb, dcn_codec_on=dcn_on)
                    recv_probe, ovf_p = _batch_shuffle(
                        comm, ptp, b, n, p_cap, mode=shuffle,
                        compression_bits=compression_bits,
                        tape=tp, digest_tape=dtp, dcn_codec_on=dcn_on)
                with telemetry.span("join_agg", batch=b):
                    partials, t, _g, ovf_j = \
                        agg_ops.local_join_aggregate(
                            recv_build, recv_probe, keys, spec, mode,
                            groups_cap)
                parts.append(partials)
                total = total + t
                overflow = overflow | ovf_b | ovf_p | ovf_j
        if mode in ("probe", "build"):
            # Key mode needs NEITHER settle pass: a key lives in
            # exactly one (batch, rank) by the bucket arithmetic, so
            # per-batch per-rank partials are disjoint final groups.
            # Probe and build mode share both settle passes — the
            # regroup/exchange machinery is side-agnostic once the
            # partials table exists.
            if len(parts) > 1:
                # Non-key groups recur across batches — one combine
                # (concat + regroup sort at groups size) settles them.
                with telemetry.span("agg_combine"):
                    combined, _g, ovf_c = agg_ops.combine_partials(
                        parts, spec, group_names, lanes_schema,
                        groups_cap)
                overflow = overflow | ovf_c
                parts = [combined]
            if n > 1:
                # The partials-only exchange: wire bytes are
                # O(groups), not O(output rows). Per-destination
                # capacity = the full partials block, so a SEND bucket
                # can never overflow (a rank holds at most groups_cap
                # valid partials); the post-exchange combine's flag
                # fires if one rank receives more distinct groups than
                # its block holds.
                with telemetry.span("partials_exchange"):
                    ptg = radix_hash_partition(parts[0], group_names,
                                               n)
                    tg = tape.scoped("partials") if tape is not None \
                        else None
                    dtg = tape.scoped("partials.integrity") \
                        if with_integrity else None
                    recv, ovf_x = _batch_shuffle(
                        comm, ptg, 0, n, groups_cap,
                        mode=partials_mode, tape=tg, digest_tape=dtg)
                    combined, _g, ovf_c = agg_ops.combine_partials(
                        [recv], spec, group_names, lanes_schema,
                        groups_cap)
                overflow = overflow | ovf_x | ovf_c
                parts = [combined]
        finals = [agg_ops.finalize_groups(p, spec, group_names)
                  for p in parts]
        out = Table(
            {name: jnp.concatenate([t_.columns[name] for t_ in finals])
             for name in finals[0].column_names},
            jnp.concatenate([t_.valid for t_ in finals]),
        )
        if tape is not None:
            tape.add("matches", total)
            # Per-rank FINAL groups emitted (the gathered vector sums
            # to the global group count — every group lives on exactly
            # one rank after the settle passes above).
            tape.add("agg.groups",
                     jnp.sum(out.valid.astype(jnp.int64)))
            metrics = tape.gathered(comm)
        total = comm.psum(total)
        overflow = comm.psum(overflow.astype(jnp.int32)) > 0
        result = JoinResult(out, total=total, overflow=overflow)
        return (result, metrics) if tape is not None else result

    return step


def resolve_probe_capacities(p_local: int, n: int, k: int,
                             shuffle_capacity_factor: float,
                             out_capacity_factor: float,
                             out_rows_per_rank: Optional[int]):
    """THE one probe-side capacity resolution of the probe-only
    program: ``(p_cap per (sender, destination) bucket, out_cap per
    batch)`` — shared by :func:`make_probe_join_step` and
    :func:`..planning.plan.build_probe_plan` so a probe-only EXPLAIN
    and the dispatched program can never drift apart (the
    resolve_join_ladder discipline, applied to the probe side)."""
    nb = k * n
    p_cap = _round_up(
        int(math.ceil(p_local / nb * shuffle_capacity_factor)), 8)
    if out_rows_per_rank is not None:
        out_cap = _round_up(
            int(math.ceil(int(out_rows_per_rank) / k)), 8)
    else:
        out_cap = _round_up(
            int(math.ceil(p_local / k * out_capacity_factor)), 8)
    return p_cap, out_cap


def make_probe_join_step(
    comm: Communicator,
    key: str = "key",
    over_decomposition: int = 1,
    shuffle_capacity_factor: float = DEFAULT_SHUFFLE_CAPACITY_FACTOR,
    out_capacity_factor: float = DEFAULT_OUT_CAPACITY_FACTOR,
    out_rows_per_rank: Optional[int] = None,
    build_payload: Optional[Sequence[str]] = None,
    probe_payload: Optional[Sequence[str]] = None,
    shuffle: str = "padded",
    compression_bits: Optional[int] = None,
    sort_mode: str = "flat",
    aggregate=None,
    kernel_config=None,
    with_metrics: bool = False,
    with_integrity: bool = False,
    metrics_static: Optional[dict] = None,
):
    """The PROBE-ONLY join step against a resident build image
    (service/resident.py; ROADMAP item 4).

    ``sort_mode``: "flat" only. The segmented-sort path needs BOTH
    sides segmented into the same hash classes, and a resident image
    is one flat key-sorted run registered before any probe's segment
    count exists — segment-aligned resident images are a named
    leftover, so "segmented" refuses loudly here instead of silently
    serving the flat program.

    ``aggregate`` (an :class:`~..ops.aggregate.AggregateSpec`, or
    None): the fused join+aggregate pipeline on the probe-only
    dispatch — partition + shuffle only the probe columns the
    reduction reads, reduce each batch against the full resident
    shard in the merged domain, and return per-group aggregates with
    zero materialization gathers (docs/AGGREGATION.md). Key-mode
    co-location holds by the registration hash ((h % kn) % n ==
    h % n); probe mode exchanges only the groups-sized partials. The
    same refusal contract as ``make_join_step(aggregate=)``.

    ``with_integrity=True`` weaves the wire-integrity digests
    (parallel/integrity.py) into the probe-side shuffle exactly as
    the full join does — per-(src, dst) payload digests riding the
    aux Metrics block (the step then returns ``(JoinResult,
    Metrics)``), verified host-side with
    ``integrity.verify_join_result``. The build side has no wire to
    digest: its image moved at registration, where the conservation
    check (service/resident.py) already guards it.

    ``step(resident_local, probe_local) -> JoinResult`` where
    ``resident_local`` is one rank's shard of a registered build table
    that ALREADY went through the expensive 2/3 of the pipeline —
    hash-partitioned to this rank, shuffled, key-sorted into a
    valid-prefix run (resident.make_resident_prep_step). Only the
    probe side is partitioned, shuffled, and sorted here; each batch
    merges against the full resident shard. Hash routing guarantees
    co-location at ANY over-decomposition k: a key's destination rank
    is ``h % n`` whether buckets were computed mod ``n``
    (registration, k=1) or mod ``k*n`` (this step) — ``(h % kn) % n
    == h % n`` — so matching keys always meet, and each probe row
    rides exactly one batch.

    The capacity contract mirrors :func:`make_join_step` on the probe
    side verbatim (same per-bucket arithmetic, same overflow flag →
    the same ``CapacityLadder`` escalates it); the build side has no
    capacities to size — its image is fixed at registration. The skew
    sidecar and 2-D (string) columns are not part of the probe-only
    program (resident registration refuses them up front); pass those
    workloads through the full join.
    """
    n = comm.n_ranks
    k = over_decomposition
    if k < 1:
        raise ValueError("over_decomposition must be >= 1")
    if shuffle not in ("padded", "ragged", "ppermute"):
        raise ValueError(f"unknown shuffle mode {shuffle!r}")
    if sort_mode not in SORT_MODES:
        raise ValueError(
            f"unknown sort_mode {sort_mode!r}; pick one of {SORT_MODES}")
    if sort_mode != "flat":
        raise ValueError(
            "sort_mode='segmented' is not part of the probe-only "
            "program: the resident build image is one flat key-sorted "
            "run registered before the probe's segment count is known, "
            "and segments must be the SAME hash classes on both sides "
            "— segment-aligned resident images are unimplemented; "
            "serve resident joins with sort_mode='flat'")
    if compression_bits is not None and shuffle == "ragged":
        raise ValueError(
            "compression applies to the padded/ppermute shuffles; the "
            "ragged exchange already sends exact rows (combining the "
            "two is unimplemented)"
        )
    if n > 1 and getattr(comm, "n_slices", 1) > 1:
        # The same guard make_join_step applies to its flat modes:
        # the probe-only program routes one GLOBAL collective, which
        # on a multi-slice mesh drags intra-slice traffic across DCN.
        # Hierarchical probe-only serving is a named ROADMAP leftover
        # — refuse loudly instead of silently mis-routing.
        raise ValueError(
            "probe-only joins route one GLOBAL collective over the "
            "mesh; a multi-slice topology would drag intra-slice "
            "traffic across DCN, and hierarchical probe-only serving "
            "is not implemented yet — register resident tables on a "
            "flat 1-D communicator")
    nb = k * n
    keys = [key] if isinstance(key, str) else list(key)

    if aggregate is not None:
        from distributed_join_tpu.ops import aggregate as agg_ops

        if not isinstance(aggregate, agg_ops.AggregateSpec):
            raise TypeError(
                "aggregate must be an ops.aggregate.AggregateSpec "
                f"(got {type(aggregate).__name__})")
        if build_payload is not None or probe_payload is not None:
            raise agg_ops.AggregatePushdownUnsupported(
                "aggregate pushdown unsupported: explicit payload "
                "lists conflict with the pushdown's own wire-column "
                "resolution")
        if kernel_config is not None:
            raise agg_ops.AggregatePushdownUnsupported(
                "aggregate pushdown unsupported: kernel_config tunes "
                "the materializing expand/compact gathers the fused "
                "reduction never runs — drop the knob")
        return _make_probe_agg_step(
            comm, aggregate, keys=keys, k=k,
            shuffle_capacity_factor=shuffle_capacity_factor,
            out_capacity_factor=out_capacity_factor,
            out_rows_per_rank=out_rows_per_rank,
            shuffle=shuffle, compression_bits=compression_bits,
            with_metrics=with_metrics, with_integrity=with_integrity,
            metrics_static=metrics_static)

    def step(resident_local: Table, probe_local: Table):
        # The integrity digests ride the same Metrics slot, so either
        # switch materializes the tape (and the aux output) — the
        # full join's contract, verbatim.
        tape = telemetry.MetricsTape() if (with_metrics
                                           or with_integrity) else None
        if tape is not None:
            for mname, mval in (metrics_static or {}).items():
                tape.add(mname, int(mval))
        for t, side in ((resident_local, "resident"),
                        (probe_local, "probe")):
            for name, c in t.columns.items():
                if c.ndim != 1:
                    raise TypeError(
                        f"{side} column {name!r} is {c.ndim}-D; the "
                        "probe-only program covers scalar columns "
                        "(register 2-D/string workloads through the "
                        "full join)")
        for kname in keys:
            bdt = resident_local.columns[kname].dtype
            pdt = probe_local.columns[kname].dtype
            if bdt != pdt:
                raise TypeError(
                    f"key {kname!r} dtype mismatch: resident {bdt} "
                    f"vs probe {pdt}")

        p_cap, out_cap = resolve_probe_capacities(
            probe_local.capacity, n, k, shuffle_capacity_factor,
            out_capacity_factor, out_rows_per_rank)

        if tape is not None:
            tape.add("resident.rows",
                     jnp.sum(resident_local.valid.astype(jnp.int64)))

        parts = []
        total = jnp.int64(0)
        overflow = jnp.bool_(False)
        if nb == 1:
            with telemetry.span("join"):
                res = sort_merge_inner_join(
                    resident_local, probe_local, keys, out_cap,
                    build_payload=build_payload,
                    probe_payload=probe_payload,
                    kernel_config=kernel_config,
                )
            parts.append(res.table)
            total = total + res.total.astype(jnp.int64)
            overflow = overflow | res.overflow
        else:
            with telemetry.span("partition"):
                ptp = radix_hash_partition(probe_local, keys, nb)
            tp = tape.scoped("probe") if tape is not None else None
            dtp = tape.scoped("probe.integrity") if with_integrity \
                else None
            if tape is not None:
                tp.add("rows_partitioned",
                       jnp.sum(ptp.counts.astype(jnp.int64)))
                tp.record_min(
                    "overflow_margin_min",
                    jnp.int64(p_cap)
                    - jnp.max(ptp.counts).astype(jnp.int64))
            for b in range(k):
                with telemetry.span("shuffle", batch=b):
                    recv_probe, ovf_p = _batch_shuffle(
                        comm, ptp, b, n, p_cap, mode=shuffle,
                        compression_bits=compression_bits, tape=tp,
                        digest_tape=dtp)
                with telemetry.span("join", batch=b):
                    res = sort_merge_inner_join(
                        resident_local, recv_probe, keys, out_cap,
                        build_payload=build_payload,
                        probe_payload=probe_payload,
                        kernel_config=kernel_config,
                    )
                parts.append(res.table)
                total = total + res.total.astype(jnp.int64)
                overflow = overflow | ovf_p | res.overflow
        out = Table(
            {
                name: jnp.concatenate([t.columns[name] for t in parts])
                for name in parts[0].column_names
            },
            jnp.concatenate([t.valid for t in parts]),
        )
        if tape is not None:
            tape.add("matches", total)
            metrics = tape.gathered(comm)
        total = comm.psum(total)
        overflow = comm.psum(overflow.astype(jnp.int32)) > 0
        result = JoinResult(out, total=total, overflow=overflow)
        return (result, metrics) if tape is not None else result

    return step


def _make_probe_agg_step(comm, spec, *, keys, k,
                         shuffle_capacity_factor, out_capacity_factor,
                         out_rows_per_rank, shuffle, compression_bits,
                         with_metrics, with_integrity, metrics_static):
    """The PROBE-ONLY fused join+aggregate step
    (``make_probe_join_step(aggregate=)``): the resident build image
    is already hash-co-located, so only the probe's needed columns
    partition + shuffle, each batch reduces against the full resident
    shard, and the partials settle exactly as in the full fused step
    (key mode final per rank; probe mode one cross-batch combine plus
    the groups-sized padded partials exchange)."""
    from distributed_join_tpu.ops import aggregate as agg_ops

    n = comm.n_ranks
    nb = k * n
    # Probe-only refuses shuffle="hierarchical" today, so this always
    # resolves to "padded" — kept as the full fused step's expression
    # so the two pipelines cannot route partials apart if the
    # probe-only path ever learns the two-level exchange.
    partials_mode = "hierarchical" if shuffle == "hierarchical" \
        else "padded"

    def step(resident_local: Table, probe_local: Table):
        tape = telemetry.MetricsTape() if (with_metrics
                                           or with_integrity) else None
        if tape is not None:
            for mname, mval in (metrics_static or {}).items():
                tape.add(mname, int(mval))
        for t, side in ((resident_local, "resident"),
                        (probe_local, "probe")):
            for name, c in t.columns.items():
                if c.ndim != 1:
                    raise TypeError(
                        f"{side} column {name!r} is {c.ndim}-D; the "
                        "probe-only program covers scalar columns")
        for kname in keys:
            bdt = resident_local.columns[kname].dtype
            pdt = probe_local.columns[kname].dtype
            if bdt != pdt:
                raise TypeError(
                    f"key {kname!r} dtype mismatch: resident {bdt} "
                    f"vs probe {pdt}")
        bschema = agg_ops.table_schema(resident_local)
        pschema = agg_ops.table_schema(probe_local)
        mode = agg_ops.resolve_agg_mode(spec, keys, bschema, pschema)
        if mode == "build":
            raise agg_ops.AggregatePushdownUnsupported(
                "group keys live on the RESIDENT (build) side; the "
                "probe-only program keeps the build shards pinned and "
                "only exchanges probe rows, so build-keyed group-bys "
                "ride make_join_step(aggregate=) instead")
        wire_b, wire_p = agg_ops.wire_columns(spec, mode, keys,
                                              bschema, pschema)
        resident_w = resident_local.select(wire_b)
        probe_w = probe_local.select(wire_p)
        lanes_schema = agg_ops.partial_lane_schema(spec, bschema,
                                                   pschema)
        group_names = list(keys) if mode == "key" \
            else list(spec.group_keys)

        p_cap, out_cap = resolve_probe_capacities(
            probe_w.capacity, n, k, shuffle_capacity_factor,
            out_capacity_factor, out_rows_per_rank)
        groups_cap = agg_ops.resolve_groups_capacity(spec, out_cap)
        if tape is not None:
            tape.add("resident.rows",
                     jnp.sum(resident_local.valid.astype(jnp.int64)))

        total = jnp.int64(0)
        overflow = jnp.bool_(False)
        parts = []
        if nb == 1:
            with telemetry.span("join_agg"):
                partials, t, _g, ovf = agg_ops.local_join_aggregate(
                    resident_w, probe_w, keys, spec, mode, groups_cap)
            parts.append(partials)
            total = total + t
            overflow = overflow | ovf
        else:
            with telemetry.span("partition"):
                ptp = radix_hash_partition(probe_w, keys, nb)
            tp = tape.scoped("probe") if tape is not None else None
            dtp = tape.scoped("probe.integrity") if with_integrity \
                else None
            if tape is not None:
                tp.add("rows_partitioned",
                       jnp.sum(ptp.counts.astype(jnp.int64)))
                tp.record_min(
                    "overflow_margin_min",
                    jnp.int64(p_cap)
                    - jnp.max(ptp.counts).astype(jnp.int64))
            for b in range(k):
                with telemetry.span("shuffle", batch=b):
                    recv_probe, ovf_p = _batch_shuffle(
                        comm, ptp, b, n, p_cap, mode=shuffle,
                        compression_bits=compression_bits, tape=tp,
                        digest_tape=dtp)
                with telemetry.span("join_agg", batch=b):
                    partials, t, _g, ovf_j = \
                        agg_ops.local_join_aggregate(
                            resident_w, recv_probe, keys, spec, mode,
                            groups_cap)
                parts.append(partials)
                total = total + t
                overflow = overflow | ovf_p | ovf_j
        if mode == "probe":
            if len(parts) > 1:
                with telemetry.span("agg_combine"):
                    combined, _g, ovf_c = agg_ops.combine_partials(
                        parts, spec, group_names, lanes_schema,
                        groups_cap)
                overflow = overflow | ovf_c
                parts = [combined]
            if n > 1:
                with telemetry.span("partials_exchange"):
                    ptg = radix_hash_partition(parts[0], group_names,
                                               n)
                    tg = tape.scoped("partials") if tape is not None \
                        else None
                    dtg = tape.scoped("partials.integrity") \
                        if with_integrity else None
                    recv, ovf_x = _batch_shuffle(
                        comm, ptg, 0, n, groups_cap,
                        mode=partials_mode, tape=tg, digest_tape=dtg)
                    combined, _g, ovf_c = agg_ops.combine_partials(
                        [recv], spec, group_names, lanes_schema,
                        groups_cap)
                overflow = overflow | ovf_x | ovf_c
                parts = [combined]
        finals = [agg_ops.finalize_groups(p, spec, group_names)
                  for p in parts]
        out = Table(
            {name: jnp.concatenate([t_.columns[name] for t_ in finals])
             for name in finals[0].column_names},
            jnp.concatenate([t_.valid for t_ in finals]),
        )
        if tape is not None:
            tape.add("matches", total)
            tape.add("agg.groups",
                     jnp.sum(out.valid.astype(jnp.int64)))
            metrics = tape.gathered(comm)
        total = comm.psum(total)
        overflow = comm.psum(overflow.astype(jnp.int32)) > 0
        result = JoinResult(out, total=total, overflow=overflow)
        return (result, metrics) if tape is not None else result

    return step


def make_distributed_join(comm: Communicator, with_metrics=None,
                          with_integrity: bool = False, **opts):
    """Compile a distributed inner join over ``comm``'s ranks.

    Returns a jitted ``fn(build: Table, probe: Table) -> JoinResult``
    taking row-sharded global Tables (capacity divisible by n_ranks) and
    returning a row-sharded result Table plus a replicated global match
    count and overflow flag. See :func:`make_join_step` for options.

    ``with_metrics=None`` (default) resolves from the global telemetry
    state: with a session active the compiled program additionally
    emits the device-metrics block and the result carries it as a
    host-side ``res.telemetry`` attribute (a ``telemetry.Metrics``;
    like ``retry_report``, not a pytree field — the call signature and
    the JoinResult pytree are unchanged either way). With telemetry
    off this is exactly the seed program.

    ``with_integrity=True`` weaves the wire-integrity digests into the
    same aux Metrics block (``res.telemetry`` then always exists, even
    with telemetry off) — verify with ``integrity.verify_join_result``
    or use :func:`distributed_inner_join`'s ``verify_integrity``.
    """
    if with_metrics is None:
        with_metrics = telemetry.enabled()
    step = make_join_step(comm, with_metrics=with_metrics,
                          with_integrity=with_integrity, **opts)
    if not (with_metrics or with_integrity):
        return comm.spmd(step, sharded_out=JOIN_SHARDED_OUT)
    compiled = comm.spmd(step, sharded_out=JOIN_METRICS_SHARDED_OUT)

    def fn(build: Table, probe: Table) -> JoinResult:
        res, metrics = compiled(build, probe)
        object.__setattr__(res, "telemetry", metrics)
        return res

    return fn


def resolve_join_ladder(build, probe, n_ranks: int, opts: dict,
                        n_slices: int = 1):
    """THE one resolution of ``distributed_inner_join``'s capacity
    contract: pop the sizing knobs from ``opts`` (mutated — what
    remains goes to ``make_join_step`` verbatim), resolve the skew
    defaults exactly as the step would, and return the
    :class:`..faults.CapacityLadder` at its initial rung.

    Shared with :func:`..planning.explain_join` so an EXPLAIN's plan
    resolves the identical sizing a real call would run — the two
    can never drift apart."""
    from distributed_join_tpu.parallel.faults import CapacityLadder

    shuffle_f = opts.pop("shuffle_capacity_factor",
                         DEFAULT_SHUFFLE_CAPACITY_FACTOR)
    out_f = opts.pop("out_capacity_factor", DEFAULT_OUT_CAPACITY_FACTOR)
    # Resolve the HH capacities here so retries can double them too —
    # overflow can originate in the skew path as well as the shuffle.
    skew_on = opts.get("skew_threshold") is not None
    hh_build_cap = opts.pop("hh_build_capacity", None)
    hh_probe_cap = opts.pop("hh_probe_capacity", None)
    hh_out_cap = opts.pop("hh_out_capacity", None)
    if skew_on:
        hh_build_cap = hh_build_cap or (
            opts.get("hh_slots", DEFAULT_HH_SLOTS) * HH_BUILD_SLOTS_PER_HH
        )
        hh_probe_cap = hh_probe_cap or max(
            probe.capacity // (8 * n_ranks), 1024)
        hh_out_cap = hh_out_cap or max(
            probe.capacity // (4 * n_ranks), 1024)
    out_rows = opts.pop("out_rows_per_rank", None)
    comp_bits = opts.pop("compression_bits", None)
    if opts.get("shuffle") == "hierarchical" and comp_bits is None:
        # The hierarchical DCN codec defaults its residual width when
        # the caller set only the knob; resolving it HERE (not just
        # inside the step) hands the bits to the ladder, so a
        # cross-slice residual overflow escalates by widening bits —
        # the cheap axis — instead of uselessly doubling capacities.
        # Topology-gated: one slice has no cross-slice payload (the
        # degenerate path routes flat raw padded), so arming bits
        # there would burn the first rung widening a no-op knob.
        from distributed_join_tpu.planning.cost import (
            resolve_dcn_bits,
        )

        comp_bits = resolve_dcn_bits(
            opts.get("dcn_codec", "auto"), None, n_slices=n_slices)
    # The escalation policy — compression bits widen first (the cheap
    # axis), then every capacity doubles with the skew capacities
    # jumping straight to full local probe coverage — lives in
    # CapacityLadder so drivers escalate identically and the
    # decisions survive as a RetryReport.
    return CapacityLadder(
        shuffle_capacity_factor=shuffle_f,
        out_capacity_factor=out_f,
        out_rows_per_rank=out_rows,
        compression_bits=comp_bits,
        skew=skew_on,
        hh_build_capacity=hh_build_cap,
        hh_probe_capacity=hh_probe_cap,
        hh_out_capacity=hh_out_cap,
        local_probe_rows=probe.capacity // n_ranks,
    )


def distributed_inner_join(
    build: Table,
    probe: Table,
    comm: Communicator,
    key: str = "key",
    auto_retry: int = 0,
    verify_integrity: bool = False,
    program_cache=None,
    explain: bool = False,
    tuner=None,
    **opts,
) -> JoinResult:
    """One-shot convenience: pad to rank-divisible capacity, shard the
    inputs over the mesh, compile and run. For benchmarking, build the
    function once with :func:`make_distributed_join` instead.

    ``auto_retry``: on overflow (a static capacity too small for the
    data), recompile with escalated capacities up to this many times —
    the policy lives in :class:`..faults.CapacityLadder` (compression
    bits widen first, then every capacity doubles, with the skew
    capacities jumping to full local probe coverage). The reference
    sizes receive buffers exactly and can't overflow (SURVEY.md §2);
    static shapes can, so they get an escape hatch instead of a wrong
    answer. The returned result carries the full escalation trail as a
    host-side ``retry_report`` attribute (:class:`..faults.RetryReport`
    — which capacities doubled, why, per attempt), which the benchmark
    drivers embed in their JSON records.

    ``verify_integrity``: compute in-graph wire digests
    (parallel/integrity.py) and verify every (src, dst) pair host-side
    before returning. A mismatch is a RETRYABLE rung distinct from
    overflow — the ladder re-runs the SAME sizing (``retry_integrity``
    in the report; transport corruption is transient, capacities are
    innocent) up to the ``auto_retry`` budget, and raises
    :class:`..integrity.IntegrityError` instead of returning corrupt
    rows when the budget runs out. A verified clean result carries the
    report as ``res.integrity_report``. Verification is skipped on an
    overflowed attempt (clamped rows mismatch by design; the overflow
    rung handles it).

    ``program_cache``: a :class:`..service.programs.JoinProgramCache`.
    When given, every attempt resolves its executable THROUGH the
    cache instead of building a fresh closure — a repeat query, or a
    retry rung whose exact sizing (the ladder's ``sizing()`` plus the
    attempt index) was seen before, dispatches the resident program
    with zero new traces (the serving warm path, docs/SERVICE.md).
    Exception: an integrity-mismatch rung EVICTS the attempt's entry
    before the same-sizing rerun — injected corruption is woven at
    trace time, so only a re-trace is guaranteed to face a fresh
    schedule, and a possibly-tainted resident program must not keep
    serving. Default None: build per call, the historical behavior.

    ``explain``: attach the fully-resolved :class:`..planning.JoinPlan`
    of the attempt that produced the result (final ladder rung) as a
    host-side ``res.plan`` attribute — capacities, wire-byte and
    wall-time predictions, and the canonical signature digest, which
    equals the program cache's key for the same call
    (docs/OBSERVABILITY.md "Explain & cost model"). Plan construction
    is pure host arithmetic — no extra traces or compiles; use
    :func:`..planning.explain_join` for the plan WITHOUT running.

    ``tuner``: a :class:`..planning.tuner.JoinTuner`. When given, the
    call's workload signature is looked up in the tuner's history
    table BEFORE the ladder resolves: a repeat workload whose ladder
    previously escalated starts at the final rung it resolved to —
    sizing AND rung label, so with a ``program_cache`` the dispatch
    is the already-resident executable (zero new traces, zero
    escalations) — and evidence-backed structural knobs (PRPD skew,
    ragged wire) fill in when the caller left them unset. No history
    for the signature = the exact static (tuner-off) resolution. The
    verdict is attached host-side as ``res.tuned``
    (``TunedConfig.as_record()``); the ladder still guards every
    run, so a lying history costs recompiles, never wrong rows.
    """
    from distributed_join_tpu.parallel import faults, integrity

    if program_cache is not None and program_cache.comm is not comm:
        # The cache compiles over ITS communicator's mesh; silently
        # running this join on a different mesh would be a wrong-shard
        # answer, not a slow one.
        raise ValueError(
            "program_cache was built for a different communicator")

    n = comm.n_ranks

    tuned = None
    if tuner is not None:
        # Resolved on the UNPADDED tables and pre-tuned opts — the
        # same basis JoinService keys its history entries on, so the
        # signature the tuner reads is the one the store wrote.
        tuned = tuner.resolve(comm, build, probe, key=key,
                              with_integrity=verify_integrity,
                              opts=opts)
        opts = tuned.apply(opts)

    build = build.pad_to(_round_up(build.capacity, n))
    probe = probe.pad_to(_round_up(probe.capacity, n))
    if hasattr(comm, "device_put_sharded"):
        build, probe = comm.device_put_sharded((build, probe))

    ladder = resolve_join_ladder(build, probe, n, opts,
                                 n_slices=getattr(comm, "n_slices", 1))
    if tuned is not None:
        ladder.seed_rung(tuned.rung)
    last_sig = None
    for attempt in range(auto_retry + 1):
        # The rung label is ABSOLUTE (ladder.base_rung + attempt): a
        # tuner-pre-sized first attempt carries the same label — hence
        # the same program signature — as the executable the cold
        # run's escalation already traced at this sizing.
        rung = ladder.base_rung + attempt
        if program_cache is not None:
            fn, _ = program_cache.get(
                build, probe, key=key,
                with_integrity=verify_integrity,
                metrics_static={"retry_attempt_max": rung},
                **ladder.sizing(), **opts)
            last_sig = fn.signature
        else:
            fn = make_distributed_join(comm, key=key,
                                       with_integrity=verify_integrity,
                                       metrics_static={
                                           "retry_attempt_max": rung},
                                       **ladder.sizing(), **opts)
        if faults.plan_validation_enabled():
            # The violation record is process-global; drop leftovers
            # from earlier unchecked programs so what check() raises
            # below belongs to THIS attempt.
            faults.clear_plan_violations()
        res = fn(build, probe)
        overflow = bool(res.overflow)
        if faults.plan_validation_enabled():
            # The flag fetch above sequenced the validation callbacks;
            # surface a recorded inconsistency as the loud error it is
            # rather than retrying a corrupted-metadata exchange.
            faults.check_plan_violations()
        report = None
        if verify_integrity and not overflow:
            # Overflow attempts skip verification: a clamp drops rows
            # by design and the overflow rung already forces a retry.
            report = integrity.verify_join_result(res)
        ladder.note(overflow,
                    integrity_ok=None if report is None else report.ok)
        failed = overflow or (report is not None and not report.ok)
        if attempt == auto_retry or not failed:
            # retry_report is host-side metadata, not a pytree field:
            # JoinResult traces through shard_map, and the report only
            # exists outside the compiled program.
            object.__setattr__(res, "retry_report", ladder.report())
            if tuned is not None:
                object.__setattr__(res, "tuned", tuned.as_record())
            if explain:
                # Host arithmetic only (no trace/compile): the plan of
                # the attempt that produced THIS result — its digest is
                # the program cache's key for the same sizing.
                from distributed_join_tpu import planning

                object.__setattr__(res, "plan", planning.build_plan(
                    comm, build, probe, key=key,
                    with_integrity=verify_integrity,
                    metrics_static={"retry_attempt_max": rung},
                    **ladder.sizing(), **opts))
            if report is not None:
                object.__setattr__(res, "integrity_report", report)
            # Fold the device metrics of the FINAL attempt into the
            # telemetry session (one host fetch, after the retry loop
            # settled — the flag fetch above already synced).
            telemetry.emit_metrics(getattr(res, "telemetry", None))
            if report is not None and not report.ok:
                # Never hand corrupt rows back as a result. The
                # budget-exhausted rung is as tainted as a retried
                # one: a resident (or persisted) program that failed
                # verification must not serve the next same-signature
                # request.
                if program_cache is not None and last_sig is not None:
                    program_cache.evict(last_sig)
                raise integrity.IntegrityError(report)
            return res
        if overflow:
            ladder.escalate()
        else:
            # Integrity mismatch: rerun the SAME sizing — the rows
            # were wrong, not too many. Every retry recompiles (a
            # cached entry for this rung is evicted first), so a
            # deterministic injected corruption budget (FaultPlan)
            # exhausts and the rerun can verify clean.
            if program_cache is not None and last_sig is not None:
                program_cache.evict(last_sig)
            ladder.hold("retry_integrity")
    raise AssertionError("unreachable")
