"""Failure-semantics layer: fault injection, plan validation, retry
policy, and progress manifests.

The reference sizes receive buffers exactly and "can't overflow"
(SURVEY.md §2); the TPU port replaces that with static shapes plus an
``auto_retry`` ladder, a multi-host TCP handshake, and a host-side
out-of-core batch loop — three failure surfaces the rest of the code
merely documents. This module makes them *exercisable* and *recoverable*:

- :class:`FaultInjectingCommunicator` wraps any ``Communicator`` and
  deterministically injects failures — forced overflow flags (a
  capacity squeeze as the retry ladder sees it), rank-inconsistent
  ragged-plan count gathers, delayed or failed dispatches — so every
  branch of ``distributed_inner_join``'s ladder, the skew-capacity
  jump, the compression bits-widening path, and the out-of-core batch
  retry can be driven from tier-1 CPU tests.
- :func:`validate_ragged_plan` is the debug-mode cross-rank
  consistency check for the exact-size shuffle's size/offset vectors
  (``parallel/shuffle.ragged_plan``): inconsistent vectors silently
  corrupt (emulation) or hang (TPU hardware op) — validation turns
  them into a loud :class:`PlanValidationError` at the cost of one
  extra small all-gather. Enable with :func:`validate_plans` or
  ``DJTPU_VALIDATE_PLANS=1``; the gate is trace-time, so it must be
  on when the program is *traced*, not merely when it runs.
- :func:`retry_with_backoff` is the generic transient-failure loop
  (used by ``bootstrap.initialize``'s handshake retry; see
  :class:`distributed_join_tpu.parallel.bootstrap.BootstrapError`).
- :class:`CapacityLadder` + :class:`RetryReport` make the
  overflow-escalation policy a first-class, reportable object shared
  by ``distributed_inner_join``, the benchmark drivers, and
  ``bench.py`` (previously an inline loop whose decisions evaporated).
- :class:`JoinManifest` is the on-disk per-batch progress record that
  makes ``out_of_core.batched_join_host`` resumable: a killed SF-100
  run restarts from the first incomplete batch and reproduces the
  uninterrupted total bit-exactly.

When a telemetry session is active (docs/OBSERVABILITY.md), ladder
attempts and manifest writes also stream into the session's event log
(``retry_attempt`` / ``manifest_batch`` / ``manifest_failure``), so
the retry trail survives even a run killed before its RetryReport
could be assembled.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from contextlib import contextmanager
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from distributed_join_tpu.ops.join import JoinResult
from distributed_join_tpu.parallel.communicator import Communicator


class FaultInjectedError(RuntimeError):
    """An injected (not organic) failure — raised by
    :class:`FaultInjectingCommunicator` on a scheduled dispatch fault
    so recovery paths can be driven deterministically."""


class PlanValidationError(RuntimeError):
    """A ragged transfer plan failed cross-rank consistency checks.
    Raised from :func:`check_plan_violations` (violations are RECORDED
    by the in-program callback, which also trips the overflow flag —
    raising inside the compiled program would poison the backend's
    dispatch stream for the whole process, turning a diagnosable fault
    into an undiagnosable one)."""


@dataclasses.dataclass
class FaultPlan:
    """Deterministic failure schedule for
    :class:`FaultInjectingCommunicator`. All counters are cumulative
    over the wrapper's lifetime, so one plan describes one scripted
    outage scenario.

    - ``overflow_programs``: the first N programs compiled through
      ``spmd`` report ``JoinResult.overflow`` = True regardless of the
      data — indistinguishable, to the ``auto_retry`` ladder, from a
      genuine capacity squeeze (every retry recompiles, so program
      index == ladder attempt).
    - ``fail_dispatches``: the first N invocations of any compiled
      program raise :class:`FaultInjectedError` at dispatch (a
      transient launch/collective failure).
    - ``fail_after_dispatches``: every invocation AFTER the first N
      raises — a persistent outage, the "killed mid-run" scenario for
      out-of-core resume tests.
    - ``drop_dispatches``: EXACT 1-based dispatch ordinals that raise,
      everything else unaffected — a surgical single-message drop.
      The replication fencing tests use it to make one holder miss
      precisely the ``append`` delta prep (dispatch #2 after the
      register prep) while register and later probes stay healthy.
    - ``dispatch_delay_s``: sleep before each dispatch (a slow/
      congested interconnect; drives deadline paths).
      ``delay_after_dispatches`` defers the delay: the first N
      dispatches run at full speed and every LATER one sleeps — a
      replica that serves healthily and then wedges mid-soak, the
      scripted hang the fleet chaos slice injects (None/0 = delay
      from the first dispatch, the historical behavior).
    - ``corrupt_plan_gathers``: the first N 1-D int32 all-gathers (the
      ragged plan's count exchange) come back rank-INCONSISTENTLY
      perturbed: each rank adds its own rank index to row
      ``seed % n_ranks`` of its gathered view, so every rank plans
      from a different count matrix — exactly the corruption
      :func:`validate_ragged_plan` exists to catch.
    - ``corrupt_mode`` + ``corrupt_collectives``: DATA corruption —
      the adversary the wire-integrity digests
      (parallel/integrity.py) exist for, injected at the collective
      seams so the join sees exactly what a corrupting transport
      would deliver. The first N eligible collectives (counted at
      trace time, cumulative over the wrapper's lifetime — every
      retry recompiles, so a retry ladder can outlast a finite
      budget) are perturbed on rank ``corrupt_rank`` (default
      ``seed % n_ranks``):

      * ``"bit_flip"`` — one bit of one element of a received data
        block flips (seed-addressed; padded blocks via
        ``all_to_all``, ragged buffers via ``ragged_all_to_all``);
      * ``"row_truncate"`` / ``"row_duplicate"`` — a received row
        count drops/gains 1, so the receiver silently loses a real
        row or adopts a garbage one. Padded mode perturbs the target
        rank's received count vector; ragged mode perturbs one entry
        of the gathered count MATRIX identically on every rank — a
        consistent lie that sails through
        :func:`validate_ragged_plan` (whose whole check is
        cross-rank consistency) and is caught only by the digests;
      * ``"misroute"`` — rows land at the wrong rank: the target
        sender's blocks rotate one destination over (padded: the
        received block axis rolls; ragged: two entries of its
        ``input_offsets`` swap).
    """

    seed: int = 0
    overflow_programs: int = 0
    fail_dispatches: int = 0
    fail_after_dispatches: Optional[int] = None
    drop_dispatches: tuple = ()
    dispatch_delay_s: float = 0.0
    delay_after_dispatches: Optional[int] = None
    corrupt_plan_gathers: int = 0
    corrupt_mode: Optional[str] = None
    corrupt_collectives: int = 0
    corrupt_rank: Optional[int] = None


def plan_from_record(record: dict) -> FaultPlan:
    """Rebuild a :class:`FaultPlan` from its JSON-shaped record (the
    inverse of ``dataclasses.asdict``, unknown keys refused loudly) —
    the seam behind the daemon's ``--fault-plan`` flag, which lets the
    fleet chaos harness script a replica's outage from the command
    line instead of patching code."""
    known = {f.name for f in dataclasses.fields(FaultPlan)}
    unknown = set(record) - known
    if unknown:
        raise ValueError(
            f"unknown FaultPlan field(s) {sorted(unknown)}; "
            f"known: {sorted(known)}")
    record = dict(record)
    if record.get("drop_dispatches") is not None:
        record["drop_dispatches"] = tuple(record["drop_dispatches"])
    return FaultPlan(**record)


CORRUPTION_MODES = ("bit_flip", "row_truncate", "row_duplicate",
                    "misroute")


class FaultInjectingCommunicator(Communicator):
    """A ``Communicator`` decorator that injects scheduled faults.

    Collective semantics are delegated verbatim to the wrapped backend;
    injection happens at the wrapper's OWN seams (program build,
    program dispatch, the plan-count gather), so the orchestrator and
    shuffle code run unmodified — what they see is indistinguishable
    from the real failure. Unknown attributes (``device_put_sharded``,
    ``mesh``, ...) delegate to the wrapped communicator.
    """

    def __init__(self, inner: Communicator, plan: FaultPlan):
        self._inner = inner
        self.plan = plan
        if (plan.corrupt_mode is not None
                and plan.corrupt_mode not in CORRUPTION_MODES):
            raise ValueError(
                f"unknown corrupt_mode {plan.corrupt_mode!r}; "
                f"pick one of {CORRUPTION_MODES}"
            )
        self.name = f"faulty({inner.name})"
        self._programs_built = 0
        self._dispatches = 0
        self._plan_gathers = 0
        self._corruptions = 0

    # -- delegation ---------------------------------------------------

    @property
    def n_ranks(self) -> int:
        return self._inner.n_ranks

    def all_to_all(self, x):
        return self._corrupt_exchanged(self._inner.all_to_all(x))

    def ppermute_all_to_all(self, x):
        return self._corrupt_exchanged(
            self._inner.ppermute_all_to_all(x))

    # -- hierarchical tier seams (two-level shuffle) ------------------
    # Explicit wrappers, not __getattr__ delegation: delegation would
    # hand back the INNER communicator's bound methods and the
    # corruption seams would silently never see hierarchical traffic.

    @property
    def n_slices(self) -> int:
        return self._inner.n_slices

    @property
    def chips_per_slice(self) -> int:
        return self._inner.chips_per_slice

    def all_to_all_chip(self, x):
        """The intra-slice (ICI) hop — delivered CLEAN by design: the
        chaos model targets the new cross-slice transport (the DCN
        tier is the long, lossy haul the wire-integrity digests exist
        for); ICI corruption is already exercised through the flat
        all_to_all seam every non-hierarchical config routes."""
        return self._inner.all_to_all_chip(x)

    def all_to_all_slice(self, x):
        """The cross-slice (DCN) exchange seam: the same corruption
        modes as the flat data plane (bit_flip / misroute roll /
        count slip on a 1-D int32 vector), injected on what a
        corrupting DCN transport would deliver. The received block's
        leading axis is the source-slice axis, so a misroute roll
        mis-attributes whole slices — rows that hash elsewhere enter
        the local join, exactly the adversary the end-to-end pair
        digests catch."""
        return self._corrupt_exchanged(
            self._inner.all_to_all_slice(x))

    def axis_index(self):
        return self._inner.axis_index()

    def pvary(self, x):
        return self._inner.pvary(x)

    def psum(self, x):
        return self._inner.psum(x)

    def finalize(self) -> None:
        self._inner.finalize()

    def __getattr__(self, name):
        # Only reached for attributes not defined on the wrapper
        # (device_put_sharded, mesh, axis_name, ...).
        return getattr(self._inner, name)

    # -- injection seams ----------------------------------------------

    def _corrupt_rank(self) -> int:
        """The rank whose traffic the corruption modes hit (static)."""
        t = self.plan.corrupt_rank
        return (self.plan.seed if t is None else t) % self.n_ranks

    def _corrupt_budget(self) -> bool:
        """Trace-time budget: True for the first
        ``corrupt_collectives`` eligible collectives over the
        wrapper's lifetime (retries recompile, so a finite budget
        exhausts and a retried program runs clean)."""
        if (self.plan.corrupt_mode is None
                or self._corruptions >= self.plan.corrupt_collectives):
            return False
        self._corruptions += 1
        return True

    def rearm_corruption(self) -> None:
        """Reset the corruption budget so the NEXT traced program
        carries the schedule again. The drivers' verification seam
        (``benchmarks.collect_integrity``) calls this before tracing
        its verified step: the budget was spent corrupting the timed
        program traced earlier, and without rearming, the separate
        verification program would trace clean and bless benchmark
        numbers the corruption already touched."""
        self._corruptions = 0

    def _corrupt_exchanged(self, y):
        """Corruption of an all_to_all/ppermute result as the receiver
        sees it. 1-D int32 (n,) vectors are the padded shuffle's count
        exchange (truncate/duplicate seam: the target rank's received
        count for one sender slips by 1); >=2-D blocks are the data
        plane (bit_flip / misroute seams)."""
        mode = self.plan.corrupt_mode
        if mode is None:
            return y
        n = self.n_ranks
        # The count exchange: the padded shuffle's (n,) vector, or the
        # hierarchical route's (slices, chips)-nested view of the same
        # n counts (shuffle._hier_route — an int32 block of exactly n
        # entries on the tier seams).
        is_counts = (y.dtype == jnp.int32 and y.size == n
                     and y.ndim in (1, 2))
        if (mode in ("row_truncate", "row_duplicate") and is_counts
                and self._corrupt_budget()):
            j = (self.plan.seed // n) % n
            delta = jnp.int32(-1 if mode == "row_truncate" else 1)
            active = (self.axis_index()
                      == jnp.int32(self._corrupt_rank()))
            flat = y.reshape(-1)
            flat = flat.at[j].add(delta * active.astype(jnp.int32))
            return jnp.maximum(flat, 0).reshape(y.shape)
        if mode == "bit_flip" and y.ndim >= 2 \
                and self._corrupt_budget():
            active = (self.axis_index()
                      == jnp.int32(self._corrupt_rank()))
            return _flip_one_bit(y, self.plan.seed, active)
        if mode == "misroute" and y.ndim >= 2 and n > 1 \
                and self._corrupt_budget():
            # The received sender-block axis rotates by one on the
            # target rank: every block is attributed to the wrong
            # source — rows that hash elsewhere enter the local join.
            active = (self.axis_index()
                      == jnp.int32(self._corrupt_rank()))
            return jnp.where(active, jnp.roll(y, 1, axis=0), y)
        return y

    def ragged_all_to_all(self, operand, output, input_offsets,
                          send_sizes, output_offsets, recv_sizes):
        mode = self.plan.corrupt_mode
        n = self.n_ranks
        if mode == "misroute" and n > 1 and self._corrupt_budget():
            # The target SENDER reads two destinations' rows from each
            # other's bucket offsets — its rows land at wrong ranks.
            d1 = self.plan.seed % n
            d2 = (d1 + 1 + (self.plan.seed // n) % (n - 1)) % n
            swapped = input_offsets.at[d1].set(
                input_offsets[d2]).at[d2].set(input_offsets[d1])
            active = (self.axis_index()
                      == jnp.int32(self._corrupt_rank()))
            input_offsets = jnp.where(active, swapped, input_offsets)
        out = self._inner.ragged_all_to_all(
            operand, output, input_offsets, send_sizes,
            output_offsets, recv_sizes,
        )
        if mode == "bit_flip" and self._corrupt_budget():
            active = (self.axis_index()
                      == jnp.int32(self._corrupt_rank()))
            out = _flip_one_bit(out, self.plan.seed, active)
        return out

    def all_gather(self, x):
        g = self._inner.all_gather(x)
        if (self.plan.corrupt_mode in ("row_truncate", "row_duplicate")
                and x.ndim == 1 and x.dtype == jnp.int32
                and x.shape[0] == self.n_ranks
                and self._corrupt_budget()):
            # The ragged plan's count-matrix gather, perturbed
            # IDENTICALLY on every rank: a consistent lie about how
            # many rows the target sender routes to one destination.
            # validate_ragged_plan only checks cross-rank consistency,
            # so this sails through it — the wire digests are the only
            # layer that can catch it (sender digests commit to the
            # TRUE local counts before any exchange).
            n = self.n_ranks
            row = self._corrupt_rank()
            col = (self.plan.seed // n) % n
            delta = -1 if self.plan.corrupt_mode == "row_truncate" \
                else 1
            g2 = g.reshape(n, n).at[row, col].add(jnp.int32(delta))
            g = jnp.maximum(g2, 0).reshape(g.shape)
        if (x.ndim == 1 and x.dtype == jnp.int32
                and x.shape[0] == self.n_ranks
                and self._plan_gathers < self.plan.corrupt_plan_gathers):
            # The ragged plan's count-vector gather (shuffle.py
            # _ragged_plan_matrices). Perturb rank-dependently: every
            # rank sees a DIFFERENT count matrix, the defining
            # property of a corrupted/racing metadata exchange. Rank 0
            # adds 0 — inconsistency, not a uniform shift.
            self._plan_gathers += 1
            n = self.n_ranks
            row = self.plan.seed % n
            me = self.axis_index()
            g2 = g.reshape(n, n)
            g2 = g2.at[row].add(me.astype(g2.dtype))
            g = g2.reshape(g.shape)
        return g

    def spmd(self, fn: Callable, *, sharded_out=None) -> Callable:
        idx = self._programs_built
        self._programs_built += 1
        inject_overflow = idx < self.plan.overflow_programs

        def wrapped(*args):
            out = fn(*args)
            if inject_overflow:
                if isinstance(out, JoinResult):
                    out = dataclasses.replace(
                        out, overflow=out.overflow | jnp.bool_(True)
                    )
                elif (isinstance(out, tuple) and out
                      and isinstance(out[0], JoinResult)):
                    # The telemetry-instrumented step returns
                    # (JoinResult, Metrics); the squeeze must look the
                    # same to the ladder either way.
                    out = (dataclasses.replace(
                        out[0],
                        overflow=out[0].overflow | jnp.bool_(True),
                    ),) + out[1:]
            return out

        compiled = self._inner.spmd(wrapped, sharded_out=sharded_out)

        def dispatch(*args, **kwargs):
            self._dispatches += 1
            if self.plan.dispatch_delay_s and self._dispatches > (
                    self.plan.delay_after_dispatches or 0):
                time.sleep(self.plan.dispatch_delay_s)
            if self._dispatches <= self.plan.fail_dispatches:
                raise FaultInjectedError(
                    f"injected dispatch failure #{self._dispatches} "
                    f"(fail_dispatches={self.plan.fail_dispatches})"
                )
            if self._dispatches in (self.plan.drop_dispatches or ()):
                raise FaultInjectedError(
                    f"injected dispatch drop #{self._dispatches} "
                    f"(drop_dispatches={self.plan.drop_dispatches})"
                )
            after = self.plan.fail_after_dispatches
            if after is not None and self._dispatches > after:
                raise FaultInjectedError(
                    f"injected persistent outage: dispatch "
                    f"#{self._dispatches} > fail_after_dispatches={after}"
                )
            return compiled(*args, **kwargs)

        return dispatch


def _flip_one_bit(block, seed: int, active):
    """One bit of one (seed-addressed) element of ``block`` flips where
    ``active`` (a traced bool — the corrupt rank predicate) holds: the
    minimal in-flight payload corruption. Integer dtypes (and f32, via
    bitcast) flip a real bit; f64 — whose bitcast the TPU x64 rewriter
    can't lower — degrades to an additive nudge, which serves the same
    adversarial purpose."""
    dt = block.dtype
    if jnp.issubdtype(dt, jnp.floating):
        if dt == jnp.float32:
            u = jax.lax.bitcast_convert_type(block, jnp.uint32)
            return jax.lax.bitcast_convert_type(
                _flip_one_bit(u, seed, active), dt)
        flat = block.reshape(-1)
        idx = seed % flat.shape[0]
        bumped = flat.at[idx].add(jnp.where(active, dt.type(1.0),
                                            dt.type(0.0)))
        return bumped.reshape(block.shape)
    flat = block.reshape(-1)
    idx = seed % flat.shape[0]
    nbits = dt.itemsize * 8
    bit = (seed // max(flat.shape[0], 1)) % nbits
    # left_shift wraps into the sign bit for bit == nbits-1 — exactly a
    # bit flip there too (two's complement).
    mask = jnp.left_shift(jnp.asarray(1, dt), bit)
    flipped = flat.at[idx].set(
        jnp.where(active, flat[idx] ^ mask, flat[idx]))
    return flipped.reshape(block.shape)


# -- ragged-plan validation -------------------------------------------

_PLAN_VALIDATION: Optional[bool] = None  # None -> env decides


def plan_validation_enabled() -> bool:
    """Whether :func:`validate_ragged_plan` should be woven into newly
    TRACED ragged shuffles (already-compiled programs are unaffected)."""
    if _PLAN_VALIDATION is not None:
        return _PLAN_VALIDATION
    return os.environ.get("DJTPU_VALIDATE_PLANS", "") not in ("", "0")


@contextmanager
def validate_plans(enabled: bool = True):
    """Force plan validation on (or off) for programs traced inside the
    context — the test/debug switch; production uses the
    ``DJTPU_VALIDATE_PLANS`` env var."""
    global _PLAN_VALIDATION
    prev = _PLAN_VALIDATION
    _PLAN_VALIDATION = enabled
    try:
        yield
    finally:
        _PLAN_VALIDATION = prev


_plan_violations: list = []


def plan_violations() -> list:
    """Messages recorded by validation callbacks since the last
    :func:`check_plan_violations` (newest last)."""
    return list(_plan_violations)


def clear_plan_violations() -> None:
    """Drop recorded violations. The record list is process-global, so
    a harness that is about to attribute violations to ONE program
    (``distributed_inner_join`` does, before each attempt) must clear
    leftovers from earlier programs whose caller never checked —
    otherwise a stale message fails a healthy join."""
    _plan_violations.clear()


def check_plan_violations(clear: bool = True) -> None:
    """Raise :class:`PlanValidationError` if any validated program
    observed an inconsistent plan. Call after CONSUMING the program's
    outputs (the callback runs with the program; consuming any output
    array sequences after it). ``distributed_inner_join`` calls this
    for you after every attempt when validation is enabled."""
    if not _plan_violations:
        return
    msg = "; ".join(_plan_violations)
    if clear:
        _plan_violations.clear()
    raise PlanValidationError(msg)


def _plan_check_host(ok, where="shuffle_ragged"):
    import numpy as np

    if bool(ok):
        return np.int32(0)
    import warnings

    msg = (
        f"ragged plan inconsistent across ranks in {where}: "
        "send/recv/offset vectors disagree — the exchange would "
        "corrupt rows (emulation) or hang (TPU hardware op). "
        "A rank computed its plan from a different count matrix; "
        "suspect a corrupted/raced metadata all-gather."
    )
    # Record + warn + trip the overflow flag (the returned 1), never
    # raise: an exception inside a backend callback poisons the
    # process-wide dispatch stream. check_plan_violations() is the
    # raise point.
    _plan_violations.append(msg)
    warnings.warn(msg, stacklevel=2)
    return np.int32(1)


def validate_ragged_plan(comm: Communicator, send_sizes, recv_sizes,
                         output_offsets, out_capacity: int,
                         where: str = "shuffle_ragged"):
    """Cross-rank consistency check of a ragged transfer plan.

    Every rank all-gathers its (send_sizes, recv_sizes,
    output_offsets) triple and re-derives what its peers must hold;
    ``lax.ragged_all_to_all``'s contract requires these vectors to be
    MUTUALLY consistent across ranks, and nothing on the data path
    checks it. Verified invariants (identical math on every rank):

    - transpose consistency: rank j's ``send_sizes[i]`` equals rank
      i's ``recv_sizes[j]`` — what one side sends the other expects;
    - bounds: sizes non-negative, every write interval
      ``[offset, offset + send)`` within ``out_capacity``;
    - receiver packing: on each receiver, sender blocks are disjoint
      and orderly — offsets non-decreasing in sender rank with at
      least the clamped received rows between consecutive offsets.

    Returns an int32 scalar token (0 = consistent, 1 = violated) that
    the caller must fold into a live output so the check cannot be
    dead-code-eliminated — the ragged shuffle ORs it into its overflow
    flag, so a corrupted plan also reads as "do not trust this
    result". On violation the embedded host callback records the
    message (see :func:`check_plan_violations`, the raise point) and
    emits a warning; it deliberately does NOT raise in-program — a
    callback exception poisons the backend's process-wide dispatch
    stream. Cost: one (n, 3n) int32 all-gather plus O(n^2) scalar
    math — debug mode only.
    """
    n = comm.n_ranks
    me = comm.axis_index()
    mine = jnp.stack([
        send_sizes.astype(jnp.int32),
        recv_sizes.astype(jnp.int32),
        output_offsets.astype(jnp.int32),
    ], axis=0)                                       # (3, n) — 2-D, so
    # FaultPlan.corrupt_plan_gathers' 1-D predicate never corrupts the
    # validation gather itself.
    g = comm.all_gather(mine.reshape(1, 3 * n)).reshape(n, 3, n)
    g_send, g_recv, g_off = g[:, 0, :], g[:, 1, :], g[:, 2, :]

    ok = jnp.bool_(True)
    # transpose consistency: G_send[j, i] == G_recv[i, j]
    ok = ok & jnp.all(g_send == g_recv.T)
    # bounds
    ok = ok & jnp.all(g_send >= 0) & jnp.all(g_recv >= 0)
    ok = ok & jnp.all(g_off >= 0)
    # Bounds apply only to senders that actually transfer: offsets are
    # the UNclamped receiver-side prefix starts, so a sender squeezed
    # out entirely by a capacity clamp legitimately carries
    # start > out_capacity with send == 0 — a zero-size transfer at
    # any offset is valid, and flagging it would turn every
    # recoverable overflow into a phantom "corrupted plan".
    ok = ok & jnp.all(jnp.where(g_send > 0,
                                g_off + g_send <= out_capacity, True))
    # receiver packing: per receiver i, sender offsets non-decreasing
    # with at least the received rows between consecutive blocks.
    # g_off[j, i] is where sender j's block starts on receiver i.
    off_r = g_off.T                                  # (receiver, sender)
    recv_r = g_recv                                  # (receiver, sender)
    gap_ok = off_r[:, 1:] >= off_r[:, :-1] + recv_r[:, :-1]
    ok = ok & jnp.all(gap_ok)
    # A locally-consistent view can still differ from a peer's; the
    # transpose check above catches that, but only if the gathers
    # themselves delivered each rank's true vectors — psum the verdict
    # so ONE unhappy rank fails everyone deterministically.
    n_bad = comm.psum((~ok).astype(jnp.int32))
    ok_global = n_bad == 0
    from functools import partial

    tok = jax.pure_callback(
        partial(_plan_check_host, where=where),
        jax.ShapeDtypeStruct((), jnp.int32),
        ok_global,
    )
    return tok


# -- retry with backoff (bootstrap + generic transient failures) ------


def retry_with_backoff(
    fn: Callable,
    *,
    max_attempts: int = 3,
    backoff_s: float = 1.0,
    backoff_factor: float = 2.0,
    deadline_s: Optional[float] = None,
    retry_on=(Exception,),
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    on_retry: Optional[Callable] = None,
):
    """Call ``fn()`` with exponential backoff on failure.

    Returns ``(result, attempts)`` where ``attempts`` is a list of
    per-attempt records ``{"attempt", "elapsed_s", "error"}`` (error is
    None on the success entry) — the machine-readable trail
    :class:`distributed_join_tpu.parallel.bootstrap.BootstrapError`
    embeds in driver JSON output. Raises the LAST error (unwrapped,
    with the trail attached as ``exc._retry_attempts``) when attempts
    or the deadline run out; callers wrap it in their domain error.
    ``sleep``/``clock`` are injectable for tests.
    """
    if max_attempts < 1:
        raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
    t0 = clock()
    attempts = []
    delay = backoff_s
    last = None
    for attempt in range(max_attempts):
        ta = clock()
        try:
            result = fn()
            attempts.append({"attempt": attempt,
                             "elapsed_s": clock() - ta, "error": None})
            return result, attempts
        except retry_on as exc:  # noqa: PERF203 - retry loop
            last = exc
            attempts.append({
                "attempt": attempt,
                "elapsed_s": clock() - ta,
                "error": f"{type(exc).__name__}: {exc}",
            })
            out_of_time = (
                deadline_s is not None
                and clock() - t0 + delay > deadline_s
            )
            if attempt == max_attempts - 1 or out_of_time:
                break
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            sleep(delay)
            delay *= backoff_factor
    last._retry_attempts = attempts
    raise last


# -- the auto_retry capacity ladder, reified --------------------------


@dataclasses.dataclass(frozen=True)
class RetryAttempt:
    """One rung of the ladder: the sizing that ran and what happened.
    ``action`` is what produced this attempt's sizing ("initial",
    "widen_compression_bits", "double_capacities", or
    "retry_integrity" — a same-sizing rerun after a wire-integrity
    mismatch). ``integrity_ok`` is the digest verdict when the attempt
    was verified (None: verification off, or skipped on overflow)."""

    attempt: int
    action: str
    overflow: Optional[bool]           # None: never ran (ladder abandoned)
    shuffle_capacity_factor: float
    out_capacity_factor: float
    out_rows_per_rank: Optional[int]
    compression_bits: Optional[int]
    hh_build_capacity: Optional[int]
    hh_probe_capacity: Optional[int]
    hh_out_capacity: Optional[int]
    integrity_ok: Optional[bool] = None

    def as_record(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class RetryReport:
    """The full retry trail of one ``auto_retry`` join — which
    capacities doubled, why, per attempt. Attached host-side to the
    ``JoinResult`` of :func:`..distributed_join.distributed_inner_join`
    (as ``res.retry_report``) and embedded in benchmark driver JSON."""

    attempts: tuple

    @property
    def n_attempts(self) -> int:
        return len(self.attempts)

    @property
    def resolved(self) -> Optional[bool]:
        """True when the final attempt ran clean, False when it still
        overflowed, None when nothing ran."""
        if not self.attempts:
            return None
        last = self.attempts[-1].overflow
        return None if last is None else not last

    def as_record(self) -> Optional[dict]:
        """JSON-shaped record (None when the join ran once, clean,
        from rung 0 — so drivers can emit ``"retry": null`` for the
        common case). A tuner-seeded ladder (``base_rung > 0``) keeps
        its record even for a single clean attempt: the rung label
        and its sizing ARE the information the workload-history store
        persists for the next pre-size."""
        if self.n_attempts <= 1 and self.resolved and (
                not self.attempts or self.attempts[0].attempt == 0):
            return None
        return {
            "n_attempts": self.n_attempts,
            "resolved": self.resolved,
            "attempts": [a.as_record() for a in self.attempts],
        }


class CapacityLadder:
    """The overflow-escalation policy behind ``auto_retry``, extracted
    so every harness (``distributed_inner_join``, the benchmark
    drivers, ``bench.py``) escalates identically and the decisions are
    reportable.

    Policy (unchanged from the historical inline loop):

    1. compression first — a codec-width overflow is indistinguishable
       from a capacity overflow on the flag, and widening bits is the
       CHEAP axis (at most 3 bits-only recompiles 4->8->16->32) versus
       inflating every buffer up to 8x for nothing (review r4);
    2. then double every capacity a retry can relieve: the shuffle and
       output factors, ``out_rows_per_rank`` when set (it supersedes
       the factor), and — when the skew path is on — the HH capacities,
       which JUMP straight to full local probe coverage rather than
       creeping, because one retry must cover ANY skew (alpha >= 1.4
       puts ~90% of probe rows in the HH set).
    """

    def __init__(self, *, shuffle_capacity_factor: float,
                 out_capacity_factor: float,
                 out_rows_per_rank: Optional[int] = None,
                 compression_bits: Optional[int] = None,
                 skew: bool = False,
                 hh_build_capacity: Optional[int] = None,
                 hh_probe_capacity: Optional[int] = None,
                 hh_out_capacity: Optional[int] = None,
                 local_probe_rows: Optional[int] = None,
                 base_rung: int = 0):
        self.shuffle_f = shuffle_capacity_factor
        self.out_f = out_capacity_factor
        self.out_rows = out_rows_per_rank
        self.bits = compression_bits
        self.skew = skew
        self.hh_build = hh_build_capacity
        self.hh_probe = hh_probe_capacity
        self.hh_out = hh_out_capacity
        self.p_local = local_probe_rows
        # Rung-label offset for a history-pre-sized ladder (the
        # autotuner, planning/tuner.py): a warm run starting at the
        # sizing a cold run escalated to carries the SAME absolute
        # rung label, so its program signature equals the executable
        # already resident in the cache — the zero-retrace contract.
        self.base_rung = base_rung
        self._action = ("initial" if base_rung == 0
                        else "tuned_presize")
        self._attempts: list = []

    @property
    def next_rung(self) -> int:
        """Absolute rung label of the attempt about to run."""
        return self.base_rung + len(self._attempts)

    def seed_rung(self, rung: int) -> None:
        """Start the ladder at an absolute rung label (the autotuner
        pre-sized the knobs to a rung a previous run escalated to;
        the sizing itself was already applied to the ladder's
        construction kwargs). No-op for rung 0."""
        if rung:
            self.base_rung = int(rung)
            self._action = "tuned_presize"

    def sizing(self) -> dict:
        """Keyword arguments for ``make_join_step`` /
        ``make_distributed_join`` at the current rung."""
        return dict(
            shuffle_capacity_factor=self.shuffle_f,
            out_capacity_factor=self.out_f,
            out_rows_per_rank=self.out_rows,
            compression_bits=self.bits,
            hh_build_capacity=self.hh_build,
            hh_probe_capacity=self.hh_probe,
            hh_out_capacity=self.hh_out,
        )

    def note(self, overflow: Optional[bool],
             integrity_ok: Optional[bool] = None) -> None:
        """Record the outcome of running the current rung. The attempt
        also lands in the telemetry event log (the RetryReport's
        per-attempt record, streamed as it happens — a killed run
        keeps the trail its report would have carried)."""
        att = RetryAttempt(
            attempt=self.base_rung + len(self._attempts),
            action=self._action,
            overflow=overflow,
            shuffle_capacity_factor=self.shuffle_f,
            out_capacity_factor=self.out_f,
            out_rows_per_rank=self.out_rows,
            compression_bits=self.bits,
            hh_build_capacity=self.hh_build,
            hh_probe_capacity=self.hh_probe,
            hh_out_capacity=self.hh_out,
            integrity_ok=integrity_ok,
        )
        self._attempts.append(att)
        from distributed_join_tpu import telemetry

        telemetry.event("retry_attempt", **att.as_record())

    def escalate(self) -> str:
        """Advance one rung; returns the action taken."""
        if self.bits is not None and self.bits < 32:
            self.bits = min(self.bits * 2, 32)
            self._action = "widen_compression_bits"
            return self._action
        self.shuffle_f *= 2.0
        self.out_f *= 2.0
        if self.out_rows is not None:
            self.out_rows *= 2
        if self.skew:
            if self.hh_build is not None:
                self.hh_build *= 2
            if self.hh_probe is not None:
                self.hh_probe = (max(self.hh_probe * 2, self.p_local)
                                 if self.p_local else self.hh_probe * 2)
            if self.hh_out is not None:
                self.hh_out = (max(self.hh_out * 2, self.p_local)
                               if self.p_local else self.hh_out * 2)
        self._action = "double_capacities"
        return self._action

    def hold(self, action: str = "retry_integrity") -> str:
        """Advance to a rung with the SAME sizing — the retry that
        answers a wire-integrity mismatch (corruption is transient;
        the capacities were right) rather than an overflow. The rerun
        still recompiles, so a finite injected corruption budget
        (FaultPlan.corrupt_collectives) exhausts across holds."""
        self._action = action
        return self._action

    def report(self) -> RetryReport:
        return RetryReport(attempts=tuple(self._attempts))


# -- out-of-core progress manifest ------------------------------------


class ManifestMismatchError(RuntimeError):
    """An existing manifest describes a DIFFERENT run (batch count,
    capacities, or per-batch row counts changed) — resuming against it
    would silently merge unrelated partial results."""


class JoinManifest:
    """Durable per-batch progress for the out-of-core join loop.

    One JSON file, rewritten atomically (tmp + ``os.replace``) after
    every batch completes, holding the run's config fingerprint, each
    completed batch's exact match total + overflow flag, and a bounded
    failure log. A killed run resumes from the first incomplete batch;
    matching keys land in the same batch on both sides, so batch totals
    are independent and the resumed sum is bit-exact (the acceptance
    contract of this layer). Format documented in
    docs/FAILURE_SEMANTICS.md.
    """

    VERSION = 1
    MAX_FAILURES = 50

    def __init__(self, path: str, config: dict,
                 on_mismatch: str = "raise"):
        """Load (and verify) an existing manifest at ``path`` or start
        a fresh one. ``on_mismatch``: "raise" (default) or "restart" —
        discard the stale manifest and start over."""
        self.path = path
        self.config = config
        self._data = {"version": self.VERSION, "config": config,
                      "batches": {}, "failures": []}
        if os.path.exists(path):
            with open(path) as f:
                existing = json.load(f)
            if (existing.get("version") != self.VERSION
                    or existing.get("config") != config):
                if on_mismatch == "restart":
                    self._write()
                else:
                    raise ManifestMismatchError(
                        f"manifest {path} was written by a different "
                        f"run config; refusing to resume against it "
                        f"(have {existing.get('config')!r}, "
                        f"want {config!r}). Delete the file or pass "
                        "a fresh manifest path to start over."
                    )
            else:
                self._data = existing
        else:
            self._write()

    @property
    def completed(self) -> dict:
        """{batch_id (int): {"total": int, "overflow": bool}}"""
        return {int(k): v for k, v in self._data["batches"].items()}

    @property
    def failures(self) -> list:
        return list(self._data["failures"])

    def record_batch(self, batch: int, total: int,
                     overflow: bool) -> None:
        self._data["batches"][str(batch)] = {
            "total": int(total), "overflow": bool(overflow),
        }
        self._write()
        from distributed_join_tpu import telemetry

        telemetry.event("manifest_batch", path=self.path,
                        batch=int(batch), total=int(total),
                        overflow=bool(overflow))

    def record_failure(self, batch: int, error: str,
                       attempt: int) -> None:
        log = self._data["failures"]
        log.append({"batch": int(batch), "attempt": int(attempt),
                    "error": error})
        del log[:-self.MAX_FAILURES]
        self._write()
        from distributed_join_tpu import telemetry

        telemetry.event("manifest_failure", path=self.path,
                        batch=int(batch), attempt=int(attempt),
                        error=error)

    def _write(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._data, f, indent=1)
        os.replace(tmp, self.path)


def batch_config_fingerprint(build_batches: Sequence, probe_batches:
                             Sequence, n_ranks: int, key,
                             bcap: int, pcap: int) -> dict:
    """The identity a manifest binds to: resuming only makes sense
    against the SAME batching of the same tables, and per-batch row
    counts are a cheap, order-sensitive witness of that."""
    return {
        "n_batches": len(build_batches),
        "n_ranks": int(n_ranks),
        "key": list(key) if isinstance(key, (list, tuple)) else key,
        "build_capacity": int(bcap),
        "probe_capacity": int(pcap),
        "build_rows": [int(next(iter(b.values())).shape[0])
                       for b in build_batches],
        "probe_rows": [int(next(iter(b.values())).shape[0])
                       for b in probe_batches],
    }
