"""Multi-host bootstrap — the reference's MPI control plane, TPU-native.

The reference launches one OS process per GPU under ``mpirun``; MPI is
pure control plane (rank/size, ``ncclGetUniqueId`` broadcast, barriers)
while NCCL/UCX own the data plane (SURVEY.md §3.3). The 1:1 TPU mapping:

- control plane: ``jax.distributed.initialize(coordinator_address,
  num_processes, process_id)`` — a TCP/DCN handshake with a coordinator
  replaces ``MPI_Bcast`` of the NCCL id;
- data plane: unchanged — after initialization ``jax.devices()`` spans
  every process's chips, the 1-D rank mesh covers the whole slice, and
  the SAME compiled ``shard_map`` program runs on it, XLA routing
  collectives over ICI within a host and DCN across hosts.

Nothing else in the framework changes for multi-host: the
``Communicator`` is already built on the global ``jax.devices()`` view.

Configuration comes from flags or the ``DJTPU_*`` environment variables
(set by ``scripts/launch_multiprocess.py``, the framework's ``mpirun``
equivalent):

  DJTPU_COORDINATOR    host:port of process 0 (the coordinator)
  DJTPU_NUM_PROCESSES  total process count
  DJTPU_PROCESS_ID     this process's id in [0, num_processes)

For a no-TPU validation path (the reference cannot do this at all —
its multi-rank tests need real GPUs under mpirun, SURVEY.md §4), set
``DJTPU_CPU_DEVICES_PER_PROCESS=k``: each process presents ``k``
virtual CPU devices and cross-process collectives run over the gloo CPU
backend — verified working in this environment (2 procs x 4 devices).
"""

from __future__ import annotations

import os
from typing import Optional

ENV_COORDINATOR = "DJTPU_COORDINATOR"
ENV_NUM_PROCESSES = "DJTPU_NUM_PROCESSES"
ENV_PROCESS_ID = "DJTPU_PROCESS_ID"
ENV_CPU_DEVICES = "DJTPU_CPU_DEVICES_PER_PROCESS"


def initialize(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    cpu_devices_per_process: Optional[int] = None,
) -> None:
    """Join the distributed runtime. Call BEFORE any other jax use —
    like ``MPI_Init``, this must precede every collective/device call.

    ``cpu_devices_per_process`` switches to the virtual-CPU data plane
    (gloo): multi-host semantics without TPU hardware.
    """
    import jax

    # Record the identity for process_id()/is_coordinator() even when
    # this is called directly (one invocation per host) rather than via
    # the tpu-launch env.
    os.environ[ENV_NUM_PROCESSES] = str(num_processes)
    os.environ[ENV_PROCESS_ID] = str(process_id)

    if cpu_devices_per_process is not None:
        # OVERRIDE any inherited device-count flag (e.g. a test harness
        # parent sets 8): each launched process must present exactly
        # cpu_devices_per_process devices or the global mesh is wrong.
        flags = [
            f for f in os.environ.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(
            "--xla_force_host_platform_device_count="
            f"{cpu_devices_per_process}"
        )
        os.environ["XLA_FLAGS"] = " ".join(flags)
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
        # Cross-process CPU collectives need an explicit transport.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def maybe_initialize_from_env() -> bool:
    """Initialize iff the ``DJTPU_*`` launch env is present; returns
    whether it did. Drivers call this first thing, so a single-process
    run (no env) is untouched and a launched run joins its slice."""
    coord = os.environ.get(ENV_COORDINATOR)
    if not coord:
        return False
    nproc = int(os.environ[ENV_NUM_PROCESSES])
    pid = int(os.environ[ENV_PROCESS_ID])
    cpu = os.environ.get(ENV_CPU_DEVICES)
    initialize(coord, nproc, pid,
               cpu_devices_per_process=int(cpu) if cpu else None)
    return True


def process_id() -> int:
    """This process's id (0 when not launched distributed) — the
    reference's ``rank`` for rank-0-only printing.

    Once a backend exists, ``jax.process_index()`` is authoritative —
    a user may have called ``jax.distributed.initialize`` themselves
    (or relied on TPU-pod auto-detection) without any ``DJTPU_*`` env,
    and every host believing it is rank 0 would duplicate reports and
    race on ``--json-output``. The env is only a pre-initialization
    fallback; probing it must not itself initialize a backend."""
    from jax._src import xla_bridge

    if getattr(xla_bridge, "_backends", None):
        import jax

        return jax.process_index()
    return int(os.environ.get(ENV_PROCESS_ID, "0"))


def is_coordinator() -> bool:
    return process_id() == 0
