"""Multi-host bootstrap — the reference's MPI control plane, TPU-native.

The reference launches one OS process per GPU under ``mpirun``; MPI is
pure control plane (rank/size, ``ncclGetUniqueId`` broadcast, barriers)
while NCCL/UCX own the data plane (SURVEY.md §3.3). The 1:1 TPU mapping:

- control plane: ``jax.distributed.initialize(coordinator_address,
  num_processes, process_id)`` — a TCP/DCN handshake with a coordinator
  replaces ``MPI_Bcast`` of the NCCL id;
- data plane: unchanged — after initialization ``jax.devices()`` spans
  every process's chips, the 1-D rank mesh covers the whole slice, and
  the SAME compiled ``shard_map`` program runs on it, XLA routing
  collectives over ICI within a host and DCN across hosts.

Nothing else in the framework changes for multi-host: the
``Communicator`` is already built on the global ``jax.devices()`` view.

Configuration comes from flags or the ``DJTPU_*`` environment variables
(set by ``scripts/launch_multiprocess.py``, the framework's ``mpirun``
equivalent):

  DJTPU_COORDINATOR    host:port of process 0 (the coordinator)
  DJTPU_NUM_PROCESSES  total process count
  DJTPU_PROCESS_ID     this process's id in [0, num_processes)

For a no-TPU validation path (the reference cannot do this at all —
its multi-rank tests need real GPUs under mpirun, SURVEY.md §4), set
``DJTPU_CPU_DEVICES_PER_PROCESS=k``: each process presents ``k``
virtual CPU devices and cross-process collectives run over the gloo CPU
backend — verified working in this environment (2 procs x 4 devices).
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional

ENV_COORDINATOR = "DJTPU_COORDINATOR"
ENV_NUM_PROCESSES = "DJTPU_NUM_PROCESSES"
ENV_PROCESS_ID = "DJTPU_PROCESS_ID"
ENV_CPU_DEVICES = "DJTPU_CPU_DEVICES_PER_PROCESS"
# Failure-semantics knobs (docs/FAILURE_SEMANTICS.md): overall
# handshake deadline, attempt count, and first-retry backoff.
ENV_BOOTSTRAP_DEADLINE = "DJTPU_BOOTSTRAP_DEADLINE"
ENV_BOOTSTRAP_RETRIES = "DJTPU_BOOTSTRAP_RETRIES"
ENV_BOOTSTRAP_BACKOFF = "DJTPU_BOOTSTRAP_BACKOFF"

DEFAULT_DEADLINE_S = 300.0
DEFAULT_RETRIES = 3
DEFAULT_BACKOFF_S = 2.0


class BootstrapError(RuntimeError):
    """The distributed handshake (or backend init) failed or hung —
    an environment outage, not a join/benchmark result. Carries the
    full per-attempt trail so every driver can emit a machine-readable
    failure record instead of a bare traceback (generalizes bench.py's
    round-5 ad-hoc ``_BackendInitError``)."""

    def __init__(self, message: str, *, phase: str = "bootstrap",
                 attempts=None, deadline_s: Optional[float] = None,
                 coordinator: Optional[str] = None):
        super().__init__(message)
        self.phase = phase
        self.attempts = attempts or []
        self.deadline_s = deadline_s
        self.coordinator = coordinator

    def record(self) -> dict:
        """The JSON-shaped failure record drivers embed in their
        output (docs/FAILURE_SEMANTICS.md "Bootstrap failures")."""
        return {
            "error": "BootstrapError",
            "phase": self.phase,
            "message": str(self),
            "coordinator": self.coordinator,
            "deadline_s": self.deadline_s,
            "attempts": self.attempts,
        }


def call_with_deadline(fn: Callable, deadline_s: float,
                       what: str = "backend init"):
    """Run ``fn()`` under the shared hang watchdog
    (:mod:`..watchdog` — promoted there from this module in PR 5) and
    turn BOTH failure modes of a dead environment — an exception
    (round 4's "UNAVAILABLE") and a hang inside PJRT client init
    (observed round 5) — into a structured :class:`BootstrapError`.
    The caller decides whether a timed-out worker thread forces a hard
    exit (the watchdog detaches it from the atexit join, but it may
    still hold backend locks; see bench.py)."""
    from distributed_join_tpu.parallel.watchdog import (
        HangError,
        call_with_deadline as _guarded,
    )

    try:
        return _guarded(fn, deadline_s, what=what)
    except HangError:
        raise BootstrapError(
            f"{what} did not complete within {deadline_s:g}s "
            "(TPU relay down?)",
            phase=what, deadline_s=deadline_s,
            attempts=[{"attempt": 0, "elapsed_s": deadline_s,
                       "error": f"timeout after {deadline_s:g}s"}],
        ) from None
    except Exception as exc:
        raise BootstrapError(
            f"{what} failed: {type(exc).__name__}: {exc}",
            phase=what, deadline_s=deadline_s,
            attempts=[{"attempt": 0, "elapsed_s": None,
                       "error": f"{type(exc).__name__}: {exc}"}],
        ) from exc


def _connect(coordinator_address: str, num_processes: int,
             process_id: int) -> None:
    """The raw handshake — one ``jax.distributed.initialize`` attempt.
    Split out so :func:`initialize`'s retry loop (and tests) can
    substitute it."""
    import jax

    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except Exception:
        # jax sets its global client/service state BEFORE the TCP
        # connect; left in place, every retry would die instantly with
        # "distributed.initialize should only be called once" instead
        # of re-attempting the handshake. Best-effort teardown (the
        # half-initialized client may itself fail to shut down). Only
        # reachable on toolchains where a failed handshake raises —
        # this environment's XLA LOG(FATAL)s on a connect timeout,
        # which no in-process retry can survive.
        try:
            jax.distributed.shutdown()
        except Exception:
            pass
        raise


def initialize(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    cpu_devices_per_process: Optional[int] = None,
    *,
    deadline_s: Optional[float] = None,
    max_retries: Optional[int] = None,
    backoff_s: Optional[float] = None,
    connect: Optional[Callable] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> None:
    """Join the distributed runtime. Call BEFORE any other jax use —
    like ``MPI_Init``, this must precede every collective/device call.

    ``cpu_devices_per_process`` switches to the virtual-CPU data plane
    (gloo): multi-host semantics without TPU hardware.

    Failure semantics: the TCP/DCN handshake replaces the reference's
    ``MPI_Bcast`` of the NCCL id and fails in its ways — a coordinator
    that is not up yet (workers race it at launch), a transient
    connect refusal, or a hung endpoint. Attempts retry with
    exponential backoff (``max_retries`` attempts, first retry after
    ``backoff_s``, doubling) under an overall ``deadline_s``; the env
    knobs ``DJTPU_BOOTSTRAP_DEADLINE`` / ``DJTPU_BOOTSTRAP_RETRIES`` /
    ``DJTPU_BOOTSTRAP_BACKOFF`` configure launched processes.
    Exhaustion raises :class:`BootstrapError` carrying the full
    per-attempt trail, which drivers embed as a machine-readable
    failure record in their JSON output. ``connect``/``sleep`` are
    injectable for tests.
    """
    import jax

    from distributed_join_tpu.parallel.faults import retry_with_backoff

    deadline_s = (float(os.environ.get(ENV_BOOTSTRAP_DEADLINE,
                                       DEFAULT_DEADLINE_S))
                  if deadline_s is None else deadline_s)
    max_retries = (int(os.environ.get(ENV_BOOTSTRAP_RETRIES,
                                      DEFAULT_RETRIES))
                   if max_retries is None else max_retries)
    backoff_s = (float(os.environ.get(ENV_BOOTSTRAP_BACKOFF,
                                      DEFAULT_BACKOFF_S))
                 if backoff_s is None else backoff_s)

    # Record the identity for process_id()/is_coordinator() even when
    # this is called directly (one invocation per host) rather than via
    # the tpu-launch env.
    os.environ[ENV_NUM_PROCESSES] = str(num_processes)
    os.environ[ENV_PROCESS_ID] = str(process_id)

    if cpu_devices_per_process is not None:
        # OVERRIDE any inherited device-count flag (e.g. a test harness
        # parent sets 8): each launched process must present exactly
        # cpu_devices_per_process devices or the global mesh is wrong.
        flags = [
            f for f in os.environ.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(
            "--xla_force_host_platform_device_count="
            f"{cpu_devices_per_process}"
        )
        os.environ["XLA_FLAGS"] = " ".join(flags)
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
        # Cross-process CPU collectives need an explicit transport.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")

    do_connect = connect if connect is not None else _connect
    t0 = time.monotonic()

    def _on_retry(attempt, exc, delay):
        # Telemetry is a no-op without a session; with one, the
        # handshake's backoff trail streams into the event log as it
        # happens (docs/OBSERVABILITY.md) — the same per-attempt data
        # BootstrapError.record() carries on exhaustion.
        from distributed_join_tpu import telemetry

        telemetry.event(
            "bootstrap_retry", attempt=attempt,
            coordinator=coordinator_address, backoff_s=delay,
            error=f"{type(exc).__name__}: {exc}",
        )

    def _bounded_connect():
        # retry_with_backoff's deadline check only runs BETWEEN
        # attempts; a hung endpoint (TCP accepted, handshake never
        # completing) must be bounded too, so each attempt runs under
        # call_with_deadline's watchdog holding the REMAINING
        # deadline (its timed-out worker thread stays hung — the
        # caller decides whether to force a hard exit). INVARIANT: a
        # timed-out attempt burned that whole remainder, so the retry
        # loop's deadline check stops before launching another attempt
        # — a hung handshake is never retried (a retry would race the
        # still-blocked worker thread on jax's global state).
        remaining = max(0.0, deadline_s - (time.monotonic() - t0))
        return call_with_deadline(
            lambda: do_connect(coordinator_address, num_processes,
                               process_id),
            remaining, what="handshake",
        )

    from distributed_join_tpu import telemetry

    try:
        _, attempts = retry_with_backoff(
            _bounded_connect,
            max_attempts=max(1, max_retries),
            backoff_s=backoff_s,
            deadline_s=deadline_s,
            sleep=sleep,
            on_retry=_on_retry,
        )
        telemetry.event("bootstrap_ok",
                        coordinator=coordinator_address,
                        process_id=process_id, attempts=len(attempts))
    except BootstrapError as exc:
        # Every connect outcome — hang or error — reaches here wrapped
        # by call_with_deadline; fill in the handshake identity, the
        # CONFIGURED deadline (not the last attempt's remainder), and
        # the retry loop's full per-attempt trail.
        exc.coordinator = exc.coordinator or coordinator_address
        exc.deadline_s = deadline_s
        exc.attempts = getattr(exc, "_retry_attempts", None) or exc.attempts
        telemetry.event("bootstrap_failed", **exc.record())
        raise


def maybe_initialize_from_env() -> bool:
    """Initialize iff the ``DJTPU_*`` launch env is present; returns
    whether it did. Drivers call this first thing, so a single-process
    run (no env) is untouched and a launched run joins its slice."""
    coord = os.environ.get(ENV_COORDINATOR)
    if not coord:
        return False
    nproc = int(os.environ[ENV_NUM_PROCESSES])
    pid = int(os.environ[ENV_PROCESS_ID])
    cpu = os.environ.get(ENV_CPU_DEVICES)
    initialize(coord, nproc, pid,
               cpu_devices_per_process=int(cpu) if cpu else None)
    return True


def process_id() -> int:
    """This process's id (0 when not launched distributed) — the
    reference's ``rank`` for rank-0-only printing.

    Once a backend exists, ``jax.process_index()`` is authoritative —
    a user may have called ``jax.distributed.initialize`` themselves
    (or relied on TPU-pod auto-detection) without any ``DJTPU_*`` env,
    and every host believing it is rank 0 would duplicate reports and
    race on ``--json-output``. The env is only a pre-initialization
    fallback; probing it must not itself initialize a backend."""
    from jax._src import xla_bridge

    if getattr(xla_bridge, "_backends", None):
        import jax

        return jax.process_index()
    return int(os.environ.get(ENV_PROCESS_ID, "0"))


def is_coordinator() -> bool:
    return process_id() == 0
