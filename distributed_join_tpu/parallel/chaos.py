"""Seeded chaos-soak harness: randomized fault schedules x join
configs, every trial verified against the host pandas oracle.

``python -m distributed_join_tpu.parallel.chaos --trials 25 --seed 42``
runs 25 deterministic trials on the 8-virtual-device CPU mesh. Each
trial derives its OWN rng from ``(seed, trial_index)``, draws a join
config (padded / ragged / skew / out-of-core) and a fault schedule
(nothing, capacity squeezes, transient dispatch failures, or one of
the :data:`..faults.CORRUPTION_MODES` data corruptions), runs the join
with ``verify_integrity=True``, and grades the outcome against ground
truth computed with pandas on the host:

- ``ok`` / ``recovered`` — the result is oracle-exact (full content
  comparison via the order-invariant table digest, not just the match
  count — a flipped payload byte with an intact key count would fool
  a count oracle); ``recovered`` means the retry ladder worked for it;
- ``detected`` — the run refused to return corrupt rows: a structured
  ``IntegrityError`` / ``PlanValidationError`` / ``FaultInjectedError``
  surfaced. For a corrupting schedule this is a PASS — the acceptance
  bar is "injected corruption is detected and survived, never silently
  joined";
- ``FAILED:silent_corruption`` — a trial RETURNED rows that disagree
  with the oracle: the one unforgivable outcome;
- ``FAILED:hang`` — the trial blew its watchdog deadline
  (:mod:`..watchdog`); ``FAILED:crash`` — an unstructured error, or
  any error on a fault-free trial.

Every failure writes a minimal-repro JSON (``--repro-out``) holding
the harness seed, the trial index, the exact config + fault plan, and
the replay command — ``--trial K`` reruns exactly trial K of a seed,
bit-for-bit (generators, schedule draws, and fault addressing are all
keyed on the trial rng).

The CI smoke lane (``scripts/run_tier1.sh chaos``) runs a fixed-seed
~20-trial soak; exit code 0 = every trial survived, 1 = at least one
failure (repro files written), 2 = bad usage.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import random
import sys
import time
from typing import Optional

from distributed_join_tpu.parallel.faults import (
    CORRUPTION_MODES,
    FaultInjectedError,
    FaultInjectingCommunicator,
    FaultPlan,
    PlanValidationError,
    retry_with_backoff,
)

CONFIGS = ("padded", "ragged", "skew", "out_of_core")

# Discrete, small parameter sets: trials stay fast and programs repeat
# across trials, so the persistent XLA cache absorbs most compiles.
# rand_max stays >= 256 so key duplication can't organically overflow
# a 3x-sized output block — organic overflow on a fault-free trial
# would read as a phantom harness failure (the retry budget still
# absorbs moderate skew).
_BUILD_ROWS = (512, 1024)
_PROBE_ROWS = (1024, 2048)
_RAND_MAX = (256, 700, 5000)
_SELECTIVITY = (0.3, 0.5)


def random_fault_plan(rng: random.Random, *, corruption: bool = True,
                      dispatch_failures: bool = True) -> FaultPlan:
    """One seeded fault schedule. ``corruption=False`` restricts to
    the recoverable faults (squeezes/transients) — the knob behind the
    soak's ``--no-corruption`` control arm and the drivers'
    ``--chaos-seed`` smoke wrap."""
    kinds = ["none", "overflow"]
    if dispatch_failures:
        kinds.append("transient_dispatch")
    if corruption:
        kinds += ["corruption", "corruption"]  # corruption-heavy soak
    kind = rng.choice(kinds)
    seed = rng.randrange(1 << 16)
    if kind == "overflow":
        return FaultPlan(seed=seed,
                         overflow_programs=rng.choice((1, 2)))
    if kind == "transient_dispatch":
        return FaultPlan(seed=seed, fail_dispatches=1)
    if kind == "corruption":
        return FaultPlan(
            seed=seed,
            corrupt_mode=rng.choice(CORRUPTION_MODES),
            corrupt_collectives=rng.choice((1, 2)),
        )
    return FaultPlan(seed=seed)


def _trial_rng(seed: int, trial: int) -> random.Random:
    """Deterministic ACROSS processes: integer-mixed seeding (tuple/
    str seeds route through PYTHONHASHSEED-randomized hashing)."""
    return random.Random(seed * 1_000_003 + trial)


def fault_label(plan: FaultPlan) -> str:
    if plan.corrupt_mode is not None:
        return plan.corrupt_mode
    if plan.overflow_programs:
        return "overflow"
    if plan.fail_dispatches or plan.fail_after_dispatches is not None:
        return "transient_dispatch"
    return "none"


def wrap_communicator(comm, seed: int,
                      plan: Optional[FaultPlan] = None):
    """Driver seam (``--chaos-seed N``): wrap a communicator in a
    seeded fault schedule. Corruption modes are INCLUDED — pair with
    ``--verify-integrity`` (the drivers do) so a corrupted run is
    detected, not reported as a clean benchmark number."""
    if plan is None:
        plan = random_fault_plan(_trial_rng(seed, 0),
                                 dispatch_failures=False)
    return FaultInjectingCommunicator(comm, plan)


def _plan_record(plan: FaultPlan) -> dict:
    return {k: v for k, v in dataclasses.asdict(plan).items()
            if v not in (None, 0, 0.0)}


def _oracle_frame(build, probe):
    """Ground truth on the host: the merged pandas frame (the
    reference implementation this repo reproduces is, at trial scale,
    exactly a pandas inner join)."""
    return build.to_pandas().merge(probe.to_pandas(), on="key")


def _content_digest(columns: dict) -> int:
    from distributed_join_tpu.parallel.integrity import table_digest_np

    return table_digest_np(columns)


def _result_columns(res_table) -> dict:
    """Valid rows of a (possibly device) result table as host numpy."""
    import numpy as np

    valid = np.asarray(res_table.valid)
    return {n: np.asarray(c)[valid]
            for n, c in res_table.columns.items()}


def _frame_columns(frame, names) -> dict:
    import numpy as np

    return {n: np.asarray(frame[n].to_numpy()) for n in names}


@dataclasses.dataclass
class TrialOutcome:
    verdict: str
    error: Optional[str] = None
    expected_total: Optional[int] = None
    got_total: Optional[int] = None
    retries: int = 0

    @property
    def failed(self) -> bool:
        return self.verdict.startswith("FAILED")


def _grade_result(got_cols, got_total, oracle_cols, oracle_total,
                  corrupting: bool, retries: int) -> TrialOutcome:
    content_ok = (
        got_total == oracle_total
        and _content_digest(got_cols) == _content_digest(oracle_cols)
    )
    if content_ok:
        return TrialOutcome("recovered" if retries else "ok",
                            expected_total=oracle_total,
                            got_total=got_total, retries=retries)
    return TrialOutcome(
        "FAILED:silent_corruption" if corrupting
        else "FAILED:wrong_result",
        expected_total=oracle_total, got_total=got_total,
        retries=retries,
    )


def run_trial(harness_seed: int, trial: int, n_ranks: int = 8,
              corruption: bool = True,
              deadline_s: Optional[float] = 300.0) -> dict:
    """Run one trial; returns its JSON-shaped record. Deterministic in
    (harness_seed, trial): the config draw, the generators, and the
    fault schedule all derive from the trial rng."""
    from distributed_join_tpu.parallel.watchdog import (
        HangError,
        call_with_deadline,
    )

    rng = _trial_rng(harness_seed, trial)
    config = {
        "mode": CONFIGS[trial % len(CONFIGS)],
        "build_rows": rng.choice(_BUILD_ROWS),
        "probe_rows": rng.choice(_PROBE_ROWS),
        "rand_max": rng.choice(_RAND_MAX),
        "selectivity": rng.choice(_SELECTIVITY),
        "table_seed": rng.randrange(1 << 16),
    }
    plan = random_fault_plan(rng, corruption=corruption)
    # Corrupting schedules sometimes run with NO retry budget — the
    # IntegrityError raise path must soak too. Every other schedule
    # keeps budget to absorb injected squeezes + moderate skew.
    config["auto_retry"] = (
        rng.choice((0, 2)) if plan.corrupt_mode is not None else 3
    )
    record = {
        "trial": trial,
        "config": config,
        "fault": fault_label(plan),
        "fault_plan": _plan_record(plan),
    }
    t0 = time.perf_counter()
    try:
        if deadline_s is not None:
            out = call_with_deadline(
                lambda: _run_trial_body(config, plan, n_ranks),
                deadline_s, what=f"chaos trial {trial}",
            )
        else:
            out = _run_trial_body(config, plan, n_ranks)
    except HangError as exc:
        out = TrialOutcome("FAILED:hang", error=str(exc))
    except Exception as exc:  # noqa: BLE001 — grading seam
        # Anything the trial body did not convert to a structured
        # refusal is a crash VERDICT, not a soak abort: the trial is
        # graded FAILED, its repro JSON is written, and the remaining
        # trials still run.
        out = TrialOutcome(
            "FAILED:crash", error=f"{type(exc).__name__}: {exc}")
    record.update(dataclasses.asdict(out))
    record["verdict"] = out.verdict
    record["elapsed_s"] = round(time.perf_counter() - t0, 3)
    from distributed_join_tpu import telemetry

    telemetry.event("chaos_trial", trial=trial,
                    verdict=out.verdict, mode=config["mode"])
    return record


def _run_trial_body(config, plan: FaultPlan, n_ranks: int
                    ) -> TrialOutcome:
    import distributed_join_tpu as dj
    from distributed_join_tpu.parallel import integrity
    from distributed_join_tpu.utils.generators import (
        generate_build_probe_tables,
    )

    build, probe = generate_build_probe_tables(
        seed=config["table_seed"],
        build_nrows=config["build_rows"],
        probe_nrows=config["probe_rows"],
        rand_max=config["rand_max"],
        selectivity=config["selectivity"],
    )
    oracle = _oracle_frame(build, probe)
    oracle_total = len(oracle)
    out_names = ["key", "build_payload", "probe_payload"]
    oracle_cols = _frame_columns(oracle, out_names)
    corrupting = plan.corrupt_mode is not None

    comm = FaultInjectingCommunicator(
        dj.make_communicator("tpu", n_ranks=n_ranks), plan)
    mode = config["mode"]
    injected = fault_label(plan) != "none"

    def loud(kind: str, detail: Optional[str] = None) -> TrialOutcome:
        # A structured refusal (IntegrityError, a still-flagged
        # overflow, a surfaced injected fault) PASSES a trial whose
        # schedule injected something — corruption detected, never
        # silently joined. On a fault-free trial the same outcome is
        # a harness catch: a false alarm or an organic failure.
        return TrialOutcome(
            "detected" if injected else f"FAILED:{kind}",
            error=detail or kind, expected_total=oracle_total)

    try:
        if mode == "out_of_core":
            return _run_out_of_core(build, probe, comm, oracle_cols,
                                    oracle_total, corrupting, loud,
                                    plan)
        join_opts = dict(
            out_capacity_factor=3.0,
            shuffle_capacity_factor=3.0,
            shuffle="ragged" if mode == "ragged" else "padded",
        )
        if mode == "skew":
            join_opts["skew_threshold"] = 0.05

        def attempt():
            return dj.distributed_inner_join(
                build, probe, comm,
                auto_retry=config["auto_retry"],
                verify_integrity=True, **join_opts,
            )

        # Driver-level transient retry (the drivers' own contract):
        # injected dispatch failures are retried a couple of times
        # before counting as a loud structured failure.
        res, _ = retry_with_backoff(
            attempt, max_attempts=3, backoff_s=0.01,
            retry_on=(FaultInjectedError,),
        )
        retries = res.retry_report.n_attempts - 1
        if bool(res.overflow):
            return loud("overflow_after_ladder")
        return _grade_result(
            _result_columns(res.table), int(res.total),
            oracle_cols, oracle_total, corrupting, retries,
        )
    except integrity.IntegrityError as exc:
        return loud("false_integrity_alarm", f"IntegrityError: {exc}")
    except (PlanValidationError, FaultInjectedError) as exc:
        return loud("structured_error",
                    f"{type(exc).__name__}: {exc}")


def _run_out_of_core(build, probe, comm, oracle_cols, oracle_total,
                     corrupting: bool, loud,
                     plan: FaultPlan) -> TrialOutcome:
    import numpy as np

    from distributed_join_tpu.parallel.out_of_core import (
        keyrange_batched_join,
    )

    fetched = []

    def consumer(_b, res):
        fetched.append(_result_columns(res.table))

    total, overflow = keyrange_batched_join(
        build, probe, comm, n_batches=3, warmup=False,
        batch_retries=2, batch_retry_backoff_s=0.01,
        verify_integrity=True, on_batch_result=consumer,
        out_capacity_factor=3.0, shuffle_capacity_factor=3.0,
    )
    if overflow:
        return loud("overflow_flagged")
    got = {
        n: np.concatenate([c[n] for c in fetched])
        for n in (fetched[0] if fetched else {})
    }
    # A clean finish over an injected transient necessarily consumed a
    # batch retry — grade it "recovered" so the verdict histogram
    # reflects the retry machinery (the batch loop doesn't surface
    # attempt counts).
    retries = 1 if plan.fail_dispatches else 0
    return _grade_result(got, int(total), oracle_cols, oracle_total,
                         corrupting, retries=retries)


# -- the tuner slice (poisoned-history grading) -----------------------


def poisoned_history_entry(signature: str, *,
                           shuffle_capacity_factor: float = 0.4,
                           out_capacity_factor: float = 0.2,
                           rung: int = 1) -> dict:
    """A history line CLAIMING a workload resolved at a rung whose
    capacities are far too small — the lying-history adversary the
    tuner slice feeds the autotuner. Shaped like a real request entry
    (escalations recorded, resolved knobs at the bogus sizing) so the
    trend aggregation adopts it exactly as it would a genuine one."""
    return {
        "schema_version": 1,
        "kind": "request",
        "request_id": "poisoned",
        "op": "join",
        "signature": signature,
        "outcome": "served",
        "wall_s": 0.01,
        "new_traces": 1,
        "cache_hits": 0,
        "matches": 1,
        "retry": {"n_attempts": 2, "escalations": 1,
                  "integrity_retries": 0},
        "resolved_knobs": {
            "shuffle_capacity_factor": shuffle_capacity_factor,
            "out_capacity_factor": out_capacity_factor,
        },
        "rung": rung,
        "tuned": None,
        "error": None,
    }


def run_tuner_trial(harness_seed: int, trial: int,
                    n_ranks: int = 8,
                    deadline_s: Optional[float] = 300.0) -> dict:
    """One poisoned-history trial: seed a temp history store with
    capacities claiming a too-small rung for EXACTLY the workload
    signature the tuner will compute, run the join with the tuner
    armed, and grade:

    - the result must be pandas-oracle-exact (the retry ladder
      catches the mis-size — a self-tuned config must never trade
      correctness for speed);
    - the post-run history must record the ESCALATED rung with
      capacities strictly above the poisoned claim (the tuner
      *learns* the corrected rung for the next run).
    """
    import tempfile

    import distributed_join_tpu as dj
    from distributed_join_tpu.parallel.watchdog import (
        HangError,
        call_with_deadline,
    )
    from distributed_join_tpu.planning.tuner import (
        JoinTuner,
        workload_signature,
    )
    from distributed_join_tpu.telemetry import history as tel_history
    from distributed_join_tpu.utils.generators import (
        generate_build_probe_tables,
    )

    rng = _trial_rng(harness_seed, 1_000 + trial)
    config = {
        "mode": ("padded", "ragged", "skew")[trial % 3],
        "build_rows": rng.choice(_BUILD_ROWS),
        "probe_rows": rng.choice(_PROBE_ROWS),
        "rand_max": rng.choice(_RAND_MAX),
        "selectivity": rng.choice(_SELECTIVITY),
        "table_seed": rng.randrange(1 << 16),
    }
    # Recoverable faults only: the poisoned store is this slice's
    # adversary; corruption grading is the main soak's job.
    plan = random_fault_plan(rng, corruption=False)
    record = {
        "trial": trial,
        "config": config,
        "fault": fault_label(plan),
        "fault_plan": _plan_record(plan),
        "poisoned_history": True,
    }
    t0 = time.perf_counter()

    def body():
        build, probe = generate_build_probe_tables(
            seed=config["table_seed"],
            build_nrows=config["build_rows"],
            probe_nrows=config["probe_rows"],
            rand_max=config["rand_max"],
            selectivity=config["selectivity"],
        )
        oracle = _oracle_frame(build, probe)
        out_names = ["key", "build_payload", "probe_payload"]
        oracle_cols = _frame_columns(oracle, out_names)
        comm = FaultInjectingCommunicator(
            dj.make_communicator("tpu", n_ranks=n_ranks), plan)
        join_opts = dict(
            shuffle="ragged" if config["mode"] == "ragged"
            else "padded",
        )
        if config["mode"] == "skew":
            join_opts["skew_threshold"] = 0.05
        # The poison must land on EXACTLY the signature the tuner
        # will look up (same function, same tables, same opts).
        sig = workload_signature(comm, build, probe, key="key",
                                 with_integrity=True, **join_opts)
        poison = poisoned_history_entry(sig)
        with tempfile.TemporaryDirectory() as tmp:
            store = tel_history.WorkloadHistory(
                tmp + "/history.jsonl")
            store.append(poison)
            store.close()
            tuner = JoinTuner(store.path)

            def attempt():
                return dj.distributed_inner_join(
                    build, probe, comm, auto_retry=6,
                    verify_integrity=True, tuner=tuner,
                    **join_opts)

            res, _ = retry_with_backoff(
                attempt, max_attempts=3, backoff_s=0.01,
                retry_on=(FaultInjectedError,),
            )
            if bool(res.overflow):
                return TrialOutcome(
                    "FAILED:overflow_after_ladder",
                    expected_total=len(oracle)), None
            # Grade content against the oracle, THEN verify the
            # corrected rung landed back in the store.
            out = _grade_result(
                _result_columns(res.table), int(res.total),
                oracle_cols, len(oracle), corrupting=False,
                retries=res.retry_report.n_attempts - 1,
            )
            store2 = tel_history.WorkloadHistory(store.path)
            store2.append(tel_history.request_entry(
                request_id=f"tuner-trial-{trial}", op="join",
                signature=sig, outcome="served",
                wall_s=time.perf_counter() - t0,
                retry_record=res.retry_report.as_record(),
                tuned=getattr(res, "tuned", None)))
            store2.close()
            entries, _ = tel_history.load_history(store.path)
            trend = tel_history.trends_of(entries)[sig].as_dict()
            learned = trend["resolved_knobs_last"] or {}
            corrected = (
                (trend["resolved_rung_last"] or 0)
                > poison["rung"]
                and learned.get("out_capacity_factor", 0)
                > poison["resolved_knobs"]["out_capacity_factor"]
            )
            tuned_rec = getattr(res, "tuned", None) or {}
            presized = (tuned_rec.get("source") == "history")
            return out, {
                "tuner_presized": presized,
                "tuner_corrected": corrected,
                "learned_rung": trend["resolved_rung_last"],
                "learned_knobs": learned,
            }

    try:
        if deadline_s is not None:
            out, tuner_verdict = call_with_deadline(
                body, deadline_s, what=f"tuner trial {trial}")
        else:
            out, tuner_verdict = body()
    except HangError as exc:
        out, tuner_verdict = TrialOutcome("FAILED:hang",
                                          error=str(exc)), None
    except Exception as exc:  # noqa: BLE001 — grading seam
        out, tuner_verdict = TrialOutcome(
            "FAILED:crash", error=f"{type(exc).__name__}: {exc}"), None
    if tuner_verdict is not None:
        record.update(tuner_verdict)
        if not out.failed and not (tuner_verdict["tuner_presized"]
                                   and tuner_verdict["tuner_corrected"]):
            # Oracle-clean but the loop didn't close: either the
            # poisoned sizing never applied (the slice tested
            # nothing) or the corrected rung never landed — both are
            # harness failures, loudly.
            out = TrialOutcome(
                "FAILED:tuner_loop_open",
                error=str(tuner_verdict),
                expected_total=out.expected_total,
                got_total=out.got_total, retries=out.retries)
    record.update(dataclasses.asdict(out))
    record["verdict"] = out.verdict
    record["elapsed_s"] = round(time.perf_counter() - t0, 3)
    return record


def run_hier_trial(harness_seed: int, trial: int, n_ranks: int = 8,
                   deadline_s: Optional[float] = 300.0) -> dict:
    """One hierarchical-shuffle chaos trial: a two-level
    ``--shuffle hierarchical`` join over a faked multi-slice mesh,
    with the fault schedule injected at the communicator seams —
    including the new cross-slice (DCN) exchange
    (``FaultInjectingCommunicator.all_to_all_slice``) — graded
    against the pandas oracle with wire digests on. Deterministic in
    ``(harness_seed, trial)`` like every other trial."""
    from distributed_join_tpu.parallel.watchdog import (
        HangError,
        call_with_deadline,
    )

    rng = _trial_rng(harness_seed, 10_000 + trial)
    slices = rng.choice([s for s in (2, 4) if n_ranks % s == 0]
                        or [1])
    config = {
        "mode": "hierarchical",
        "n_slices": slices,
        "dcn_codec": rng.choice(("auto", "on", "off")),
        "build_rows": rng.choice(_BUILD_ROWS),
        "probe_rows": rng.choice(_PROBE_ROWS),
        "rand_max": rng.choice(_RAND_MAX),
        "selectivity": rng.choice(_SELECTIVITY),
        "table_seed": rng.randrange(1 << 16),
        "auto_retry": 3,
    }
    plan = random_fault_plan(rng, corruption=True)
    record = {
        "trial": trial,
        "config": config,
        "fault": fault_label(plan),
        "fault_plan": _plan_record(plan),
    }
    t0 = time.perf_counter()
    try:
        body = lambda: _run_hier_trial_body(config, plan, n_ranks)  # noqa: E731
        out = (call_with_deadline(body, deadline_s,
                                  what=f"hier chaos trial {trial}")
               if deadline_s is not None else body())
    except HangError as exc:
        out = TrialOutcome("FAILED:hang", error=str(exc))
    except Exception as exc:  # noqa: BLE001 — grading seam
        out = TrialOutcome(
            "FAILED:crash", error=f"{type(exc).__name__}: {exc}")
    record.update(dataclasses.asdict(out))
    record["verdict"] = out.verdict
    record["elapsed_s"] = round(time.perf_counter() - t0, 3)
    return record


def _run_hier_trial_body(config, plan: FaultPlan, n_ranks: int
                         ) -> TrialOutcome:
    import distributed_join_tpu as dj
    from distributed_join_tpu.parallel import integrity
    from distributed_join_tpu.parallel.communicator import (
        HierarchicalTpuCommunicator,
    )
    from distributed_join_tpu.utils.generators import (
        generate_build_probe_tables,
    )

    build, probe = generate_build_probe_tables(
        seed=config["table_seed"],
        build_nrows=config["build_rows"],
        probe_nrows=config["probe_rows"],
        rand_max=config["rand_max"],
        selectivity=config["selectivity"],
    )
    oracle = _oracle_frame(build, probe)
    oracle_total = len(oracle)
    oracle_cols = _frame_columns(
        oracle, ["key", "build_payload", "probe_payload"])
    corrupting = plan.corrupt_mode is not None
    injected = fault_label(plan) != "none"

    comm = FaultInjectingCommunicator(
        HierarchicalTpuCommunicator(n_slices=config["n_slices"],
                                    n_ranks=n_ranks), plan)

    def loud(kind: str, detail: Optional[str] = None) -> TrialOutcome:
        return TrialOutcome(
            "detected" if injected else f"FAILED:{kind}",
            error=detail or kind, expected_total=oracle_total)

    join_opts = dict(
        out_capacity_factor=3.0,
        shuffle_capacity_factor=3.0,
        shuffle="hierarchical",
        dcn_codec=config["dcn_codec"],
    )
    try:
        def attempt():
            return dj.distributed_inner_join(
                build, probe, comm,
                auto_retry=config["auto_retry"],
                verify_integrity=True, **join_opts,
            )

        res, _ = retry_with_backoff(
            attempt, max_attempts=3, backoff_s=0.01,
            retry_on=(FaultInjectedError,),
        )
        retries = res.retry_report.n_attempts - 1
        if bool(res.overflow):
            return loud("overflow_after_ladder")
        return _grade_result(
            _result_columns(res.table), int(res.total),
            oracle_cols, oracle_total, corrupting, retries,
        )
    except integrity.IntegrityError as exc:
        return loud("false_integrity_alarm", f"IntegrityError: {exc}")
    except (PlanValidationError, FaultInjectedError) as exc:
        return loud("structured_error",
                    f"{type(exc).__name__}: {exc}")


def hier_slice(seed: int, trials: int, n_ranks: int = 8,
               deadline_s: Optional[float] = 300.0,
               repro_out: Optional[str] = None) -> dict:
    """The --hier-slice soak: N hierarchical-shuffle trials; exit
    contract mirrors the main soak (0 failures = pass)."""
    records, failures = [], []
    for k in range(trials):
        rec = run_hier_trial(seed, k, n_ranks=n_ranks,
                             deadline_s=deadline_s)
        records.append(rec)
        print(f"hier trial {k:3d} [{rec['config']['n_slices']}x"
              f"{n_ranks // rec['config']['n_slices']} "
              f"codec={rec['config']['dcn_codec']:4s}] "
              f"fault={rec['fault']:17s} -> {rec['verdict']} "
              f"({rec['elapsed_s']}s)", flush=True)
        if rec["verdict"].startswith("FAILED"):
            failures.append(rec)
            if repro_out:
                path = f"{repro_out}_hier_{seed}_{k}.json"
                with open(path, "w") as f:
                    json.dump({**rec, "harness_seed": seed}, f,
                              indent=2)
                print(f"  repro written: {path}", flush=True)
    verdicts: dict = {}
    for rec in records:
        verdicts[rec["verdict"]] = verdicts.get(rec["verdict"], 0) + 1
    return {
        "harness_seed": seed,
        "slice": "hierarchical_shuffle",
        "n_ranks": n_ranks,
        "trials": len(records),
        "verdicts": verdicts,
        "failures": len(failures),
        "records": records,
    }


def tuner_slice(seed: int, trials: int, n_ranks: int = 8,
                deadline_s: Optional[float] = 300.0,
                repro_out: Optional[str] = None) -> dict:
    """The --tuner-slice soak: N poisoned-history trials; exit
    contract mirrors the main soak (0 failures = pass)."""
    records, failures = [], []
    for k in range(trials):
        rec = run_tuner_trial(seed, k, n_ranks=n_ranks,
                              deadline_s=deadline_s)
        records.append(rec)
        print(f"tuner trial {k:3d} [{rec['config']['mode']:7s}] "
              f"fault={rec['fault']:17s} -> {rec['verdict']} "
              f"(presized={rec.get('tuner_presized')}, "
              f"corrected={rec.get('tuner_corrected')}, "
              f"{rec['elapsed_s']}s)", flush=True)
        if rec["verdict"].startswith("FAILED"):
            failures.append(rec)
            if repro_out:
                path = f"{repro_out}_tuner_{seed}_{k}.json"
                with open(path, "w") as f:
                    json.dump({**rec, "harness_seed": seed}, f,
                              indent=2)
                print(f"  repro written: {path}", flush=True)
    verdicts: dict = {}
    for rec in records:
        verdicts[rec["verdict"]] = verdicts.get(rec["verdict"], 0) + 1
    return {
        "harness_seed": seed,
        "slice": "tuner_poisoned_history",
        "n_ranks": n_ranks,
        "trials": len(records),
        "verdicts": verdicts,
        "failures": len(failures),
        "records": records,
    }


# -- the fleet slice (kill/hang/corrupt one replica mid-soak) ---------


def _fleet_trial_spec(seed: int, trial: int) -> dict:
    """One trial's wire join spec, deterministic in (seed, trial).
    Small discrete shape sets so the fleet shares few compiled
    programs (the shared persist dir absorbs repeats); rand_max stays
    >= 256 for the usual organic-overflow reason."""
    trng = _trial_rng(seed, 555_100 + trial)
    return {
        "op": "join",
        "build_nrows": trng.choice((512, 1024)),
        "probe_nrows": 1024,
        "rand_max": 512,
        "selectivity": trng.choice((0.3, 0.5)),
        "seed": trng.randrange(1 << 16),
        "out_capacity_factor": 3.0,
    }


def fleet_slice(seed: int, trials: int, *, replica_ranks: int = 2,
                fault: Optional[str] = None,
                repro_out: Optional[str] = None) -> dict:
    """The ``--fleet`` soak (docs/FLEET.md): a 2-replica subprocess
    fleet behind the signature-affinity router, N seeded join trials
    through the router's TCP wire, ONE replica faulted mid-soak —
    ``kill`` (SIGKILL at the midpoint trial), ``hang``
    (``FaultPlan.dispatch_delay_s`` armed after a few dispatches via
    ``--fault-plan``, turning into a replica-side HangError + poison),
    or ``corrupt`` (a corruption-mode plan + ``--verify-integrity
    --auto-retry 0``, so the integrity rung refuses loudly through
    the router; the victim is excluded from the shared persist dir so
    its corrupted trace can never enter the distribution tier).

    Gates (the ISSUE 15 acceptance bar):

    - every non-refused answer grades pandas-oracle-clean (a wrong
      match count through the router is ``FAILED:wrong_result`` —
      the one unforgivable outcome);
    - a refusal is only a PASS when it is structured AND the schedule
      injected something (kill/hang failovers absorb transparently;
      the corrupt victim's IntegrityError passes through);
    - for kill/hang: the faulted replica is DRAINED within one probe
      interval (+ scheduling slack) of the fault surfacing and
      REPLACED (healthy at a higher generation), and the
      post-replacement repeat of a PRE-FAULT workload signature
      dispatches with ZERO new traces (the shared persist dir is the
      distribution tier);
    - no request is lost: served + structured refusals == trials
      (the router answers everything; the failover budget bounds the
      retries behind each answer).
    """
    import tempfile

    from distributed_join_tpu.service import fleet as fleet_mod
    from distributed_join_tpu.service.server import (
        ServiceClient,
        _tables_from_spec,
    )

    rng = _trial_rng(seed, 555_000)
    fault = fault or rng.choice(("kill", "hang", "corrupt"))
    # The victim is the replica AFFINE to the workload that will face
    # the fault (the router's routing is deterministic given the
    # spec) — an rng-drawn index could land on a replica the whole
    # soak never routes to. hang/corrupt arm a FaultPlan at spawn, so
    # trial 0's replica faces it; the kill lands right before the
    # MIDPOINT trial's dispatch, so THAT trial's replica is the
    # victim — the router's first post-kill attempt is then
    # guaranteed to hit the dead backend, producing the failed-
    # attempt/failover-retry pair the trace-continuity gate walks.
    trial0 = _fleet_trial_spec(seed, 0)
    victim = fleet_mod.affine_replica(
        _fleet_trial_spec(seed, trials // 2) if fault == "kill"
        else trial0,
        replica_ranks, 2)
    workdir = tempfile.mkdtemp(prefix="djtpu_fleet_soak_")
    cfg = fleet_mod.FleetConfig(
        n_replicas=2,
        replica_ranks=replica_ranks,
        persist_dir=os.path.join(workdir, "programs"),
        history_dir=os.path.join(workdir, "history"),
        probe_interval_s=0.5,
        suspect_strikes=2,
        retry_budget=2,
        request_deadline_s=120.0,
    )
    # Per-INDEX flight-recorder paths in the soak's workdir: a shared
    # path would let the sibling's stop-time dump clobber the
    # victim's postmortem (respawned generations default to the
    # persist dir — still inside the workdir, never the cwd).
    overrides: dict = {
        i: {"extra_args": ["--flight-recorder-path",
                           os.path.join(workdir,
                                        f"replica{i}_fr.json")]}
        for i in (0, 1)
    }
    if fault == "hang":
        overrides[victim]["fault_plan"] = {
            "seed": seed % (1 << 16),
            "dispatch_delay_s": 30.0,
            "delay_after_dispatches": 3}
        # The per-request watchdog must cover the victim's cold
        # compiles (its first dispatches are delay-free) but trip
        # well inside the 30s injected stall.
        overrides[victim]["extra_args"] += ["--guard-deadline-s",
                                            "10.0"]
    elif fault == "corrupt":
        overrides[victim]["fault_plan"] = {
            "seed": seed % (1 << 16),
            "corrupt_mode": rng.choice(CORRUPTION_MODES),
            "corrupt_collectives": 1}
        overrides[victim]["extra_args"] += ["--verify-integrity",
                                            "--auto-retry", "0"]
        overrides[victim]["persist"] = False
    router = fleet_mod.FleetRouter(
        fleet_mod.process_fleet_factory(
            cfg, platform="cpu", replica_overrides=overrides), cfg)
    router.start()
    server, port = fleet_mod.start_router_daemon(router)
    client = ServiceClient("127.0.0.1", port)
    kill_at = trials // 2

    def refusal_injected(k: int, err: str) -> bool:
        """Whether a structured refusal is attributable to THE armed
        fault — anything else (a spurious shed, a bogus overflow
        refusal from the healthy sibling) must grade FAILED even on a
        faulted soak, or the acceptance gate would mask regressions.
        corrupt injects exactly an IntegrityError; hang surfaces as
        HangError/poisoned (raw, or folded into the router's
        failover-exhausted FleetError message); a post-kill refusal
        must chain from the dead replica's connection (a spurious
        shed from the healthy sibling is a failure even then)."""
        if fault == "kill":
            return k >= kill_at and ("connection" in err
                                     or "FleetError" in err)
        if fault == "corrupt":
            return "IntegrityError" in err
        return any(tag in err for tag in ("Hang", "hang", "poisoned"))

    records, failures = [], []
    pre_fault_spec = None
    fault_seen_at: Optional[float] = None
    kill_send_at: Optional[float] = None

    def grade(resp, expected, k: int) -> TrialOutcome:
        if resp.get("ok"):
            if resp.get("overflow"):
                return TrialOutcome("FAILED:overflow",
                                    expected_total=expected)
            got = resp.get("matches")
            failovers = (resp.get("fleet") or {}).get("failovers", 0)
            if got == expected:
                return TrialOutcome(
                    "recovered" if failovers else "ok",
                    expected_total=expected, got_total=got,
                    retries=failovers)
            return TrialOutcome(
                "FAILED:wrong_result", expected_total=expected,
                got_total=got, retries=failovers)
        err = f"{resp.get('error')}: {resp.get('message')}"
        return TrialOutcome(
            "detected" if refusal_injected(k, err)
            else "FAILED:refused",
            error=err, expected_total=expected)

    try:
        for k in range(trials):
            spec = _fleet_trial_spec(seed, k)
            if pre_fault_spec is None:
                pre_fault_spec = dict(spec)
            build, probe = _tables_from_spec(spec)
            expected = len(_oracle_frame(build, probe))
            if fault == "kill" and k == kill_at:
                # SIGKILL right before THIS dispatch (the oracle is
                # already computed): the victim is this trial's
                # affine replica, so the router's next attempt dials
                # the dead backend unless the 0.5s prober wins the
                # microsecond race (detected below via drained_at
                # and excused by the trace-continuity gate).
                router.replicas[victim].backend.kill()
                fault_seen_at = time.monotonic()
            t_send = time.monotonic()
            if fault == "kill" and k == kill_at:
                kill_send_at = t_send
            t0 = time.perf_counter()
            try:
                resp = client.send(spec)
            except (OSError, ValueError) as exc:
                # The ROUTER must never die under a replica fault.
                resp = {"ok": False, "error": "RouterLost",
                        "message": f"{type(exc).__name__}: {exc}"}
            t_resp = time.monotonic()
            out = grade(resp, expected, k)
            rep = router.replicas[victim]
            if fault in ("hang", "corrupt") \
                    and fault_seen_at is None \
                    and (rep.drained_at or 0) >= t_send:
                # The armed fault surfaced during THIS trial: it
                # became OBSERVABLE when the replica's HangError
                # answer (its own watchdog deadline — the in-flight
                # request 'deadlines out' by design) reached the
                # router, which is no later than our response. The
                # drain-latency gate measures from there.
                fault_seen_at = min(t_resp, rep.drained_at)
            rec = {"trial": k, "spec": spec, "fault": fault,
                   **dataclasses.asdict(out),
                   "verdict": out.verdict,
                   "elapsed_s": round(time.perf_counter() - t0, 3)}
            records.append(rec)
            print(f"fleet trial {k:3d} fault={fault:7s} -> "
                  f"{rec['verdict']} ({rec['elapsed_s']}s)",
                  flush=True)
            if out.failed:
                failures.append(rec)
                if repro_out:
                    path = f"{repro_out}_fleet_{seed}_{k}.json"
                    with open(path, "w") as f:
                        json.dump({**rec, "harness_seed": seed,
                                   "replay": "python -m distributed_"
                                   "join_tpu.parallel.chaos --fleet "
                                   f"{trials} --seed {seed}"},
                                  f, indent=2)
                    print(f"  repro written: {path}", flush=True)

        drain_replace = {"required": fault in ("kill", "hang")}
        post_replacement_new_traces = None
        trace_continuity = {"required": False}
        if fault in ("kill", "hang"):
            rep = router.replicas[victim]
            replaced = router.wait_replaced(
                victim, timeout_s=cfg.spawn_timeout_s)
            drained_after_s = (
                (rep.drained_at - fault_seen_at)
                if rep.drained_at is not None
                and fault_seen_at is not None else None)
            within = (drained_after_s is not None
                      and drained_after_s
                      <= 3 * cfg.probe_interval_s + 5.0)
            drain_replace.update(
                drained=rep.drained_at is not None,
                drained_after_s=(round(drained_after_s, 3)
                                 if drained_after_s is not None
                                 else None),
                drained_within_probe_interval=within,
                replaced=replaced,
                generation=rep.generation)
            if not (replaced and within):
                failures.append({"gate": "drain_replace",
                                 **drain_replace})
            # Zero-trace warm repeat of a PRE-FAULT signature on the
            # replacement (the shared persist dir at work) — only
            # when a replacement is actually up; dialing the dead
            # backend's old port would crash the harness instead of
            # recording the gate failure above.
            if replaced:
                try:
                    direct = ServiceClient(*rep.addr(),
                                           timeout_s=120.0)
                    try:
                        replay = direct.send(dict(pre_fault_spec))
                    finally:
                        direct.close()
                except (OSError, ValueError) as exc:
                    replay = {"ok": False, "error": "RouterLost",
                              "message":
                                  f"{type(exc).__name__}: {exc}"}
                post_replacement_new_traces = replay.get(
                    "new_traces")
                if not replay.get("ok") \
                        or replay.get("new_traces") != 0:
                    failures.append({
                        "gate": "post_replacement_warm",
                        "response": {kk: replay.get(kk) for kk in
                                     ("ok", "error", "message",
                                      "new_traces", "matches")}})
        # Distributed-tracing continuity through the kill
        # (docs/OBSERVABILITY.md "Distributed tracing"): every
        # failed join-dispatch attempt the flight ring recorded must
        # share its trace_id with the SAME request's final served
        # record — the victim hop and the winning failover retry are
        # ONE causal trace, never two. The scripted SIGKILL
        # guarantees at least one mid-soak failover, so an empty
        # attempt ring here means trace stamping broke, not a quiet
        # soak.
        if fault == "kill":
            ring = router.recorder.snapshot()["records"]
            failed_attempts = [
                r for r in ring
                if r.get("outcome") == "attempt_failed"
                and r.get("op") == "join"
                and (r.get("trace") or {}).get("trace_id")]
            served_by_rid = {
                r.get("request_id"): r for r in ring
                if r.get("outcome") == "served"}
            broken = []
            for r in failed_attempts:
                final = served_by_rid.get(r.get("request_id"))
                f_tid = (r.get("trace") or {}).get("trace_id")
                s_tid = ((final or {}).get("trace")
                         or {}).get("trace_id")
                if final is None or f_tid != s_tid:
                    broken.append({"request_id": r.get("request_id"),
                                   "attempt_trace": f_tid,
                                   "served_trace": s_tid})
            # The prober can (rarely) drain the freshly killed victim
            # in the microseconds between the SIGKILL and the
            # midpoint dispatch — then the router routes straight to
            # the sibling and no attempt ever fails. Observable as
            # drained_at preceding the dispatch; excused, because
            # there was no failover whose continuity COULD be graded.
            rep = router.replicas[victim]
            prober_won = bool(
                not failed_attempts
                and rep.drained_at is not None
                and kill_send_at is not None
                and rep.drained_at <= kill_send_at)
            trace_continuity = {
                "required": True,
                "failed_attempts": len(failed_attempts),
                "broken": broken,
                "prober_won_race": prober_won,
            }
            if broken or (not failed_attempts and not prober_won):
                failures.append({"gate": "trace_continuity",
                                 **trace_continuity})
    finally:
        client.close()
        server.shutdown()
        server.server_close()
        router.stop()

    verdicts: dict = {}
    for rec in records:
        verdicts[rec["verdict"]] = verdicts.get(rec["verdict"], 0) + 1
    answered = sum(1 for r in records
                   if not r["verdict"].startswith("FAILED"))
    if failures:
        # Keep the workdir: the per-replica flight dumps and the
        # shared program dir ARE the postmortem.
        print(f"fleet soak artifacts kept at {workdir}", flush=True)
    else:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    return {
        "kind": "fleet_soak",
        "schema_version": 1,
        "harness_seed": seed,
        "slice": "fleet",
        "fault": fault,
        "victim": victim,
        "replica_ranks": replica_ranks,
        "trials": len(records),
        "verdicts": verdicts,
        "answered": answered,
        "failures": len(failures),
        "failure_records": failures,
        "drain_replace": drain_replace,
        "post_replacement_new_traces": post_replacement_new_traces,
        "trace_continuity": trace_continuity,
        "fleet_stats": router.stats(),
        "records": records,
    }


# -- the multi-tenant fleet slice -------------------------------------


def fleet_tenant_slice(seed: int, trials: int, *,
                       replica_ranks: int = 2,
                       repro_out: Optional[str] = None) -> dict:
    """The ``--tenants`` soak (docs/FLEET.md "Multi-tenancy &
    autoscaling"): a 2-replica subprocess fleet with two configured
    tenants — ``noisy`` (QPS-quota'd, priority 1) FLOODS at ~5x its
    quota while ``quiet`` (no quota, priority 2) runs oracle-graded
    joins; one replica is SIGKILLed at the midpoint trial.

    Gates (the ISSUE 20 acceptance bar):

    - the quiet tenant's every answer grades pandas-oracle-EXACT and
      its shed count is ZERO (router-side and response-side): the
      noisy tenant's flood is shed, never the quiet tenant;
    - the noisy tenant IS shed — structured ``QuotaExceededError`` /
      ``ShedError`` refusals naming the bound, with the router's
      per-tenant shed counters agreeing;
    - tenant isolation in the tuner namespace: every router history
      entry carries its sender's tenant stamp and the trend table
      keys stay ``tenant/signature``-namespaced — the noisy flood
      never moves the quiet tenant's knobs;
    - the killed replica is drained and REPLACED, and the
      replacement serves a pre-kill quiet signature with ZERO new
      traces (the shared persist dir is the distribution tier — the
      same warm contract the autoscaler's rotation gate enforces);
      the router's ``fleet_autoscale`` record stays well-formed with
      the control loop live the whole soak.
    """
    import tempfile

    from distributed_join_tpu.service import fleet as fleet_mod
    from distributed_join_tpu.service.server import (
        ServiceClient,
        _tables_from_spec,
    )
    from distributed_join_tpu.telemetry import history as tel_history

    noisy_qps = 2.0
    flood_per_trial = 10  # ~5x the one-second bucket capacity
    workdir = tempfile.mkdtemp(prefix="djtpu_tenant_soak_")
    cfg = fleet_mod.FleetConfig(
        n_replicas=2,
        replica_ranks=replica_ranks,
        persist_dir=os.path.join(workdir, "programs"),
        history_dir=os.path.join(workdir, "history"),
        probe_interval_s=0.5,
        suspect_strikes=2,
        retry_budget=2,
        request_deadline_s=120.0,
        tenants={
            "noisy": {"qps": noisy_qps, "burst_s": 1.0,
                      "priority": 1},
            "quiet": {"priority": 2},
        },
        # The control loop runs the whole soak (its record must stay
        # well-formed under fault); the up bound is out of reach so
        # the scripted kill's respawn is the one lifecycle event.
        autoscale=True,
        autoscale_up_qps=1e9,
        autoscale_interval_s=0.5,
    )
    overrides: dict = {
        i: {"extra_args": ["--flight-recorder-path",
                           os.path.join(workdir,
                                        f"replica{i}_fr.json")]}
        for i in (0, 1)
    }
    kill_at = trials // 2
    victim = fleet_mod.affine_replica(
        _fleet_trial_spec(seed, kill_at), replica_ranks, 2)
    router = fleet_mod.FleetRouter(
        fleet_mod.process_fleet_factory(
            cfg, platform="cpu", replica_overrides=overrides), cfg)
    router.start()
    server, port = fleet_mod.start_router_daemon(router)
    client = ServiceClient("127.0.0.1", port)

    records, failures = [], []
    noisy_counts = {"sent": 0, "ok": 0, "quota_shed": 0,
                    "priority_shed": 0, "excused": 0, "other": 0}
    quiet_shed_responses = 0
    pre_kill_spec = None
    killed = False

    def send(spec):
        try:
            return client.send(spec)
        except (OSError, ValueError) as exc:
            return {"ok": False, "error": "RouterLost",
                    "message": f"{type(exc).__name__}: {exc}"}

    try:
        for k in range(trials):
            spec = _fleet_trial_spec(seed, k)
            build, probe = _tables_from_spec(spec)
            expected = len(_oracle_frame(build, probe))
            if k == kill_at:
                router.replicas[victim].backend.kill()
                killed = True
            # The noisy flood rides FIRST each round: back-to-back
            # sends far over the bucket — the quiet trial right
            # after must be untouched by it.
            for j in range(flood_per_trial):
                nresp = send({**_fleet_trial_spec(seed, k),
                              "tenant": "noisy",
                              "request_id":
                                  f"noisy-{seed}-{k}-{j}"})
                noisy_counts["sent"] += 1
                if nresp.get("ok"):
                    noisy_counts["ok"] += 1
                elif nresp.get("error") == "QuotaExceededError":
                    noisy_counts["quota_shed"] += 1
                elif nresp.get("error") == "ShedError":
                    noisy_counts["priority_shed"] += 1
                elif killed and nresp.get("error") in (
                        "FleetError", "AdmissionError"):
                    # An ADMITTED noisy request can land on the dead
                    # backend before the prober drains it — that is
                    # the scripted kill, not a quota bug.
                    noisy_counts["excused"] += 1
                else:
                    noisy_counts["other"] += 1
                    failures.append({"gate": "noisy_outcome",
                                     "trial": k, "flood": j,
                                     "error": nresp.get("error"),
                                     "message":
                                         nresp.get("message")})
            t0 = time.perf_counter()
            resp = send({**spec, "tenant": "quiet",
                         "request_id": f"quiet-{seed}-{k}"})
            if resp.get("shed"):
                quiet_shed_responses += 1
            got = resp.get("matches")
            failovers = (resp.get("fleet") or {}).get("failovers",
                                                      0)
            if resp.get("ok") and got == expected:
                verdict = "recovered" if failovers else "ok"
            elif resp.get("ok"):
                verdict = "FAILED:wrong_result"
            else:
                verdict = "FAILED:refused"
            rec = {"trial": k, "spec": spec, "verdict": verdict,
                   "expected_total": expected, "got_total": got,
                   "retries": failovers,
                   "error": (None if resp.get("ok") else
                             f"{resp.get('error')}: "
                             f"{resp.get('message')}"),
                   "elapsed_s": round(time.perf_counter() - t0, 3)}
            records.append(rec)
            print(f"tenant trial {k:3d} -> {verdict} "
                  f"({rec['elapsed_s']}s)", flush=True)
            if verdict.startswith("FAILED"):
                failures.append(rec)
                if repro_out:
                    path = f"{repro_out}_tenant_{seed}_{k}.json"
                    with open(path, "w") as f:
                        json.dump({**rec, "harness_seed": seed,
                                   "replay": "python -m distributed"
                                   "_join_tpu.parallel.chaos "
                                   f"--tenants {trials} --seed "
                                   f"{seed}"}, f, indent=2)
                    print(f"  repro written: {path}", flush=True)
            if not verdict.startswith("FAILED") \
                    and k < kill_at:
                pre_kill_spec = dict(spec)

        st = router.stats()
        tenants_st = st.get("tenants") or {}
        quiet_st = tenants_st.get("quiet") or {}
        noisy_st = tenants_st.get("noisy") or {}
        # Gate: the quiet tenant was NEVER shed — zero shed answers
        # on the wire AND a zero router-side shed counter.
        if quiet_shed_responses \
                or (quiet_st.get("shed") or 0) != 0:
            failures.append({
                "gate": "quiet_never_shed",
                "shed_responses": quiet_shed_responses,
                "router_shed": quiet_st.get("shed")})
        # Gate: the noisy tenant WAS shed, with the router's counter
        # agreeing that sheds happened.
        if noisy_counts["quota_shed"] == 0 \
                or (noisy_st.get("shed") or 0) == 0:
            failures.append({
                "gate": "noisy_shed",
                "counts": dict(noisy_counts),
                "router_shed": noisy_st.get("shed")})
        # Gate: tenant isolation in the tuner namespace — every
        # history entry stamped with its sender's tenant, every
        # trend key tenant/signature-namespaced.
        entries, _ = tel_history.load_history(
            cfg.history_dir)
        request_entries = [e for e in entries
                           if e.get("kind") == "request"]
        unstamped = [e for e in request_entries
                     if e.get("tenant") not in ("noisy", "quiet")]
        trend_keys = list(tel_history.trends_of(request_entries))
        bare = [key for key in trend_keys if "/" not in key]
        if unstamped or bare:
            failures.append({
                "gate": "tenant_namespace",
                "unstamped_entries": len(unstamped),
                "bare_trend_keys": bare})
        # Gate: drain + replace + the warm contract on the
        # replacement (the autoscaler's own rotation gate).
        rep = router.replicas[victim]
        replaced = router.wait_replaced(
            victim, timeout_s=cfg.spawn_timeout_s)
        drain_replace = {"required": True,
                         "drained": rep.drained_at is not None,
                         "replaced": replaced,
                         "generation": rep.generation}
        post_replacement_new_traces = None
        if not replaced:
            failures.append({"gate": "drain_replace",
                             **drain_replace})
        elif pre_kill_spec is not None:
            try:
                direct = ServiceClient(*rep.addr(),
                                       timeout_s=120.0)
                try:
                    replay = direct.send(
                        {**pre_kill_spec, "tenant": "quiet"})
                finally:
                    direct.close()
            except (OSError, ValueError) as exc:
                replay = {"ok": False, "error": "RouterLost",
                          "message":
                              f"{type(exc).__name__}: {exc}"}
            post_replacement_new_traces = replay.get("new_traces")
            if not replay.get("ok") \
                    or replay.get("new_traces") != 0:
                failures.append({
                    "gate": "post_replacement_warm",
                    "response": {kk: replay.get(kk) for kk in
                                 ("ok", "error", "message",
                                  "new_traces", "matches")}})
        autoscale = router.autoscale_record()
        from distributed_join_tpu.telemetry.analyze import (
            check_file,
        )

        as_path = os.path.join(workdir, "fleet_autoscale.json")
        with open(as_path, "w") as f:
            json.dump(autoscale, f, indent=2)
        as_problems = check_file(as_path)
        if as_problems:
            failures.append({"gate": "autoscale_record",
                             "problems": as_problems})
    finally:
        client.close()
        server.shutdown()
        server.server_close()
        router.stop()

    verdicts: dict = {}
    for rec in records:
        verdicts[rec["verdict"]] = verdicts.get(rec["verdict"],
                                                0) + 1
    if failures:
        print(f"tenant soak artifacts kept at {workdir}",
              flush=True)
    else:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    return {
        "kind": "fleet_tenant_soak",
        "schema_version": 1,
        "harness_seed": seed,
        "slice": "tenants",
        "victim": victim,
        "replica_ranks": replica_ranks,
        "trials": len(records),
        "verdicts": verdicts,
        "noisy": {"quota_qps": noisy_qps,
                  "flood_per_trial": flood_per_trial,
                  **noisy_counts,
                  "router_shed": noisy_st.get("shed"),
                  "quota_sheds": noisy_st.get("quota_sheds"),
                  "priority_sheds": noisy_st.get("priority_sheds")},
        "quiet": {"trials": len(records),
                  "shed_responses": quiet_shed_responses,
                  "router_shed": quiet_st.get("shed") or 0},
        "failures": len(failures),
        "failure_records": failures,
        "drain_replace": drain_replace,
        "post_replacement_new_traces": post_replacement_new_traces,
        "autoscale": {"enabled": autoscale.get("enabled"),
                      "spawns_total":
                          autoscale.get("spawns_total"),
                      "drains_total":
                          autoscale.get("drains_total")},
        "fleet_stats": st,
        "records": records,
    }


# -- the resident-kill fleet slice ------------------------------------


def _resident_trial_spec(seed: int, trial: int, table: str) -> dict:
    """One probe-only trial spec, deterministic in (seed, trial).
    The table name pins the signature ring slot, so every trial
    ring-starts at the table's primary holder — the victim faces
    ALL the traffic."""
    trng = _trial_rng(seed, 555_200 + trial)
    return {
        "op": "join",
        "table": table,
        "probe_nrows": trng.choice((512, 1024)),
        "rand_max": 4096,
        "selectivity": trng.choice((0.3, 0.5)),
        "seed": trng.randrange(1 << 16),
        "out_capacity_factor": 3.0,
    }


def fleet_resident_slice(seed: int, trials: int, *,
                         replica_ranks: int = 2,
                         repro_out: Optional[str] = None) -> dict:
    """The ``--fleet-fault resident-kill`` soak (docs/FLEET.md
    "Replication & HA"): a K=2 fleet holds ONE resident table
    (register + one append, so generation fencing is live), N
    probe-only trials ring-start at the table's PRIMARY holder, and
    that holder is SIGKILLed at the midpoint.

    Gates:

    - **zero wrong rows from any holder** — every non-refused answer
      must equal the pandas oracle over register + delta (stale or
      rebuilt images either serve the full image or refuse; a wrong
      match count is the unforgivable ``FAILED:wrong_result``);
    - **failover within the retry budget** — post-kill probe-only
      trials are answered by the surviving holder within
      ``retry_budget + 1`` attempts (a refusal only passes when
      attributable to the kill);
    - **rebuild** — the replacement walks ``rebuilding -> serving``
      at the directory generation by replaying the durable manifest,
      and a generation-FENCED replay of a pre-fault probe-only
      signature on it answers oracle-exact with ZERO new traces (the
      shared persist dir hands the rebuilt holder its warm program).
    """
    import tempfile

    import pandas as pd

    from distributed_join_tpu.service import fleet as fleet_mod
    from distributed_join_tpu.service.server import (
        ServiceClient,
        _build_from_spec,
        _probe_from_spec,
    )

    table = "soak_residents"
    reg = {"op": "register", "name": table, "rows": 2048,
           "seed": seed % 9973 + 11, "rand_max": 4096,
           "unique_keys": True}
    delta = {"op": "append", "name": table, "rows": 256,
             "seed": seed % 9973 + 13, "rand_max": 4096}
    victim = fleet_mod.affine_replica({"op": "join", "table": table},
                                      replica_ranks, 2)
    workdir = tempfile.mkdtemp(prefix="djtpu_fleet_resident_soak_")
    cfg = fleet_mod.FleetConfig(
        n_replicas=2,
        replica_ranks=replica_ranks,
        persist_dir=os.path.join(workdir, "programs"),
        history_dir=os.path.join(workdir, "history"),
        coord_dir=os.path.join(workdir, "coord"),
        table_replication=2,
        probe_interval_s=0.5,
        suspect_strikes=2,
        retry_budget=2,
        request_deadline_s=120.0,
    )
    overrides: dict = {
        i: {"extra_args": ["--flight-recorder-path",
                           os.path.join(workdir,
                                        f"replica{i}_fr.json")]}
        for i in (0, 1)
    }
    router = fleet_mod.FleetRouter(
        fleet_mod.process_fleet_factory(
            cfg, platform="cpu", replica_overrides=overrides), cfg)
    router.start()
    server, port = fleet_mod.start_router_daemon(router)
    client = ServiceClient("127.0.0.1", port)
    kill_at = trials // 2

    # The resident oracle: register + delta, concatenated once.
    base = _build_from_spec(reg)
    build_df = pd.concat(
        [base.to_pandas(), _build_from_spec(delta).to_pandas()],
        ignore_index=True)

    class _Stub:
        wire_spec = {k: reg[k] for k in
                     ("rows", "seed", "rand_max", "unique_keys")}
        wire_build_keys = base.columns["key"]

    def expected_matches(spec: dict) -> int:
        probe = _probe_from_spec(spec, _Stub)
        return len(build_df.merge(probe.to_pandas(), on="key"))

    def refusal_injected(k: int, err: str) -> bool:
        return k >= kill_at and ("connection" in err
                                 or "FleetError" in err
                                 or "StaleGeneration" in err)

    records, failures = [], []
    pre_fault_spec = None
    generation = None
    try:
        r = client.send(reg)
        if not r.get("ok"):
            raise RuntimeError(f"soak register failed: {r}")
        a = client.send(delta)
        if not a.get("ok"):
            raise RuntimeError(f"soak append failed: {a}")
        generation = int(a.get("generation", 0))

        for k in range(trials):
            spec = _resident_trial_spec(seed, k, table)
            if pre_fault_spec is None:
                pre_fault_spec = dict(spec)
            if k == kill_at:
                router.replicas[victim].backend.kill()
            expected = expected_matches(spec)
            t0 = time.perf_counter()
            try:
                resp = client.send(spec)
            except (OSError, ValueError) as exc:
                resp = {"ok": False, "error": "RouterLost",
                        "message": f"{type(exc).__name__}: {exc}"}
            if resp.get("ok"):
                got = resp.get("matches")
                fl = resp.get("fleet") or {}
                if resp.get("overflow"):
                    out = TrialOutcome("FAILED:overflow",
                                       expected_total=expected)
                elif got != expected:
                    # The unforgivable outcome: a holder served rows
                    # that exclude the delta (or worse).
                    out = TrialOutcome("FAILED:wrong_result",
                                       expected_total=expected,
                                       got_total=got,
                                       retries=fl.get("failovers",
                                                      0))
                elif fl.get("attempts", 1) > cfg.retry_budget + 1:
                    out = TrialOutcome(
                        "FAILED:budget",
                        expected_total=expected, got_total=got,
                        retries=fl.get("failovers", 0))
                else:
                    out = TrialOutcome(
                        "recovered" if fl.get("failovers") else "ok",
                        expected_total=expected, got_total=got,
                        retries=fl.get("failovers", 0))
            else:
                err = (f"{resp.get('error')}: "
                       f"{resp.get('message')}")
                out = TrialOutcome(
                    "detected" if refusal_injected(k, err)
                    else "FAILED:refused",
                    error=err, expected_total=expected)
            rec = {"trial": k, "spec": spec,
                   "fault": "resident-kill",
                   **dataclasses.asdict(out),
                   "verdict": out.verdict,
                   "elapsed_s": round(time.perf_counter() - t0, 3)}
            records.append(rec)
            print(f"resident trial {k:3d} -> {rec['verdict']} "
                  f"({rec['elapsed_s']}s)", flush=True)
            if out.failed:
                failures.append(rec)
                if repro_out:
                    path = f"{repro_out}_resident_{seed}_{k}.json"
                    with open(path, "w") as f:
                        json.dump({**rec, "harness_seed": seed,
                                   "replay": "python -m distributed_"
                                   "join_tpu.parallel.chaos --fleet "
                                   f"{trials} --fleet-fault "
                                   "resident-kill "
                                   f"--seed {seed}"},
                                  f, indent=2)
                    print(f"  repro written: {path}", flush=True)

        # -- the rebuild gate -----------------------------------------
        drain_replace = {"required": True}
        rebuild: dict = {"required": True}
        replaced = router.wait_replaced(victim,
                                       timeout_s=cfg.spawn_timeout_s)
        drain_replace.update(
            replaced=replaced,
            generation=router.replicas[victim].generation)
        if not replaced:
            failures.append({"gate": "drain_replace",
                             **drain_replace})
        holder = None
        deadline = time.monotonic() + cfg.spawn_timeout_s
        while time.monotonic() < deadline:
            holder = (router.stats()["tables"]
                      .get(table, {}).get("holders", {})
                      .get(str(victim)))
            if holder and holder["state"] == "serving":
                break
            time.sleep(0.2)
        rebuild.update(holder=holder,
                       rebuilds_total=router.stats()
                       ["rebuilds_total"])
        if not (holder and holder["state"] == "serving"
                and holder["generation"] == generation):
            failures.append({"gate": "rebuild_serving", **rebuild})
        elif replaced:
            # The FENCED replay of a pre-fault signature on the
            # rebuilt image: oracle-exact, correct generation, zero
            # new traces.
            try:
                direct = ServiceClient(
                    *router.replicas[victim].addr(),
                    timeout_s=120.0)
                try:
                    replay = direct.send(
                        {**pre_fault_spec,
                         "min_generation": generation})
                finally:
                    direct.close()
            except (OSError, ValueError) as exc:
                replay = {"ok": False, "error": "RouterLost",
                          "message": f"{type(exc).__name__}: {exc}"}
            rebuild["replay"] = {kk: replay.get(kk) for kk in
                                 ("ok", "error", "message",
                                  "new_traces", "matches")}
            rebuild["replay"]["generation"] = \
                (replay.get("resident") or {}).get("generation")
            if (not replay.get("ok")
                    or replay.get("new_traces") != 0
                    or replay.get("matches")
                    != expected_matches(pre_fault_spec)
                    or rebuild["replay"]["generation"]
                    != generation):
                failures.append({"gate": "rebuilt_replay_warm",
                                 **rebuild})
    finally:
        client.close()
        server.shutdown()
        server.server_close()
        router.stop()

    verdicts: dict = {}
    for rec in records:
        verdicts[rec["verdict"]] = verdicts.get(rec["verdict"],
                                                0) + 1
    if failures:
        print(f"resident soak artifacts kept at {workdir}",
              flush=True)
    else:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    return {
        "kind": "fleet_soak",
        "schema_version": 1,
        "harness_seed": seed,
        "slice": "fleet_resident",
        "fault": "resident-kill",
        "victim": victim,
        "table": table,
        "generation": generation,
        "replica_ranks": replica_ranks,
        "trials": len(records),
        "verdicts": verdicts,
        "failures": len(failures),
        "failure_records": failures,
        "drain_replace": drain_replace,
        "rebuild": rebuild,
        "fleet_stats": router.stats(),
        "records": records,
    }


# -- the soak loop ----------------------------------------------------


def soak(seed: int, trials: int, n_ranks: int = 8,
         corruption: bool = True, only_trial: Optional[int] = None,
         deadline_s: Optional[float] = 300.0,
         repro_out: Optional[str] = None) -> dict:
    """Run the soak (or one replayed trial); returns the summary
    record and writes a minimal-repro JSON per failed trial."""
    indices = ([only_trial] if only_trial is not None
               else list(range(trials)))
    records, failures = [], []
    for k in indices:
        rec = run_trial(seed, k, n_ranks=n_ranks,
                        corruption=corruption, deadline_s=deadline_s)
        records.append(rec)
        line = (f"trial {k:3d} [{rec['config']['mode']:11s}] "
                f"fault={rec['fault']:17s} -> {rec['verdict']} "
                f"({rec['elapsed_s']}s)")
        print(line, flush=True)
        if rec["verdict"].startswith("FAILED"):
            failures.append(rec)
            if repro_out:
                path = repro_out.replace(
                    ".json", f"_{seed}_{k}.json") if repro_out.endswith(
                    ".json") else f"{repro_out}_{seed}_{k}.json"
                repro = dict(rec)
                repro["harness_seed"] = seed
                repro["replay"] = (
                    "python -m distributed_join_tpu.parallel.chaos "
                    f"--seed {seed} --trial {k}"
                )
                with open(path, "w") as f:
                    json.dump(repro, f, indent=2)
                print(f"  repro written: {path}", flush=True)
    verdicts: dict = {}
    for rec in records:
        verdicts[rec["verdict"]] = verdicts.get(rec["verdict"], 0) + 1
    return {
        "harness_seed": seed,
        "n_ranks": n_ranks,
        "trials": len(records),
        "verdicts": verdicts,
        "failures": len(failures),
        "records": records,
    }


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--trials", type=int, default=25)
    p.add_argument("--seed", type=int, default=42,
                   help="harness seed; every trial derives its own "
                        "rng from (seed, trial) so any trial replays "
                        "exactly")
    p.add_argument("--trial", type=int, default=None,
                   help="replay ONE trial of this seed (the repro "
                        "workflow)")
    p.add_argument("--n-ranks", type=int, default=8)
    p.add_argument("--no-corruption", action="store_true",
                   help="restrict schedules to recoverable faults "
                        "(squeezes/transients) — the control arm")
    p.add_argument("--hier-slice", type=int, default=None,
                   metavar="N",
                   help="instead of the main soak: N hierarchical-"
                        "shuffle trials (--shuffle hierarchical over "
                        "a faked multi-slice mesh, fault schedules "
                        "including the cross-slice DCN exchange seam, "
                        "pandas-oracle graded with wire digests on)")
    p.add_argument("--fleet", type=int, default=None, metavar="N",
                   help="instead of the main soak: N join trials "
                        "through a 2-replica subprocess fleet behind "
                        "the signature-affinity router "
                        "(service/fleet.py), ONE replica killed/"
                        "hung/corrupted mid-soak — every non-refused "
                        "answer pandas-oracle-graded, drain+replace "
                        "and the zero-trace warm replacement gated "
                        "(docs/FLEET.md)")
    p.add_argument("--fleet-fault", default=None,
                   choices=("kill", "hang", "corrupt",
                            "resident-kill"),
                   help="pin the fleet soak's fault (default: drawn "
                        "from the harness seed); resident-kill runs "
                        "the REPLICATED-table slice instead (K=2 "
                        "holders, the table's primary holder "
                        "SIGKILLed mid-soak, manifest rebuild + "
                        "fenced zero-trace replay gated)")
    p.add_argument("--replica-ranks", type=int, default=2,
                   help="mesh size of each fleet replica")
    p.add_argument("--tenants", type=int, default=None, metavar="N",
                   help="instead of the main soak: N oracle-graded "
                        "quiet-tenant trials through a 2-replica "
                        "fleet while a noisy tenant floods at ~5x "
                        "its QPS quota and one replica is killed "
                        "mid-soak — the quiet tenant must stay "
                        "exact with ZERO sheds, the noisy tenant "
                        "must be quota-shed, the tuner namespace "
                        "must stay tenant-isolated, and the "
                        "replacement must serve warm (docs/FLEET.md "
                        "\"Multi-tenancy & autoscaling\")")
    p.add_argument("--tuner-slice", type=int, default=None,
                   metavar="N",
                   help="instead of the main soak: N poisoned-history "
                        "autotuner trials (a history file claims a "
                        "too-small rung; every trial must still grade "
                        "oracle-clean via the retry ladder, and the "
                        "post-run history must record the escalated "
                        "rung)")
    p.add_argument("--trial-deadline-s", type=float, default=300.0,
                   help="hang watchdog per trial (0 disables)")
    p.add_argument("--repro-out", default="chaos_repro.json",
                   help="minimal-repro JSON path stem for failed "
                        "trials")
    p.add_argument("--json-output", default=None,
                   help="write the full soak summary record here")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.trials < 1 or args.n_ranks < 2:
        print("chaos: need --trials >= 1 and --n-ranks >= 2",
              file=sys.stderr)
        return 2
    # The soak is a CPU-mesh harness by design (deterministic,
    # hardware-free); reuse the shared platform forcing + the
    # persistent compile cache so repeat soaks replay their programs.
    from distributed_join_tpu.benchmarks import force_cpu_platform

    force_cpu_platform(args.n_ranks)
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/djtpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      0.5)

    if args.tenants:
        summary = fleet_tenant_slice(
            args.seed, args.tenants,
            replica_ranks=args.replica_ranks,
            repro_out=args.repro_out)
    elif args.fleet and args.fleet_fault == "resident-kill":
        summary = fleet_resident_slice(
            args.seed, args.fleet,
            replica_ranks=args.replica_ranks,
            repro_out=args.repro_out)
    elif args.fleet:
        summary = fleet_slice(args.seed, args.fleet,
                              replica_ranks=args.replica_ranks,
                              fault=args.fleet_fault,
                              repro_out=args.repro_out)
    elif args.hier_slice:
        summary = hier_slice(args.seed, args.hier_slice,
                             n_ranks=args.n_ranks,
                             deadline_s=(args.trial_deadline_s
                                         or None),
                             repro_out=args.repro_out)
    elif args.tuner_slice:
        summary = tuner_slice(args.seed, args.tuner_slice,
                              n_ranks=args.n_ranks,
                              deadline_s=(args.trial_deadline_s
                                          or None),
                              repro_out=args.repro_out)
    else:
        summary = soak(
            args.seed, args.trials, n_ranks=args.n_ranks,
            corruption=not args.no_corruption,
            only_trial=args.trial,
            deadline_s=(args.trial_deadline_s or None),
            repro_out=args.repro_out,
        )
    print(json.dumps({k: v for k, v in summary.items()
                      if k != "records"}))
    if args.json_output:
        with open(args.json_output, "w") as f:
            json.dump(summary, f, indent=2)
    return 1 if summary["failures"] else 0


if __name__ == "__main__":
    sys.exit(main())
