"""Multi-operator query plans — the full join-type family composed
into ONE SPMD program.

A :class:`QueryPlan` is a wire-able DAG spec over named base tables:
``join`` operators (any :data:`~..ops.join.JOIN_TYPES` member) plus at
most one ``aggregate`` node riding the terminal join as the existing
fused pushdown (docs/AGGREGATION.md). The executor
(:mod:`..parallel.query_exec`) lowers the whole plan into a single
``shard_map``-compiled program: every intermediate stays row-sharded
on device, each operator's shuffle re-partitions it by the next key in
graph, and only the final groups/rows block ever reaches the host.

Plans are *left-deep chains by construction*: every operator consumes
at most one intermediate, an intermediate feeds exactly one consumer,
and the aggregate (when present) is terminal. Anything else refuses by
name — the same loud-refusal discipline as the join knobs themselves.

``digest()`` (a :func:`~..service.programs.spec_digest` over the
canonical record) keys the program cache: a repeated query — same
plan, same table shapes, same options — dispatches the resident
executable with zero new traces, through the library call and the
daemon's ``query`` wire op alike.

:func:`explain_query` prices the plan per operator with the SAME
:func:`~.plan.build_plan` machinery single joins use, sums the cost
model's verdicts, and — the cost model's first real optimization
decision — enumerates the alternative left-deep join orders and prices
each, surfacing whether the submitted order is the one the model would
pick (``kind: "queryplan"``; ``analyze check``/``analyze explain``
understand the record).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

__all__ = [
    "QueryOp",
    "QueryPlan",
    "QUERY_SCHEMA_VERSION",
    "explain_query",
    "tpch_query_plan",
    "TPCH_QUERIES",
]

QUERY_SCHEMA_VERSION = 1

# Per-operator knobs a plan may carry (everything else refuses: the
# wiring fields have dedicated slots, and an unknown knob would only
# surface as a confusing TypeError deep inside make_join_step).
OP_OPTION_KEYS = (
    "over_decomposition",
    "shuffle_capacity_factor",
    "out_capacity_factor",
    "out_rows_per_rank",
    "shuffle",
    "compression_bits",
    "skew_threshold",
    "sort_mode",
    "sort_segments",
    "dcn_codec",
)


def _refuse(reason: str):
    raise ValueError(f"query plan unsupported: {reason}")


@dataclasses.dataclass(frozen=True)
class QueryOp:
    """One normalized join operator: ``build``/``probe`` name either a
    base table or an earlier operator's output; ``aggregate`` (wire
    dict, terminal op only) fuses the group-by into this join."""

    op_id: str
    build: str
    probe: str
    keys: tuple
    join_type: str = "inner"
    options: tuple = ()          # name-sorted (knob, value) pairs
    aggregate: Optional[dict] = None

    def opts(self) -> dict:
        return dict(self.options)

    def as_record(self) -> dict:
        rec = {
            "id": self.op_id,
            "op": "join",
            "build": self.build,
            "probe": self.probe,
            "key": list(self.keys),
            "join_type": self.join_type,
            "options": dict(self.options),
        }
        if self.aggregate is not None:
            rec["aggregate"] = dict(self.aggregate)
        return rec


@dataclasses.dataclass(frozen=True)
class QueryPlan:
    """The validated, normalized multi-operator plan. Build with
    :meth:`of` (or :meth:`from_wire`); the raw constructor performs no
    validation."""

    tables: tuple                # base table names, execution arg order
    ops: tuple                   # QueryOp chain, topological order

    # -- construction --------------------------------------------------

    @classmethod
    def of(cls, ops: Sequence[dict], tables=None) -> "QueryPlan":
        """Normalize a loose op list. Each entry is a dict:

        ``{"op": "join", "id": ..., "build": ref, "probe": ref,
        "key": name-or-list, "join_type": ..., "options": {...}}``
        or
        ``{"op": "aggregate", "id": ..., "input": join-id,
        "spec": AggregateSpec-or-wire-dict}``.

        The aggregate node is fused into its input join (which must be
        terminal); refs resolve to base table names or earlier op ids.
        ``tables`` fixes the execution argument order; omitted, it is
        the order of first reference.
        """
        from distributed_join_tpu.ops import aggregate as agg_ops
        from distributed_join_tpu.ops.join import JOIN_TYPES

        if not ops:
            _refuse("empty operator list")
        seen_ids: set = set()
        joins: list = []
        agg_nodes: list = []
        for entry in ops:
            if not isinstance(entry, dict):
                _refuse(f"operator entry {entry!r} is not a mapping")
            kind = entry.get("op")
            op_id = entry.get("id")
            if not op_id or not isinstance(op_id, str):
                _refuse(f"operator {entry!r} is missing an 'id'")
            if op_id in seen_ids:
                _refuse(f"duplicate operator id {op_id!r}")
            seen_ids.add(op_id)
            if kind == "join":
                key = entry.get("key")
                keys = ((key,) if isinstance(key, str)
                        else tuple(key or ()))
                if not keys:
                    _refuse(f"join {op_id!r} has no key")
                jt = entry.get("join_type") or "inner"
                if jt not in JOIN_TYPES:
                    _refuse(f"join {op_id!r} join_type {jt!r} is not "
                            f"one of {JOIN_TYPES}")
                raw_opts = dict(entry.get("options") or {})
                for knob in raw_opts:
                    if knob not in OP_OPTION_KEYS:
                        _refuse(
                            f"join {op_id!r} option {knob!r} is not a "
                            f"plan-settable knob {OP_OPTION_KEYS}")
                joins.append(QueryOp(
                    op_id=op_id,
                    build=str(entry.get("build")),
                    probe=str(entry.get("probe")),
                    keys=keys,
                    join_type=jt,
                    options=tuple(sorted(raw_opts.items())),
                ))
            elif kind == "aggregate":
                spec = entry.get("spec")
                if isinstance(spec, agg_ops.AggregateSpec):
                    wire = _agg_wire(spec)
                elif isinstance(spec, dict):
                    # Round-trip so a malformed wire spec refuses HERE
                    wire = _agg_wire(agg_ops.AggregateSpec.from_wire(
                        spec))
                else:
                    _refuse(f"aggregate {op_id!r} has no spec")
                agg_nodes.append((op_id, str(entry.get("input")),
                                  wire))
            else:
                _refuse(f"operator {op_id!r} kind {kind!r} is not "
                        "'join' or 'aggregate'")

        if not joins:
            _refuse("plan has no join operators")
        if len(agg_nodes) > 1:
            _refuse("more than one aggregate node; compose further "
                    "reductions on the host")
        if agg_nodes:
            agg_id, agg_input, wire = agg_nodes[0]
            if agg_input != joins[-1].op_id:
                _refuse(
                    f"aggregate {agg_id!r} consumes {agg_input!r}, "
                    f"but only the terminal join "
                    f"({joins[-1].op_id!r}) supports the fused "
                    "pushdown — standalone group-by nodes are "
                    "unimplemented")
            joins[-1] = dataclasses.replace(joins[-1], aggregate=wire)

        plan = cls(tables=(), ops=tuple(joins))
        plan = dataclasses.replace(
            plan, tables=plan._resolve_tables(tables))
        plan._validate_wiring()
        return plan

    @classmethod
    def from_wire(cls, doc: dict) -> "QueryPlan":
        """Rebuild from :meth:`canonical` / the daemon's wire form."""
        if not isinstance(doc, dict):
            _refuse("wire plan is not a mapping")
        ops = []
        for rec in doc.get("ops") or ():
            rec = dict(rec)
            agg = rec.pop("aggregate", None)
            rec.setdefault("op", "join")
            ops.append(rec)
            if agg is not None:
                ops.append({"op": "aggregate",
                            "id": f"__agg_{rec['id']}",
                            "input": rec["id"], "spec": agg})
        return cls.of(ops, tables=doc.get("tables"))

    # -- identity ------------------------------------------------------

    def canonical(self) -> dict:
        return {
            "schema_version": QUERY_SCHEMA_VERSION,
            "tables": list(self.tables),
            "ops": [op.as_record() for op in self.ops],
            "output": self.ops[-1].op_id,
        }

    def digest(self) -> str:
        from distributed_join_tpu.service.programs import spec_digest

        return spec_digest(self.canonical())

    @property
    def output(self) -> str:
        return self.ops[-1].op_id

    @property
    def aggregate(self):
        from distributed_join_tpu.ops import aggregate as agg_ops

        wire = self.ops[-1].aggregate
        return (None if wire is None
                else agg_ops.AggregateSpec.from_wire(wire))

    def n_operators(self) -> int:
        agg = 1 if self.ops[-1].aggregate is not None else 0
        return len(self.ops) + agg

    # -- validation ----------------------------------------------------

    def _resolve_tables(self, tables) -> tuple:
        op_ids = {op.op_id for op in self.ops}
        referenced = []
        for op in self.ops:
            for ref in (op.build, op.probe):
                if ref not in op_ids and ref not in referenced:
                    referenced.append(ref)
        if tables is None:
            return tuple(referenced)
        tables = tuple(tables)
        if sorted(tables) != sorted(referenced):
            _refuse(f"declared tables {sorted(tables)} != referenced "
                    f"base tables {sorted(referenced)}")
        return tables

    def _validate_wiring(self) -> None:
        available = set(self.tables)
        consumers: dict = {}
        for op in self.ops:
            for ref in (op.build, op.probe):
                if ref not in available:
                    _refuse(
                        f"join {op.op_id!r} input {ref!r} is neither "
                        "a base table nor an earlier operator (plans "
                        "are topologically ordered)")
                consumers.setdefault(ref, []).append(op.op_id)
            if op.build == op.probe:
                _refuse(f"join {op.op_id!r} joins {op.build!r} with "
                        "itself on the same reference; alias the "
                        "table under two names for a self-join")
            available.add(op.op_id)
        op_ids = {op.op_id for op in self.ops}
        for ref, users in consumers.items():
            if ref in op_ids and len(users) > 1:
                _refuse(
                    f"intermediate {ref!r} feeds {sorted(users)}; "
                    "DAG fan-out of an operator output is "
                    "unimplemented — plans are left-deep chains")
        terminal = [op.op_id for op in self.ops
                    if op.op_id not in consumers]
        if terminal != [self.ops[-1].op_id]:
            _refuse(f"plan has dangling operators {sorted(terminal)}; "
                    "exactly the last op may be unconsumed")

    # -- schema inference ----------------------------------------------

    def infer_schemas(self, table_schemas: dict) -> dict:
        """Propagate column schemas through the chain. Input and
        output are ``{name: {column: (dtype_str, trailing_shape)}}``;
        refusals (missing keys, dtype mismatches, cross-side column
        collisions) name the operator. The returned dict additionally
        holds every intermediate under its op id."""
        from distributed_join_tpu.ops import aggregate as agg_ops
        from distributed_join_tpu.ops.join import (
            BUILD_VALID, OUTER_TYPES, PROBE_VALID,
        )

        env = {name: dict(cols)
               for name, cols in table_schemas.items()}
        for name in self.tables:
            if name not in env:
                _refuse(f"no schema given for base table {name!r}")
        for op in self.ops:
            b, p = env[op.build], env[op.probe]
            for kname in op.keys:
                if kname not in b or kname not in p:
                    _refuse(f"join {op.op_id!r} key {kname!r} missing "
                            f"on {'build' if kname not in b else 'probe'}"
                            f" side")
                if b[kname] != p[kname]:
                    _refuse(f"join {op.op_id!r} key {kname!r} dtype "
                            f"mismatch: build {b[kname]} vs probe "
                            f"{p[kname]}")
            out = {kname: p[kname] for kname in op.keys}
            b_pay = {c: s for c, s in b.items() if c not in op.keys}
            p_pay = {c: s for c, s in p.items() if c not in op.keys}
            clash = sorted(set(b_pay) & set(p_pay))
            if clash and op.join_type not in ("semi", "anti"):
                _refuse(f"join {op.op_id!r} payload column(s) {clash} "
                        "exist on both sides — rename before "
                        "planning")
            if op.join_type in ("semi", "anti"):
                out.update(p_pay)
            else:
                out.update(b_pay)
                out.update(p_pay)
                if op.join_type in OUTER_TYPES:
                    if op.join_type in ("left", "full_outer"):
                        out[BUILD_VALID] = ("bool", ())
                    if op.join_type in ("right", "full_outer"):
                        out[PROBE_VALID] = ("bool", ())
            if op.aggregate is not None:
                spec = agg_ops.AggregateSpec.from_wire(op.aggregate)
                bcols = {c: s for c, s in b.items()}
                pcols = {c: s for c, s in p.items()}
                # The step-level contract, checked at plan time.
                agg_ops.resolve_agg_mode(
                    spec, list(op.keys),
                    {c: (dt, 1 + len(sh)) for c, (dt, sh)
                     in bcols.items()},
                    {c: (dt, 1 + len(sh)) for c, (dt, sh)
                     in pcols.items()})
                out = _agg_out_schema(spec, op.keys, bcols, pcols)
            env[op.op_id] = out
        return env


def _agg_wire(spec) -> dict:
    return {
        "group_by": list(spec.group_keys),
        "aggs": [[a.op, a.column, a.name] for a in spec.aggs],
        "carry": list(spec.carry),
        "groups_per_rank": spec.groups_per_rank,
    }


def _agg_out_schema(spec, keys, bcols, pcols) -> dict:
    def side(col):
        return bcols.get(col) or pcols.get(col) or ("int64", ())

    out = {g: side(g) for g in spec.group_keys}
    for a in spec.aggs:
        if a.op == "count":
            out[a.name] = ("int64", ())
        elif a.op == "sum":
            out[a.name] = ("int64", ())
        elif a.op == "mean":
            out[a.name] = ("float64", ())
        else:                      # min / max keep the input dtype
            out[a.name] = side(a.column)
    for c in spec.carry:
        out[c] = side(c)
    return out


# -- explain / costing -------------------------------------------------


def _table_schema(table) -> dict:
    return {name: (str(col.dtype), tuple(col.shape[1:]))
            for name, col in table.columns.items()}


def _abstract_table(schema: dict, rows: int):
    import jax
    import jax.numpy as jnp

    from distributed_join_tpu.table import Table

    cols = {
        name: jax.ShapeDtypeStruct((rows,) + tuple(shape),
                                   jnp.dtype(dtype))
        for name, (dtype, shape) in schema.items()
    }
    return Table(cols, jax.ShapeDtypeStruct((rows,), jnp.bool_))


def _est_out_rows(op: QueryOp, b_rows: int, p_rows: int) -> int:
    """Host-side cardinality estimate for an intermediate: the chain's
    joins are FK joins in the workloads we price (each probe row
    matches at most one build row), so the preserved probe side bounds
    inner/left/semi/anti output; right/full_outer add the unmatched
    build rows. Display/cardinality only — operator plans size their
    inputs from :func:`_materialized_capacity`, which the estimate
    never enters."""
    if op.join_type in ("right", "full_outer"):
        return p_rows + b_rows
    return p_rows


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _materialized_capacity(op: QueryOp, p_global: int, n: int,
                           defaults: dict) -> int:
    """The EXACT global capacity of this operator's output table —
    the step materializes k parts of ``out_cap`` rows per rank
    (plus the heavy-hitter sidecar block when PRPD is on), pure host
    arithmetic mirroring ``make_join_step``. This is what makes the
    downstream operator's wire-byte predictions exact: the next step
    partitions the materialized block, padding included, not the
    matched rows."""
    import math as _math

    opts = dict(defaults)
    opts.update(op.opts())
    k = int(opts.get("over_decomposition") or 1)
    out_f = float(opts.get("out_capacity_factor")
                  or _DEFAULT_OUT_F())
    out_rows = opts.get("out_rows_per_rank")
    p_local = _round_up(p_global, n) // n
    if out_rows is not None:
        out_cap = _round_up(int(_math.ceil(out_rows / k)), 8)
    else:
        out_cap = _round_up(int(_math.ceil(p_local / k * out_f)), 8)
    per_rank = k * out_cap
    if opts.get("skew_threshold") is not None:
        per_rank += int(opts.get("hh_out_capacity")
                        or max(p_local // 4, 1024))
    return n * per_rank


def _DEFAULT_OUT_F():
    from distributed_join_tpu.parallel.distributed_join import (
        DEFAULT_OUT_CAPACITY_FACTOR,
    )

    return DEFAULT_OUT_CAPACITY_FACTOR


def explain_query(plan: QueryPlan, comm, tables: dict,
                  cost_model=None, defaults: Optional[dict] = None,
                  orders: bool = True) -> dict:
    """Price ``plan`` per operator without tracing anything. ``tables``
    maps base table name -> Table (real arrays or ShapeDtypeStructs).
    Returns the ``kind: "queryplan"`` record: per-operator
    :func:`~.plan.explain_join` plans + :func:`~.cost.predict`
    verdicts, the summed critical path, and (``orders=True``, all-inner
    chains only) every alternative left-deep join order priced by the
    same model with the cheapest flagged."""
    from distributed_join_tpu.planning import cost as cost_mod
    from distributed_join_tpu.planning.plan import explain_join

    defaults = dict(defaults or {})
    schemas = {name: _table_schema(t) for name, t in tables.items()}
    inferred = plan.infer_schemas(schemas)
    rows = {name: int(next(iter(t.columns.values())).shape[0])
            for name, t in tables.items()}

    op_records = []
    total_s = 0.0
    for op in plan.ops:
        b_rows, p_rows = rows[op.build], rows[op.probe]
        b_tbl = (tables[op.build] if op.build in tables
                 else _abstract_table(inferred[op.build], b_rows))
        p_tbl = (tables[op.probe] if op.probe in tables
                 else _abstract_table(inferred[op.probe], p_rows))
        opts = dict(defaults)
        opts.update(op.opts())
        if op.aggregate is not None:
            from distributed_join_tpu.ops import aggregate as agg_ops

            opts["aggregate"] = agg_ops.AggregateSpec.from_wire(
                op.aggregate)
        opts["join_type"] = op.join_type
        key = list(op.keys) if len(op.keys) > 1 else op.keys[0]
        jplan = explain_join(b_tbl, p_tbl, comm, key=key,
                             cost_model=cost_model, **opts)
        verdict = cost_mod.predict(jplan, cost_model)
        op_total = float(verdict.get("total_s") or 0.0)
        total_s += op_total
        rows[op.op_id] = _materialized_capacity(
            op, p_rows, int(comm.n_ranks), defaults)
        op_records.append({
            "id": op.op_id,
            "build": op.build,
            "probe": op.probe,
            "key": list(op.keys),
            "join_type": op.join_type,
            "aggregate": (dict(op.aggregate)
                          if op.aggregate is not None else None),
            "build_rows": b_rows,
            "probe_rows": p_rows,
            "est_out_rows": _est_out_rows(op, b_rows, p_rows),
            "out_capacity": rows[op.op_id],
            "digest": jplan.digest,
            "wire": jplan.wire,
            "cost": verdict,
        })

    record = {
        "schema_version": QUERY_SCHEMA_VERSION,
        "kind": "queryplan",
        "digest": plan.digest(),
        "n_ranks": int(comm.n_ranks),
        "plan": plan.canonical(),
        "operators": op_records,
        "n_operators": plan.n_operators(),
        "total_s": total_s,
    }
    if orders:
        record["orders"] = _priced_orders(
            plan, comm, tables, rows, cost_model, defaults, total_s)
    return record


def _priced_orders(plan, comm, tables, rows, cost_model, defaults,
                   own_total) -> list:
    """Enumerate + price the alternative left-deep join orders. Only
    all-inner chains reorder (outer/semi/anti joins are not freely
    commutative); each candidate keeps the submitted plan's per-op
    options for the op joining the same new table."""
    if any(op.join_type != "inner" for op in plan.ops):
        return [{"tables": list(_chain_order(plan)),
                 "total_s": own_total, "chosen": True,
                 "note": "non-inner joins pin the submitted order"}]
    base = list(plan.tables)
    if len(base) != len(plan.ops) + 1 or len(base) > 6:
        return [{"tables": list(_chain_order(plan)),
                 "total_s": own_total, "chosen": True,
                 "note": "order enumeration covers simple chains of "
                         "up to 6 tables"}]
    import itertools

    schemas = {name: _table_schema(t) for name, t in tables.items()}
    key_universe = sorted({k for op in plan.ops for k in op.keys})
    own = tuple(_chain_order(plan))
    priced = []
    for perm in itertools.permutations(base):
        chain = _chain_plan(plan, perm, key_universe, schemas)
        if chain is None:
            continue
        if perm == own:
            priced.append({"tables": list(perm),
                           "total_s": own_total, "chosen": True})
            continue
        try:
            rec = explain_query(chain, comm, tables,
                                cost_model=cost_model,
                                defaults=defaults, orders=False)
            priced.append({"tables": list(perm),
                           "total_s": rec["total_s"],
                           "chosen": False})
        except ValueError as exc:
            priced.append({"tables": list(perm), "total_s": None,
                           "chosen": False, "note": str(exc)})
    viable = [o for o in priced if o["total_s"] is not None]
    viable.sort(key=lambda o: o["total_s"])
    if viable:
        viable[0]["cheapest"] = True
    return priced


def _chain_order(plan: QueryPlan) -> list:
    """Base tables in the order the submitted chain accumulates
    them."""
    seen: list = []
    op_ids = {op.op_id for op in plan.ops}
    for op in plan.ops:
        for ref in (op.build, op.probe):
            if ref not in op_ids and ref not in seen:
                seen.append(ref)
    return seen


def _chain_plan(plan, order, key_universe, schemas):
    """Rebuild ``plan`` as the left-deep chain accumulating ``order``;
    None when some step shares no join key with the accumulated
    schema. First pair orients smaller-schema... the FIRST table as
    build (candidates are priced relative to each other under one
    convention; the submitted plan keeps its own orientation)."""
    avail = dict(schemas[order[0]])
    ops = []
    prev = order[0]
    for i, name in enumerate(order[1:]):
        keys = [k for k in key_universe
                if k in avail and k in schemas[name]]
        if not keys:
            return None
        ops.append({
            "op": "join", "id": f"o{i}", "build": prev,
            "probe": name, "key": keys,
            "join_type": "inner",
        })
        for col, sig in schemas[name].items():
            avail.setdefault(col, sig)
        prev = f"o{i}"
    if plan.ops[-1].aggregate is not None:
        ops.append({"op": "aggregate", "id": "__agg",
                    "input": ops[-1]["id"],
                    "spec": dict(plan.ops[-1].aggregate)})
    try:
        return QueryPlan.of(ops)
    except ValueError:
        return None


# -- the TPC-H demo plans ----------------------------------------------

TPCH_QUERIES = ("q3", "q10")


def tpch_query_plan(query: str) -> QueryPlan:
    """The canonical 3-table TPC-H chains the drivers/tests/daemon
    run: ``customer ⋈ orders`` on ``custkey``, the intermediate ⋈
    ``lineitem`` on ``orderkey``, group-by fused into the second join.
    Q3 groups by the join key (key-mode pushdown); Q10 groups by the
    BUILD-side customer key (build-mode pushdown) — between them the
    two exercise both fused settle paths end to end."""
    from distributed_join_tpu.ops.aggregate import AggregateSpec

    if query == "q3":
        agg = AggregateSpec.of(
            "orderkey",
            [("sum", "l_extendedprice", "revenue"),
             ("count", None, "n_lines")],
            carry=("o_orderdate",))
    elif query == "q10":
        agg = AggregateSpec.of(
            "custkey",
            [("sum", "l_extendedprice", "revenue"),
             ("count", None, "n_lines")],
            carry=("c_acctbal",))
    else:
        raise ValueError(
            f"unknown TPC-H query {query!r}; pick one of "
            f"{TPCH_QUERIES}")
    return QueryPlan.of([
        {"op": "join", "id": "j_cust_ord", "build": "customer",
         "probe": "orders", "key": "custkey", "join_type": "inner"},
        {"op": "join", "id": "j_ord_line", "build": "j_cust_ord",
         "probe": "lineitem", "key": "orderkey",
         "join_type": "inner"},
        {"op": "aggregate", "id": "groupby", "input": "j_ord_line",
         "spec": agg},
    ])
