"""History-driven autotuner — the closed loop of ROADMAP item 5.

The repo *measures* everything needed to pick an execution
configuration: the workload-history store (:mod:`..telemetry.history`)
records, per workload signature, what the retry ladder resolved to,
the device-counter skew/headroom indicators, and the cost-model
prediction error; the :class:`~.plan.JoinPlan` makes the static knob
resolution inspectable. Until this module a human had to read them
(``analyze history`` / ``analyze diagnose``) and re-run with better
flags. :class:`JoinTuner` closes the loop:

- **first run conservative** — no history for a signature means no
  overrides: the join runs exactly ``build_plan``'s static resolution
  (the tuner-off program, byte-identical);
- **later runs pre-sized** — a workload whose ladder previously
  escalated starts at the FINAL rung it resolved to: the adopted
  sizing *and* the rung label, so the dispatch signature equals the
  executable the cold run already traced — a warm tuned repeat is a
  program-cache hit with ZERO new traces and ZERO ladder escalations
  (the cost ADVICE.md flags the skew auto-policy paying when its
  sizing model is wrong, eliminated for repeat workloads);
- **structural knobs from evidence** — PRPD skew handling turns on
  when the observed per-rank key-skew Gini crosses the diagnosis
  threshold; the exact-size ragged wire replaces padded when the
  measured wire efficiency shows padding dominating the bytes. These
  change the program (one trace on the first tuned run, warm after),
  and only ever fill knobs the caller left UNSET — an explicit
  structural choice is never overridden;
- **never correctness for speed** — the tuner only picks starting
  points; the capacity ladder still guards every run, so a lying
  history (too-small claimed capacities) costs recompiles, not wrong
  rows, and the corrected rung lands back in the store for the next
  run (the chaos harness grades exactly this, ``chaos --tuner-slice``).

Sizing knobs (capacity factors, ``out_rows_per_rank``, compression
width, HH block sizes) OVERRIDE caller values: the history is
evidence that this exact workload signature — which already binds the
caller's values — overflowed them, and pre-applying the ladder's own
escalation is the entire point. Structural knobs (``shuffle`` mode,
``skew_threshold``) fill only when absent.

Surfaces: ``distributed_inner_join(tuner=)``,
``JoinService`` / ``tpu-join-service --auto-tune``, the drivers' and
bench.py's ``--auto-tune[=HISTORY]`` (capacity pre-sizing only — the
driver store keys workloads by flag identity, where a mode switch
would fork the signature), and ``analyze tune`` (the dry run: knob
delta vs the static plan, per signature).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
from typing import Optional

TUNER_SCHEMA_VERSION = 1

# The ladder's own axes: pre-applied from history, overriding caller
# values (see module docstring for why override is correct here).
SIZING_KNOBS = (
    "shuffle_capacity_factor", "out_capacity_factor",
    "out_rows_per_rank", "compression_bits",
    "hh_build_capacity", "hh_probe_capacity", "hh_out_capacity",
)
# Program-shape knobs: filled only when the caller left them unset.
STRUCTURAL_KNOBS = ("shuffle", "skew_threshold", "dcn_codec",
                    "sort_mode")

# The local sort dominates a workload's join stage when the measured
# join-stage wall crosses this fraction of the summed stage walls —
# the per-stage-history evidence bar for flipping the segmented-sort
# path on (docs/ROOFLINE.md §9; the stage walls come from
# --stage-profile runs recorded in the history store).
SORT_STAGE_SHARE_WARN = 0.5

# The DCN tier dominates a hierarchical run's wire when the measured
# cross-slice share of the bytes crosses this fraction — the evidence
# bar for recommending the cross-slice codec (docs/HIERARCHY.md).
DCN_SHARE_WARN = 0.4

# The measured sweep default the skew recommendation names
# (telemetry/analyze.recommend's skew_enable_prpd flag).
DEFAULT_SKEW_THRESHOLD = 0.001
# Headroom bump mirrors analyze.recommend's shuffle_headroom advice.
HEADROOM_BUMP = 1.5


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def workload_signature(comm, build, probe, key="key",
                       with_metrics=None, with_integrity: bool = False,
                       **opts) -> str:
    """THE rung-stable workload identity (16 hex chars): the program
    cache's canonical signature digest over the tables and the
    caller's PRE-TUNED options — the ladder resolves its sizing at
    dispatch and the tuner applies its overrides after this hash, so
    one workload keeps one identity across rungs and tuned re-runs.
    Shared by :class:`~..service.server.JoinService` (its history
    entries and live-metrics keys) and
    ``distributed_inner_join(tuner=)`` so writer and reader can never
    disagree. Unknown option combinations still get an identity (the
    join itself refuses them loudly) via a shape+options hash."""
    from distributed_join_tpu import telemetry

    if with_metrics is None:
        with_metrics = telemetry.enabled()
    try:
        from distributed_join_tpu.service.programs import JoinSignature

        return JoinSignature.of(
            comm, build, probe, key=key, with_metrics=with_metrics,
            with_integrity=with_integrity, **opts).digest()[:16]
    except Exception:
        basis = json.dumps(
            {"key": key,
             "build": sorted(build.columns),
             "probe": sorted(probe.columns),
             "opts": sorted((k, repr(v)) for k, v in opts.items())},
            sort_keys=True, default=str)
        return hashlib.sha256(basis.encode()).hexdigest()[:16]


@dataclasses.dataclass
class TunedConfig:
    """One signature's tuning verdict: what to override (sizing), what
    to fill (structural), which rung to label the first attempt with,
    and the evidence. ``source`` is ``"history"`` when anything was
    adopted, ``"static"`` for the conservative no-history first run."""

    signature: str
    source: str = "static"
    rung: int = 0
    sizing: dict = dataclasses.field(default_factory=dict)
    structural: dict = dataclasses.field(default_factory=dict)
    basis: dict = dataclasses.field(default_factory=dict)
    applied: dict = dataclasses.field(default_factory=dict)

    def apply(self, opts: dict) -> dict:
        """Merge this verdict into a join's option dict (returns a new
        dict; ``opts`` untouched). Structural knobs fill only when
        absent; sizing knobs override. HH capacities apply only when
        the merged options actually run the skew path — injecting them
        into a skew-off program would fork its cache signature for
        options the step never reads. Records what changed in
        ``self.applied``."""
        new = dict(opts)
        applied = {}
        for k, v in self.structural.items():
            if k not in new:
                new[k] = v
                applied[k] = v
        skew_on = new.get("skew_threshold") is not None
        for k, v in self.sizing.items():
            if k.startswith("hh_") and not skew_on:
                continue
            if new.get(k) != v:
                applied[k] = v
            new[k] = v
        self.applied = applied
        return new

    def as_record(self) -> dict:
        return {
            "schema_version": TUNER_SCHEMA_VERSION,
            "signature": self.signature,
            "source": self.source,
            "rung": self.rung,
            "sizing": dict(self.sizing),
            "structural": dict(self.structural),
            "applied": dict(self.applied),
            "basis": dict(self.basis),
        }


class JoinTuner:
    """Per-signature knob selection from a :class:`~..telemetry.
    history.WorkloadHistory` store plus the roofline cost model.

    In-memory table of :class:`~..telemetry.history.SignatureTrend`
    aggregates: load once from a history file (missing file = empty =
    every workload static), then feed live entries via
    :meth:`observe_entry` (the JoinService wiring — the tuner learns
    within one server lifetime, including the corrected rung after a
    mis-sized pre-size). Thresholds default to the diagnosis layer's
    (``telemetry/analyze.py``), so the tuner automates exactly the
    recommendations ``analyze diagnose`` prints."""

    def __init__(self, history: Optional[str] = None, *,
                 min_entries: int = 1,
                 skew_gini_warn: Optional[float] = None,
                 wire_efficiency_warn: Optional[float] = None,
                 headroom_ratio_warn: Optional[float] = None):
        from distributed_join_tpu.telemetry.analyze import (
            HEADROOM_RATIO_WARN,
            SKEW_GINI_WARN,
            WIRE_EFFICIENCY_WARN,
        )

        self.path = None
        self.min_entries = int(min_entries)
        self.skew_gini_warn = (skew_gini_warn if skew_gini_warn
                               is not None else SKEW_GINI_WARN)
        self.wire_efficiency_warn = (
            wire_efficiency_warn if wire_efficiency_warn is not None
            else WIRE_EFFICIENCY_WARN)
        self.headroom_ratio_warn = (
            headroom_ratio_warn if headroom_ratio_warn is not None
            else HEADROOM_RATIO_WARN)
        self._trends: dict = {}
        self.observed = 0
        self.recommendations = 0
        self.history_hits = 0
        # The serving layer's per-request tenant (set under the exec
        # lock right before dispatch): recommend() consults it when no
        # explicit tenant rides the call, so the deep call sites
        # inside distributed_inner_join need no signature change.
        # None = default tenant = the bare-signature lookup.
        self.active_tenant: Optional[str] = None
        if history:
            self.load(history)

    # -- the write side (what the tuner knows) -------------------------

    def load(self, history: str) -> int:
        """(Re)load a history store; missing file = empty table (every
        workload starts static). Returns entries loaded."""
        from distributed_join_tpu.telemetry import history as hist

        self.path = hist.history_path(history)
        self._trends = {}
        self.observed = 0
        if not os.path.exists(self.path):
            return 0
        entries, _ = hist.load_history(self.path)
        for e in entries:
            self.observe_entry(e)
        return len(entries)

    def observe_entry(self, entry: dict) -> None:
        """Fold one history entry (request/run/rollup line) into the
        per-signature table — the JoinService calls this right after
        appending to its store, so a pre-size that still escalated is
        corrected for the very next request."""
        from distributed_join_tpu.telemetry.history import (
            SignatureTrend,
            tenant_key,
        )

        # Tenant-NAMESPACED key (docs/FLEET.md "Multi-tenancy"): a
        # non-default tenant's entries land under tenant/signature, so
        # its skewed or poisoned history can never pre-size another
        # tenant's programs. Un-stamped (default-tenant) entries keep
        # the bare signature — the exact pre-tenancy table.
        sig = tenant_key(entry.get("signature"), entry.get("tenant"))
        self._trends.setdefault(sig, SignatureTrend()).add(entry)
        self.observed += 1

    def stats(self) -> dict:
        return {
            "signatures": len(self._trends),
            "observed": self.observed,
            "recommendations": self.recommendations,
            "history_hits": self.history_hits,
            "min_entries": self.min_entries,
            "history_path": self.path,
        }

    # -- the read side (the decision) ----------------------------------

    def recommend(self, signature: str, user_opts: Optional[dict] = None,
                  *, side_geometry: Optional[dict] = None,
                  tenant: Optional[str] = None) -> TunedConfig:
        """The knob verdict for one workload signature.

        ``user_opts`` is the caller's raw option dict (structural
        knobs present there are never filled). ``side_geometry`` —
        ``{"b_local", "p_local", "nb", "row_bytes": {side: int}}`` —
        enables the shape-dependent policies (headroom ratio, wire
        efficiency); :meth:`resolve` supplies it from real tables.

        Policy, in order (each clause records its evidence in
        ``basis``):

        1. no trend / fewer than ``min_entries`` entries / no
           successful run / counter DRIFT at unchanged sizing ->
           static (conservative; drift means the data moved and old
           sizing is stale evidence);
        2. ladder escalations on record -> adopt the final rung's
           sizing AND its rung label (the zero-recompile warm path);
        3. else tight overflow-margin headroom (margin below
           ``headroom_ratio_warn`` of the per-bucket capacity) ->
           bump ``shuffle_capacity_factor`` by ``HEADROOM_BUMP``
           before the next data drift trips a recompile;
        4. key-skew Gini over the warn threshold -> enable PRPD
           (``skew_threshold=0.001``) when the caller didn't choose;
        5. padded wire efficiency under the warn threshold -> switch
           to the exact-size ragged wire when the caller didn't
           choose a mode and compression is off.
        """
        from distributed_join_tpu.telemetry.history import tenant_key

        user_opts = user_opts or {}
        self.recommendations += 1
        cfg = TunedConfig(signature=signature)
        # Read from the caller's OWN tenant namespace only (None /
        # default tenant = the bare-signature table, the pre-tenancy
        # lookup exactly).
        if tenant is None:
            tenant = self.active_tenant
        trend = self._trends.get(tenant_key(signature, tenant))
        if trend is None or trend.entries < self.min_entries:
            cfg.basis["note"] = (
                f"no history for signature ({trend.entries if trend else 0}"
                f"/{self.min_entries} entries) — static plan")
            return cfg
        cfg.basis["entries"] = trend.entries
        if trend.successes == 0:
            cfg.basis["note"] = ("no successful run on record — "
                                 "refusing to pre-size from failures")
            return cfg
        if trend.counter_drift:
            cfg.basis["note"] = (
                "counter signature drifted at unchanged sizing — data "
                "moved; re-observing before pre-sizing")
            return cfg

        # 2. adopt the escalated rung's sizing.
        if trend.escalations and trend.resolved_knobs_last:
            cfg.sizing = {k: v for k, v
                          in trend.resolved_knobs_last.items()
                          if k in SIZING_KNOBS}
            cfg.rung = int(trend.resolved_rung_last or 0)
            cfg.source = "history"
            cfg.basis["adopted_rung"] = {
                "escalations": trend.escalations,
                "rung": cfg.rung,
            }
        elif side_geometry:
            # 3. no escalations: check the recorded headroom against
            # the capacity the observed factor implies.
            bump = self._headroom_bump(trend, user_opts,
                                       side_geometry)
            if bump is not None:
                cfg.sizing["shuffle_capacity_factor"] = bump[0]
                cfg.source = "history"
                cfg.basis["headroom"] = bump[1]

        # 4. skew: enable PRPD on observed per-rank imbalance. Never
        # under aggregation pushdown — the fused pipeline refuses the
        # skew sidecar loudly (ops/aggregate.py's contract), and a
        # history-filled knob must not turn a working aggregate
        # workload into an error.
        if "skew_threshold" not in user_opts \
                and user_opts.get("aggregate") is None:
            gini = self._worst_gini(trend.indicators_last)
            if gini is not None and gini[1] > self.skew_gini_warn:
                cfg.structural["skew_threshold"] = \
                    DEFAULT_SKEW_THRESHOLD
                cfg.source = "history"
                cfg.basis["skew"] = {"counter": gini[0],
                                     "gini": gini[1],
                                     "warn": self.skew_gini_warn}

        # 5. wire: padding-dominated bytes -> the exact-size ragged
        # wire on a flat mesh; on a multi-slice mesh ragged would
        # route ONE global exchange across DCN, so the evidence-backed
        # answer there is the two-level hierarchical shuffle instead
        # (docs/HIERARCHY.md — the slice-local/global split choice).
        if ("shuffle" not in user_opts
                and user_opts.get("compression_bits") is None
                and "compression_bits" not in cfg.sizing
                and side_geometry):
            eff = self._wire_efficiency(trend.counters_last,
                                        side_geometry)
            if eff is not None and eff[1] < self.wire_efficiency_warn:
                multi_slice = (side_geometry.get("n_slices") or 1) > 1
                cfg.structural["shuffle"] = (
                    "hierarchical" if multi_slice else "ragged")
                cfg.source = "history"
                cfg.basis["wire"] = {"side": eff[0],
                                     "efficiency": eff[1],
                                     "warn": self.wire_efficiency_warn}

        # 6. DCN codec: a hierarchical workload whose measured
        # cross-slice bytes dominate the wire — and whose codec was
        # off — flips the FoR+bitpack wire on for exactly that tier
        # when the caller didn't choose (the break-even argument,
        # docs/HIERARCHY.md; the codec-ON case needs no clause — its
        # bits adopt through the rung sizing like every other knob).
        if "dcn_codec" not in user_opts:
            share = self._dcn_share(trend.counters_last)
            if share is not None and share[0] > DCN_SHARE_WARN \
                    and not share[1]:
                cfg.structural["dcn_codec"] = "on"
                cfg.source = "history"
                cfg.basis["dcn_codec"] = {
                    "dcn_share": share[0],
                    "warn": DCN_SHARE_WARN,
                    "codec_was_on": share[1]}

        # 7. sort mode: per-stage history (--stage-profile runs land a
        # stages block on the signature's entries) showing the JOIN
        # stage — where the merged sort lives — dominating the summed
        # stage walls flips the segmented-sort path on
        # (docs/ROOFLINE.md §9) when the caller didn't choose and the
        # combination supports it (never over ragged/compressed/
        # aggregate programs — the step refuses those loudly, and a
        # history-filled knob must not turn a working workload into
        # an error). Gated on the resolver actually segmenting at
        # this shape: a one-segment resolution is flat parity and a
        # pointless signature fork.
        # A hierarchical workload with the DCN codec armed (explicit,
        # step-6-filled, or "auto" resolving on over a multi-slice
        # mesh) refuses segmented — the fill must not produce it.
        shuffle_eff = cfg.structural.get("shuffle",
                                         user_opts.get("shuffle"))
        dcn_knob = cfg.structural.get(
            "dcn_codec", user_opts.get("dcn_codec", "auto")) or "auto"
        from distributed_join_tpu.planning.cost import (
            DCN_CODEC_KNOBS,
            resolve_dcn_codec,
        )

        hier_codec_armed = (
            shuffle_eff == "hierarchical"
            and ((side_geometry or {}).get("n_slices") or 1) > 1
            # An invalid knob is the join's loud error, not the
            # tuner's — treat it as armed (conservative: no fill).
            and (dcn_knob not in DCN_CODEC_KNOBS
                 or resolve_dcn_codec(dcn_knob)))
        if ("sort_mode" not in user_opts
                and shuffle_eff != "ragged"
                and not hier_codec_armed
                and user_opts.get("compression_bits") is None
                and "compression_bits" not in cfg.sizing
                and user_opts.get("aggregate") is None
                and user_opts.get("kernel_config") is None
                and side_geometry):
            share = self._join_stage_share(trend.stages_last)
            if share is not None and share > SORT_STAGE_SHARE_WARN:
                from distributed_join_tpu.ops.segmented import (
                    resolve_sort_segments,
                )

                n_ranks = int(side_geometry.get("n_ranks") or 1)
                nb = int(side_geometry.get("nb") or n_ranks)
                factor = float(
                    (trend.resolved_knobs_last or {}).get(
                        "shuffle_capacity_factor")
                    or user_opts.get("shuffle_capacity_factor")
                    or _static_defaults()["shuffle_capacity_factor"])
                segs = resolve_sort_segments(
                    user_opts.get("sort_segments"),
                    max(side_geometry.get("b_local") or 0,
                        side_geometry.get("p_local") or 0),
                    n_ranks, max(nb // max(n_ranks, 1), 1), factor)
                if segs > 1:
                    cfg.structural["sort_mode"] = "segmented"
                    cfg.source = "history"
                    cfg.basis["sort_mode"] = {
                        "join_stage_share": round(share, 4),
                        "warn": SORT_STAGE_SHARE_WARN,
                        "segments": segs}
        if cfg.source == "history":
            self.history_hits += 1
        return cfg

    def resolve(self, comm, build, probe, *, key="key",
                with_integrity: bool = False,
                opts: Optional[dict] = None) -> TunedConfig:
        """The full library-path resolution
        (``distributed_inner_join(tuner=)``): compute the workload
        signature from the call exactly as the service's history
        entries are keyed, derive the shape geometry for the
        shape-dependent policies, and return the verdict."""
        opts = dict(opts or {})
        wm = opts.pop("with_metrics", None)
        wi = opts.pop("with_integrity", with_integrity)
        sig = workload_signature(comm, build, probe, key=key,
                                 with_metrics=wm, with_integrity=wi,
                                 **opts)
        n = comm.n_ranks
        k = int(opts.get("over_decomposition") or 1)
        geometry = {
            "nb": n * k,
            "n_ranks": n,
            "n_slices": int(getattr(comm, "n_slices", 1)),
            "b_local": _round_up(build.capacity, n) // n,
            "p_local": _round_up(probe.capacity, n) // n,
            "row_bytes": {
                "build": _fixed_row_bytes(build),
                "probe": _fixed_row_bytes(probe),
            },
        }
        return self.recommend(sig, user_opts=opts,
                              side_geometry=geometry)

    def resolve_resident(self, comm, resident_rows_per_rank: int,
                         probe, *, signature: str,
                         opts: Optional[dict] = None) -> TunedConfig:
        """The PROBE-ONLY verdict (resident build tables,
        ``service/resident.py``): sizing knobs and the rung label
        only. The build side has no per-request sizing — its image is
        fixed at registration — and structural fills never apply (the
        skew sidecar is not part of the probe-only program, and the
        wire mode was chosen when the probe workload was shaped), so
        any structural recommendation the trend would make is
        dropped. ``signature`` is the registry's GENERATION-FREE
        workload identity, so the sizing history survives delta
        merges."""
        opts = dict(opts or {})
        n = comm.n_ranks
        k = int(opts.get("over_decomposition") or 1)
        geometry = {
            "nb": n * k,
            "n_ranks": n,
            # The "build" margin slot maps to the resident image —
            # its overflow margin never appears in probe-only
            # indicators, so only the probe clauses can fire.
            "b_local": int(resident_rows_per_rank),
            "p_local": _round_up(probe.capacity, n) // n,
            "row_bytes": {
                "build": None,
                "probe": _fixed_row_bytes(probe),
            },
        }
        cfg = self.recommend(signature, user_opts=opts,
                             side_geometry=geometry)
        if cfg.structural:
            cfg.basis["structural_dropped"] = dict(cfg.structural)
            cfg.structural = {}
        return cfg

    # -- policy helpers ------------------------------------------------

    @staticmethod
    def _join_stage_share(stages_last):
        """Join-stage fraction of the summed per-stage walls from the
        signature's latest stages block (``history.stages_block``
        shape: {"wall_s": {stage: seconds}}), or None without
        stage-profiled evidence."""
        walls = ((stages_last or {}).get("wall_s") or {})
        join_w = walls.get("join")
        total = sum(v for v in walls.values() if v)
        if not join_w or total <= 0:
            return None
        return float(join_w) / float(total)

    @staticmethod
    def _worst_gini(indicators):
        worst = None
        for name, d in (indicators or {}).items():
            if not isinstance(d, dict) or "gini" not in d:
                continue
            if worst is None or d["gini"] > worst[1]:
                worst = (name, d["gini"])
        return worst

    def _headroom_bump(self, trend, user_opts: dict,
                       geometry: dict):
        """(new_factor, basis) when any side's recorded minimum
        overflow margin is within ``headroom_ratio_warn`` of its
        per-bucket capacity — the pre-emptive half of capacity tuning
        (the reactive half is rung adoption)."""
        ind = trend.indicators_last or {}
        factor = float(
            (trend.resolved_knobs_last or {}).get(
                "shuffle_capacity_factor")
            or user_opts.get("shuffle_capacity_factor")
            or _static_defaults()["shuffle_capacity_factor"])
        nb = geometry["nb"]
        if nb <= 1:
            return None
        tight = None
        for side, local in (("build", geometry["b_local"]),
                            ("probe", geometry["p_local"])):
            margin = ind.get(f"{side}.overflow_margin_min")
            if margin is None:
                continue
            cap = _round_up(
                int(math.ceil(local / nb * factor)), 8)
            if cap <= 0:
                continue
            ratio = margin / cap
            if 0 <= ratio < self.headroom_ratio_warn:
                if tight is None or ratio < tight["ratio"]:
                    tight = {"side": side, "margin_rows": int(margin),
                             "capacity_rows": cap,
                             "ratio": round(ratio, 4)}
        if tight is None:
            return None
        new_factor = round(factor * HEADROOM_BUMP, 6)
        tight["factor"] = {"from": factor, "to": new_factor}
        return new_factor, tight

    @staticmethod
    def _dcn_share(counters):
        """(dcn share of wire bytes, codec_was_on) from the last
        recorded per-tier counters — present only for hierarchical
        runs (``wire_bytes_ici``/``wire_bytes_dcn``,
        shuffle.shuffle_hierarchical). None when the workload never
        ran hierarchically."""
        if not counters:
            return None
        dcn = sum(counters.get(f"{s}.wire_bytes_dcn") or 0
                  for s in ("build", "probe"))
        total = sum(counters.get(f"{s}.wire_bytes") or 0
                    for s in ("build", "probe"))
        if not dcn or not total:
            return None
        # codec_was_on is inferred from savings, not from the knob
        # (counters don't carry it): a codec-on run whose columns are
        # ALL codec-ineligible saves 0 bytes and reads as "off" — the
        # resulting 'on' recommendation is a no-op there (structural
        # knobs fill only UNSET ones and nothing is compressible), so
        # the misread costs accounting noise, never wrong routing.
        saved = sum(counters.get(f"{s}.wire_bytes_saved") or 0
                    for s in ("build", "probe"))
        return round(dcn / total, 4), saved > 0

    def _wire_efficiency(self, counters, geometry: dict):
        """(side, efficiency) of the worst side from the last
        recorded device counters: actual payload bytes over wire
        bytes (fixed-width schema estimate — the same basis as
        ``analyze``'s wire-efficiency indicator)."""
        if not counters:
            return None
        worst = None
        for side in ("build", "probe"):
            wire = counters.get(f"{side}.wire_bytes")
            rows = counters.get(f"{side}.rows_shuffled")
            row_bytes = geometry["row_bytes"].get(side)
            if not wire or not rows or not row_bytes:
                continue
            eff = round((rows * row_bytes) / wire, 4)
            if worst is None or eff < worst[1]:
                worst = (side, eff)
        return worst

    # -- the dry run (analyze tune) ------------------------------------

    def dry_run(self, signature: Optional[str] = None) -> dict:
        """The ``analyze tune`` record: every known signature's (or
        one signature's) verdict plus the knob delta vs the static
        defaults — what a tuned run WOULD change, with evidence,
        executing nothing."""
        statics = _static_defaults()
        sigs = ([signature] if signature
                else sorted(self._trends))
        out: dict = {}
        for sig in sigs:
            cfg = self.recommend(sig)
            trend = self._trends.get(sig)
            t = trend.as_dict() if trend is not None else None
            knobs = {**cfg.structural, **cfg.sizing}
            out[sig] = {
                "source": cfg.source,
                "rung": cfg.rung,
                "knobs": knobs,
                "delta": {
                    k: {"static": statics.get(k), "tuned": v}
                    for k, v in sorted(knobs.items())
                    if statics.get(k) != v
                },
                "basis": cfg.basis,
                "trend": {
                    "entries": t["entries"],
                    "outcomes": t["outcomes"],
                    "escalations": t["escalations"],
                    "counter_drift": t["counter_drift"],
                } if t else None,
            }
        return {
            "schema_version": TUNER_SCHEMA_VERSION,
            "kind": "tune",
            "history": self.path,
            "n_signatures": len(out),
            "signatures": out,
        }


def _fixed_row_bytes(table) -> Optional[int]:
    """Fixed-width bytes/row over a Table's columns (length-companion
    columns excluded — they describe, not ship). A shape-level
    estimate for the wire-efficiency policy, not the exact wire
    schema (string-key packing happens downstream)."""
    import numpy as np

    total = 0
    try:
        for name, c in table.columns.items():
            if name.endswith("#len"):
                continue
            trailing = 1
            for d in c.shape[1:]:
                trailing *= int(d)
            total += np.dtype(c.dtype).itemsize * trailing
    except Exception:
        return None
    return total or None


def _static_defaults() -> dict:
    """The knob values a tuner-off run resolves to (the "static plan"
    column of ``analyze tune``'s delta)."""
    from distributed_join_tpu.parallel.distributed_join import (
        DEFAULT_OUT_CAPACITY_FACTOR,
        DEFAULT_SHUFFLE_CAPACITY_FACTOR,
    )

    return {
        "shuffle_capacity_factor": DEFAULT_SHUFFLE_CAPACITY_FACTOR,
        "out_capacity_factor": DEFAULT_OUT_CAPACITY_FACTOR,
        "out_rows_per_rank": None,
        "compression_bits": None,
        "hh_build_capacity": None,
        "hh_probe_capacity": None,
        "hh_out_capacity": None,
        "shuffle": "padded",
        "skew_threshold": None,
        "dcn_codec": "auto",
    }


def format_tune(record: dict) -> str:
    """Human rendering of :meth:`JoinTuner.dry_run` (the ``analyze
    tune`` default output)."""
    lines = [f"tune: {record['n_signatures']} signature(s)"
             + (f"  [{record['history']}]" if record.get("history")
                else "")]
    for sig, v in record["signatures"].items():
        trend = v.get("trend") or {}
        lines.append(
            f"  {sig}: {v['source']}"
            + (f" (rung {v['rung']})" if v["rung"] else "")
            + (f"  [{trend.get('entries', 0)} run(s), "
               f"{trend.get('escalations', 0)} escalation(s)]"
               if trend else ""))
        for k, d in (v.get("delta") or {}).items():
            lines.append(f"    {k}: {d['static']} -> {d['tuned']}")
        basis = v.get("basis") or {}
        note = basis.get("note")
        if note:
            lines.append(f"    note: {note}")
        for kind in ("adopted_rung", "headroom", "skew", "wire"):
            if kind in basis:
                lines.append(f"    evidence[{kind}]: "
                             f"{json.dumps(basis[kind], sort_keys=True)}")
        if not v.get("delta") and not note:
            lines.append("    no knob changes vs the static plan")
    return "\n".join(lines)
