"""EXPLAIN for the distributed join: the plan, before any execution.

``distributed_inner_join`` resolves a dozen knobs per query — shuffle
mode, over-decomposition, the full capacity contract (every ladder
rung's sizing), skew policy, compression, telemetry/integrity switches
— but until now none of that was visible before a run had already paid
trace + compile. A :class:`JoinPlan` materializes the whole resolution
as a structured, inspectable record from nothing but table SHAPES and
options: per-bucket capacities, per-rank and total wire bytes, the HBM
footprint, the skew sidecar sizing, and the canonical program identity.

Two agreement contracts, both load-bearing:

- **Plan == cache key.** The plan's identity is
  :class:`~..service.programs.JoinSignature` — the SAME canonical
  resolution the serving program cache keys executables under — so an
  EXPLAIN's digest and the executable a run would dispatch can never
  disagree (tests/test_explain.py locks digest equality against a
  real cached run).
- **Padded wire bytes are exact.** The padded (and compressed) shuffle
  moves static-shaped blocks, so the predicted ``wire_bytes`` equals
  the measured device counter (``build.wire_bytes`` /
  ``probe.wire_bytes`` in the :class:`~..telemetry.metrics.Metrics`
  block) to the byte — a hard CI gate (``analyze explain
  --gate-wire-bytes``), not a dashboard estimate. Ragged mode ships
  actual rows, so its byte prediction is an upper-bound estimate and
  says so (``wire.exact = false``).

Everything here is host arithmetic over shapes: building a plan
traces nothing, compiles nothing, and touches no device — the
admission-free ``explain`` wire op of the join service dry-runs a
query spec through exactly this path.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Optional

from distributed_join_tpu.planning.cost import (
    CostModel,
    predict,
    predict_exchange,
)

EXPLAIN_SCHEMA_VERSION = 1

_DTYPE_BYTES = {
    "bool": 1, "int8": 1, "uint8": 1, "int16": 2, "uint16": 2,
    "int32": 4, "uint32": 4, "float32": 4, "int64": 8, "uint64": 8,
    "float64": 8,
}


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _itemsize(dtype: str) -> int:
    try:
        return _DTYPE_BYTES[dtype]
    except KeyError:
        raise ValueError(f"unknown dtype {dtype!r} in plan schema")


def _row_bytes(columns) -> int:
    """Fixed-width wire bytes per row over (name, dtype, trailing)."""
    total = 0
    for _, dtype, trailing in columns:
        total += _itemsize(dtype) * math.prod(trailing or (1,))
    return total


@dataclasses.dataclass(frozen=True)
class SidePlan:
    """One side's shape story: the columns that actually ride the
    partition + shuffle (post string-key packing), global/local rows,
    and the fixed row width on the wire."""

    rows_global: int
    rows_local: int
    columns: tuple            # ((name, dtype, trailing), ...) sorted
    varwidth: tuple           # byte-exact-eligible names (ragged mode)
    row_bytes: int            # fixed-width bytes/row incl. varwidth
    row_bytes_fixed: int      # bytes/row excluding varwidth columns

    def as_record(self) -> dict:
        return {
            "rows_global": self.rows_global,
            "rows_local": self.rows_local,
            "columns": [list(c) for c in self.columns],
            "varwidth": list(self.varwidth),
            "row_bytes": self.row_bytes,
            "row_bytes_fixed": self.row_bytes_fixed,
        }


@dataclasses.dataclass(frozen=True)
class JoinPlan:
    """The fully-resolved, pre-execution description of one join
    program. ``digest`` is the program-cache signature digest — the
    plan IS the cache key, rendered human-readable."""

    digest: str
    n_ranks: int
    over_decomposition: int
    key: tuple
    shuffle: str
    compression_bits: Optional[int]
    with_metrics: bool
    with_integrity: bool
    build: SidePlan
    probe: SidePlan
    capacities: dict
    skew: Optional[dict]
    wire: dict
    memory: dict
    resolved_options: dict
    cost: dict
    # Probe-only plans (resident build tables, service/resident.py):
    # the build side is a registered on-device image — zero build
    # wire bytes, no build partition work — and `pipeline` says so.
    pipeline: str = "join"
    probe_only: bool = False
    # Aggregation pushdown (docs/AGGREGATION.md): the fused
    # join+aggregate pipeline (``pipeline == "join_agg"``) — the spec,
    # the resolved mode ("key"/"probe"), and the per-rank partial-
    # groups capacity. The wire story changes with it: the build/probe
    # sides ship ONLY the columns the reduction reads, there are zero
    # materialization gathers, and probe mode adds the groups-sized
    # ``wire["partials"]`` exchange (exact in padded mode, gated like
    # the sides).
    aggregate: Optional[dict] = None
    # Slow-tier topology (hierarchical shuffle, docs/HIERARCHY.md):
    # slices of the communicator's mesh; 1 = flat. Mirrored from the
    # signature so plan digest == cache key holds for hierarchical
    # programs too.
    n_slices: int = 1

    @property
    def n_buckets(self) -> int:
        return self.n_ranks * self.over_decomposition

    def as_record(self) -> dict:
        return {
            "pipeline": self.pipeline,
            "probe_only": self.probe_only,
            "aggregate": self.aggregate,
            "signature_digest": self.digest,
            "n_ranks": self.n_ranks,
            "n_slices": self.n_slices,
            "over_decomposition": self.over_decomposition,
            "n_buckets": self.n_buckets,
            "key": list(self.key),
            "shuffle": self.shuffle,
            "compression_bits": self.compression_bits,
            "with_metrics": self.with_metrics,
            "with_integrity": self.with_integrity,
            "build": self.build.as_record(),
            "probe": self.probe.as_record(),
            "capacities": dict(self.capacities),
            "skew": self.skew,
            "wire": self.wire,
            "memory": self.memory,
            "resolved_options": self.resolved_options,
        }

    def explain_record(self) -> dict:
        """The ``explain.json`` artifact body — deliberately free of
        timestamps so the same query spec yields byte-identical
        output (the determinism gate)."""
        return {
            "schema_version": EXPLAIN_SCHEMA_VERSION,
            "kind": "explain",
            "plan": self.as_record(),
            "cost": self.cost,
        }

    def format(self) -> str:
        """Human rendering (the drivers' --explain stderr line set)."""
        c = self.capacities
        w = self.wire
        lines = [
            f"plan {self.digest[:16]}: {self.shuffle} shuffle, "
            f"{self.n_ranks} rank(s) x k={self.over_decomposition}"
            + (f", compression_bits={self.compression_bits}"
               if self.compression_bits is not None else ""),
            f"  build {self.build.rows_global} rows "
            f"({self.build.row_bytes} B/row) | probe "
            f"{self.probe.rows_global} rows "
            f"({self.probe.row_bytes} B/row)",
            f"  capacities: shuffle {c['shuffle_build_per_bucket']}/"
            f"{c['shuffle_probe_per_bucket']} rows/bucket, out "
            f"{c['out_rows_per_batch']} rows/batch",
            f"  wire: build {w['build']['bytes_total']} B, probe "
            f"{w['probe']['bytes_total']} B "
            f"({'EXACT' if w['exact'] else 'estimate'})",
            f"  memory/rank: {self.memory['total_per_rank_bytes']} B"
            + ("" if self.memory["fits_hbm"] else
               "  [EXCEEDS v5e HBM]"),
            f"  predicted: {self.cost['total_s']}s "
            f"({self.cost['predicted_m_rows_per_sec_per_rank']} "
            "M rows/s/rank, v5e roofline)",
        ]
        if self.skew is not None:
            lines.insert(3, f"  skew: threshold="
                            f"{self.skew['threshold']}, hh "
                            f"{c.get('hh_build')}/{c.get('hh_probe')}/"
                            f"{c.get('hh_out')}")
        return "\n".join(lines)


# -- schema resolution (the host-side mirror of the step's key prep) --


def _schema_cols(table) -> dict:
    """{name: (dtype_str, trailing_shape)} of a Table (or a Table of
    ShapeDtypeStructs) — shape metadata only, no data touch."""
    return {
        name: (str(c.dtype), tuple(int(d) for d in c.shape[1:]))
        for name, c in table.columns.items()
    }


def _wire_schemas(build, probe, keys, build_payload, probe_payload):
    """The columns each side actually partitions + shuffles, after
    ``utils.strings.prepare_string_key_join``'s packing — mirrored at
    the SHAPE level so planning never executes the packing itself.
    Returns (build_cols, probe_cols, keys_eff) with cols as sorted
    ((name, dtype, trailing), ...) tuples."""
    from distributed_join_tpu.utils.strings import (
        LEN_SUFFIX,
        string_key_word_names,
    )

    bcols = _schema_cols(build)
    pcols = _schema_cols(probe)
    str_keys = [k for k in keys if len(bcols[k][1]) == 1]
    if not str_keys:
        return (_sorted_cols(bcols), _sorted_cols(pcols), tuple(keys))
    drop = {k + LEN_SUFFIX for k in str_keys}
    if build_payload is None:
        build_payload = [n for n in bcols
                         if n not in keys and n not in drop]
    keys_eff = []
    for i, k in enumerate(keys):
        dtype, trailing = bcols[k]
        if len(trailing) != 1:
            keys_eff.append(k)
            continue
        # split_string_keys: the 2-D uint8 key becomes big-endian
        # uint64 word columns on BOTH sides; byte column dropped.
        n_words = (trailing[0] + 7) // 8
        word_names = string_key_word_names(i, n_words)
        for nm in word_names:
            bcols[nm] = ("uint64", ())
            pcols[nm] = ("uint64", ())
        del bcols[k], pcols[k]
        keys_eff.extend(word_names)
    keep_b = set(keys_eff) | set(build_payload)
    bcols = {n: v for n, v in bcols.items() if n in keep_b}
    return (_sorted_cols(bcols), _sorted_cols(pcols), tuple(keys_eff))


def _sorted_cols(cols: dict) -> tuple:
    return tuple(sorted(
        (name, dtype, trailing)
        for name, (dtype, trailing) in cols.items()
    ))


def _varwidth_names(columns) -> tuple:
    """Mirror of distributed_join._varwidth_cols over plan schema:
    2-D uint8 columns with a 4-aligned width and a '#len' companion
    (the byte-exact ragged wire's eligibility rule)."""
    names = {name for name, _, _ in columns}
    return tuple(
        name for name, dtype, trailing in columns
        if dtype == "uint8" and len(trailing) == 1
        and trailing[0] % 4 == 0 and name + "#len" in names
    )


# -- wire-byte prediction ---------------------------------------------


_COMPRESSION_BLOCK = 256   # shuffle_padded_compressed's block default


def _padded_side_bytes(n: int, k: int, cap: int, columns,
                       compression_bits: Optional[int]):
    """Per-rank wire bytes for one side across all k batches of the
    padded/ppermute shuffle — EXACTLY what the shuffle's MetricsTape
    bills (``shuffle_padded``/``shuffle_padded_compressed``): the full
    static (n, cap) block per column, pad included. Returns
    (sent_bytes_per_rank, raw_bytes_per_rank)."""
    raw = sent = 0
    for name, dtype, trailing in columns:
        isz = _itemsize(dtype)
        col_bytes = n * cap * isz * math.prod(trailing or (1,))
        raw += col_bytes
        if compression_bits is None:
            sent += col_bytes
            continue
        if not _codec_eligible_col(name, dtype, trailing):
            sent += col_bytes
            continue
        # for_bitpack_encode on each destination's cap-length row:
        # word plane = n_pad * bits/8 bytes, frame plane = one int64
        # per block (ops/compression.py; block=256 on this path).
        n_pad = _round_up(max(cap, 1), _COMPRESSION_BLOCK)
        per_dest = (n_pad * compression_bits // 8
                    + (n_pad // _COMPRESSION_BLOCK) * 8)
        sent += n * per_dest
    return k * sent, k * raw


def _hier_side_bytes(n: int, n_slices: int, k: int, cap: int, columns,
                     dcn_bits: Optional[int]):
    """Per-rank per-tier wire bytes for one side of the hierarchical
    shuffle across all k batches — EXACTLY what
    ``shuffle_hierarchical``'s tape bills: phase 1 (ICI) moves the
    full static ``n x cap`` block per column; phase 2 (DCN) moves the
    same block raw, or the codec's word+frame planes (one frame
    stream per destination SLICE, rows flattened chip-major — so the
    packing unit is ``chips_per_slice * cap`` rows padded to the
    codec block). Returns ``(ici, dcn_sent, dcn_raw)`` per rank."""
    chips = n // n_slices
    ici = dcn_raw = dcn_sent = 0
    for name, dtype, trailing in columns:
        isz = _itemsize(dtype)
        col_bytes = n * cap * isz * math.prod(trailing or (1,))
        ici += col_bytes
        dcn_raw += col_bytes
        compressible = (dcn_bits is not None
                        and _codec_eligible_col(name, dtype, trailing))
        if not compressible:
            dcn_sent += col_bytes
            continue
        n_pad = _round_up(max(chips * cap, 1), _COMPRESSION_BLOCK)
        per_slice = (n_pad * dcn_bits // 8
                     + (n_pad // _COMPRESSION_BLOCK) * 8)
        dcn_sent += n_slices * per_slice
    return k * ici, k * dcn_sent, k * dcn_raw


def _predict_wire(n: int, k: int, shuffle: str,
                  compression_bits: Optional[int],
                  build: SidePlan, probe: SidePlan,
                  b_cap: int, p_cap: int, n_slices: int = 1,
                  dcn_codec_on: bool = False) -> dict:
    single = n * k == 1
    if single:
        zero = {"bytes_per_rank": 0, "bytes_total": 0,
                "rows_estimate": 0}
        return {"exact": True, "build": dict(zero),
                "probe": dict(zero), "collectives_per_step": 0}
    hier = shuffle == "hierarchical" and n_slices > 1
    if shuffle == "hierarchical" and not hier:
        # Degenerate one-slice hierarchy: the runtime routes the flat
        # RAW padded path (_batch_shuffle ignores any armed/explicit
        # bits — there is no cross-slice payload to compress), so the
        # exact-contract wire prediction must bill raw padded too.
        compression_bits = None
    sides = {}
    exact = shuffle in ("padded", "ppermute", "hierarchical")
    for side, cap in (("build", b_cap), ("probe", p_cap)):
        sp = build if side == "build" else probe
        if hier:
            from distributed_join_tpu.parallel.distributed_join import (
                DEFAULT_DCN_CODEC_BITS,
            )

            dcn_bits = ((compression_bits or DEFAULT_DCN_CODEC_BITS)
                        if dcn_codec_on else None)
            ici, dcn, dcn_raw = _hier_side_bytes(
                n, n_slices, k, cap, sp.columns, dcn_bits)
            sides[side] = {
                "bytes_per_rank": int(ici + dcn),
                "bytes_total": int(ici + dcn) * n,
                "rows_estimate": sp.rows_local * n,
                "ici_bytes_per_rank": int(ici),
                "dcn_bytes_per_rank": int(dcn),
            }
            if dcn_bits is not None:
                sides[side]["dcn_raw_bytes_per_rank"] = int(dcn_raw)
            continue
        if shuffle == "ragged":
            # Exact-size exchange: fixed-width bytes for actual rows
            # (assume every row valid — an upper bound on a masked
            # table) plus the varwidth planes at full width (upper
            # bound; real lengths only exist at run time).
            vw_bytes = sp.row_bytes - sp.row_bytes_fixed
            per_rank = sp.rows_local * sp.row_bytes_fixed \
                + sp.rows_local * vw_bytes
            raw = per_rank
        else:
            per_rank, raw = _padded_side_bytes(
                n, k, cap, sp.columns, compression_bits)
        sides[side] = {
            "bytes_per_rank": int(per_rank),
            "bytes_total": int(per_rank) * n,
            "rows_estimate": sp.rows_local * n,
        }
        if compression_bits is not None:
            sides[side]["raw_bytes_per_rank"] = int(raw)
    # Data-plane collectives per compiled step: per batch per side one
    # count exchange + one collective per column (compressed integer
    # columns ride as two planes). Hierarchical: the count exchange
    # and every raw column ride TWO hops (chip + slice); a
    # codec-eligible column rides chip raw then two codec planes over
    # the slice axis (three collectives).
    coll = 0
    for sp, side in ((build, "build"), (probe, "probe")):
        if hier:
            per_side = 2
            for name, dtype, trailing in sp.columns:
                eligible = (dcn_codec_on and
                            _codec_eligible_col(name, dtype, trailing))
                per_side += 3 if eligible else 2
            coll += k * per_side
            continue
        per_col = 2 if compression_bits is not None else 1
        coll += k * (1 + per_col * len(sp.columns))
    return {"exact": exact, "build": sides["build"],
            "probe": sides["probe"], "collectives_per_step": coll}


def _codec_eligible_col(name: str, dtype, trailing) -> bool:
    """THE shape-level mirror of ``shuffle._codec_eligible`` (one
    rule, three wire-accounting call sites): the FoR+bitpack codec
    admits scalar integer columns that are not string word planes
    (those carry their own prefix framing)."""
    from distributed_join_tpu.utils.strings import _WORD_PREFIX

    return (not trailing
            and dtype in ("int32", "uint32", "int64", "uint64")
            and not name.startswith(_WORD_PREFIX))


# -- the builder ------------------------------------------------------


def build_plan(comm, build, probe, key="key", with_metrics=None,
               cost_model: Optional[CostModel] = None,
               **opts) -> JoinPlan:
    """Materialize the :class:`JoinPlan` for exactly the program
    ``make_join_step(comm, key=key, **opts)`` would compile over these
    tables — without tracing or compiling anything.

    ``build``/``probe`` are Tables (real arrays or ShapeDtypeStructs
    — only shapes/dtypes are read). ``with_metrics=None`` resolves
    from the telemetry session exactly as ``make_distributed_join``
    and the program cache do, so the plan digest equals the cache key
    of the run it predicts. Unknown options raise the same loud
    TypeError the signature layer raises.
    """
    from distributed_join_tpu import telemetry
    from distributed_join_tpu.service.programs import JoinSignature

    if with_metrics is None:
        with_metrics = telemetry.enabled()
    keys = [key] if isinstance(key, str) else list(key)
    sig = JoinSignature.of(comm, build, probe, key=key,
                           with_metrics=with_metrics, **opts)
    resolved = dict(sig.options)

    from distributed_join_tpu.parallel.distributed_join import (
        SHUFFLE_MODES,
    )
    from distributed_join_tpu.planning.cost import resolve_dcn_codec

    n = sig.n_ranks
    n_slices = sig.n_slices
    k = int(resolved.get("over_decomposition") or 1)
    nb = n * k
    shuffle = resolved.get("shuffle") or "padded"
    comp_bits = resolved.get("compression_bits")
    # A plan must describe a program that could compile — mirror
    # make_join_step's option validation so an EXPLAIN of a config
    # the join would reject is the same loud error, not a plausible-
    # looking plan for nothing.
    if k < 1:
        raise ValueError("over_decomposition must be >= 1")
    if shuffle not in SHUFFLE_MODES:
        raise ValueError(f"unknown shuffle mode {shuffle!r}")
    if comp_bits is not None and shuffle == "ragged":
        raise ValueError(
            "compression applies to the padded/ppermute shuffles; the "
            "ragged exchange already sends exact rows (combining the "
            "two is unimplemented)"
        )
    sort_mode = resolved.get("sort_mode") or "flat"
    sort_segments = resolved.get("sort_segments")
    from distributed_join_tpu.parallel.distributed_join import (
        SORT_MODES,
    )

    if sort_mode not in SORT_MODES:
        raise ValueError(
            f"unknown sort_mode {sort_mode!r}; pick one of {SORT_MODES}")
    if sort_mode == "flat" and sort_segments is not None:
        raise ValueError(
            "sort_segments applies to sort_mode='segmented' only — "
            "drop the knob or pass sort_mode='segmented'")
    dcn_knob = resolved.get("dcn_codec") or "auto"
    if shuffle == "hierarchical":
        if comp_bits is not None and dcn_knob == "off":
            raise ValueError(
                "dcn_codec='off' contradicts compression_bits="
                f"{comp_bits} (hierarchical mode compresses only the "
                "cross-slice tier)")
        dcn_on = resolve_dcn_codec(dcn_knob)
    else:
        resolve_dcn_codec(dcn_knob)
        dcn_on = False
        if n > 1 and n_slices > 1:
            raise ValueError(
                f"shuffle {shuffle!r} routes one GLOBAL collective "
                "over a multi-slice mesh, dragging intra-slice "
                "traffic across DCN — use shuffle='hierarchical' "
                "(or a flat 1-D communicator)")
    if sort_mode == "segmented":
        # Mirror make_join_step's refusal matrix — an EXPLAIN of a
        # config the step would reject must be the same loud error.
        if shuffle == "ragged":
            raise ValueError(
                "sort_mode='segmented' needs static per-(source, "
                "segment) receive boundaries; the ragged exchange "
                "packs exact-size blocks whose boundaries only exist "
                "at run time — use shuffle='padded'/'ppermute' (or "
                "sort_mode='flat')")
        if comp_bits is not None:
            raise ValueError(
                "sort_mode='segmented' does not combine with the "
                "compressed wire: the codec's per-destination frame "
                "streams assume one valid prefix per block — drop "
                "compression_bits (or use sort_mode='flat')")
        if shuffle == "hierarchical" and dcn_on and n_slices > 1:
            raise ValueError(
                "sort_mode='segmented' does not combine with the "
                "hierarchical DCN codec (same per-block framing "
                "problem as compression_bits) — pass dcn_codec='off' "
                "(or sort_mode='flat')")
        if resolved.get("kernel_config") is not None:
            raise ValueError(
                "sort_mode='segmented' ignores kernel_config — drop "
                "the knob")
    shuffle_f = float(resolved["shuffle_capacity_factor"])
    out_f = float(resolved["out_capacity_factor"])
    out_rows = resolved.get("out_rows_per_rank")

    b_global, p_global = sig.build_capacity, sig.probe_capacity
    b_local, p_local = b_global // n, p_global // n

    wb, wp, keys_eff = _wire_schemas(
        build, probe, keys,
        resolved.get("build_payload"), resolved.get("probe_payload"))
    # Aggregation pushdown (docs/AGGREGATION.md): validate the spec
    # against the SAME schema contract the step enforces, then restrict
    # each side's wire schema to exactly the columns the fused
    # reduction reads — the plan's padded wire bytes stay exact vs the
    # device counters because the step shuffles exactly these columns.
    agg_spec = opts.get("aggregate")
    agg_mode = None
    agg_schemas = None
    if agg_spec is not None:
        from distributed_join_tpu.ops import aggregate as agg_ops

        if sort_mode == "segmented":
            raise agg_ops.AggregatePushdownUnsupported(
                "aggregate pushdown unsupported under "
                "sort_mode='segmented': the fused reduction rides "
                "the flat pipeline's own sorts — run aggregates with "
                "sort_mode='flat'")
        if resolved.get("skew_threshold") is not None:
            raise agg_ops.AggregatePushdownUnsupported(
                "aggregate pushdown unsupported: the skew sidecar is "
                "not part of the fused pipeline")
        if resolved.get("build_payload") or resolved.get(
                "probe_payload"):
            raise agg_ops.AggregatePushdownUnsupported(
                "aggregate pushdown unsupported: explicit payload "
                "lists conflict with the pushdown's wire-column "
                "resolution")
        bcols0, pcols0 = _schema_cols(build), _schema_cols(probe)
        for kname in keys:
            if bcols0[kname][1]:
                raise agg_ops.AggregatePushdownUnsupported(
                    f"aggregate pushdown unsupported: join key "
                    f"{kname!r} is a 2-D (string) column")
        bsch = {name: (dtype, 1 + len(tr)) for name, dtype, tr in wb}
        psch = {name: (dtype, 1 + len(tr)) for name, dtype, tr in wp}
        agg_mode = agg_ops.resolve_agg_mode(agg_spec, keys_eff, bsch,
                                            psch)
        agg_schemas = (bsch, psch)
        need_b, need_p = agg_ops.wire_columns(
            agg_spec, agg_mode, keys_eff, bsch, psch)
        wb = tuple(c for c in wb if c[0] in set(need_b))
        wp = tuple(c for c in wp if c[0] in set(need_p))
    vb = _varwidth_names(wb) if shuffle == "ragged" else ()
    vp = _varwidth_names(wp) if shuffle == "ragged" else ()
    side_b = SidePlan(
        rows_global=b_global, rows_local=b_local, columns=wb,
        varwidth=vb, row_bytes=_row_bytes(wb),
        row_bytes_fixed=_row_bytes(
            [c for c in wb if c[0] not in vb]),
    )
    side_p = SidePlan(
        rows_global=p_global, rows_local=p_local, columns=wp,
        varwidth=vp, row_bytes=_row_bytes(wp),
        row_bytes_fixed=_row_bytes(
            [c for c in wp if c[0] not in vp]),
    )

    # Capacity arithmetic, verbatim from make_join_step (float order
    # included — the exact-gate depends on it). The segmented-sort
    # path resolves ONE level down through the shared owners in
    # ops/segmented.py: per-fine-bucket capacities and per-segment
    # output blocks, with the per-bucket keys carrying the EFFECTIVE
    # wire block (segments x per-segment capacity) so the wire/memory
    # accounting below reads them unchanged.
    seg = 1
    if sort_mode == "segmented" and nb > 1:
        from distributed_join_tpu.ops.segmented import (
            resolve_sort_segments,
            segment_capacity,
            segmented_out_capacity,
        )

        seg = resolve_sort_segments(sort_segments,
                                    max(b_local, p_local), n, k,
                                    shuffle_f)
    if seg > 1:
        b_cap_seg = segment_capacity(b_local, n, k, seg, shuffle_f)
        p_cap_seg = segment_capacity(p_local, n, k, seg, shuffle_f)
        out_cap_seg = segmented_out_capacity(p_local, k, seg, out_f,
                                             out_rows)
        b_cap = seg * b_cap_seg
        p_cap = seg * p_cap_seg
        out_cap = seg * out_cap_seg
    else:
        b_cap = _round_up(int(math.ceil(b_local / nb * shuffle_f)), 8)
        p_cap = _round_up(int(math.ceil(p_local / nb * shuffle_f)), 8)
        if out_rows is not None:
            out_cap = _round_up(int(math.ceil(int(out_rows) / k)), 8)
        else:
            out_cap = _round_up(int(math.ceil(p_local / k * out_f)), 8)
    capacities = {
        "shuffle_build_per_bucket": b_cap,
        "shuffle_probe_per_bucket": p_cap,
        "out_rows_per_batch": out_cap,
        "shuffle_capacity_factor": shuffle_f,
        "out_capacity_factor": out_f,
        "out_rows_per_rank": out_rows,
    }
    if seg > 1:
        capacities.update(
            sort_segments=seg,
            shuffle_build_per_segment=b_cap_seg,
            shuffle_probe_per_segment=p_cap_seg,
            out_rows_per_segment=out_cap_seg,
        )

    skew = None
    if resolved.get("skew_threshold") is not None:
        hh_slots = int(resolved.get("hh_slots") or 64)
        hh_build = resolved.get("hh_build_capacity") or hh_slots * 32
        hh_probe = _round_up(
            int(resolved.get("hh_probe_capacity")
                or max(p_local // 8, 1024)), 8)
        hh_out = int(resolved.get("hh_out_capacity")
                     or max(p_local // 4, 1024))
        capacities.update(hh_build=int(hh_build), hh_probe=hh_probe,
                          hh_out=hh_out)
        skew = {"threshold": resolved["skew_threshold"],
                "hh_slots": hh_slots}

    wire = _predict_wire(n, k, shuffle, comp_bits, side_b, side_p,
                         b_cap, p_cap, n_slices=n_slices,
                         dcn_codec_on=dcn_on)

    agg_record = None
    agg_out_row_bytes = None
    if agg_spec is not None:
        # agg_ops is bound above — the wire-schema restriction runs
        # under the same agg_spec gate.
        bsch, psch = agg_schemas
        groups_cap = agg_ops.resolve_groups_capacity(agg_spec, out_cap)
        capacities["groups_per_rank"] = groups_cap
        partial_cols = agg_ops.partial_columns(
            agg_spec, agg_mode, keys_eff, bsch, psch)
        partial_row_bytes = sum(_itemsize(dt) for _, dt in partial_cols)
        agg_out_row_bytes = partial_row_bytes
        agg_record = {
            "spec": agg_spec.as_record(),
            "mode": agg_mode,
            "groups_per_rank": groups_cap,
            "partial_columns": [list(c) for c in partial_cols],
            "partial_row_bytes": partial_row_bytes,
        }
        if agg_mode in ("probe", "build") and n > 1:
            # The partials-only cross-rank exchange (ONE padded
            # collective, not per batch): per-destination capacity is
            # the full groups block, so the billed bytes are EXACTLY
            # shuffle_padded's static n x groups_cap block per column
            # — or both tiers of the hierarchical route, raw (no
            # codec on the tiny partials).
            block = n * groups_cap * partial_row_bytes
            hier = shuffle == "hierarchical" and n_slices > 1
            per_rank = 2 * block if hier else block
            wire["partials"] = {
                "bytes_per_rank": int(per_rank),
                "bytes_total": int(per_rank) * n,
                "rows_estimate": groups_cap,
            }
            if hier:
                wire["partials"]["ici_bytes_per_rank"] = int(block)
                wire["partials"]["dcn_bytes_per_rank"] = int(block)
                wire["collectives_per_step"] += 2 * (
                    1 + len(partial_cols))
            else:
                wire["collectives_per_step"] += 1 + len(partial_cols)

    model = cost_model or CostModel()
    memory = _predict_memory(
        n, k, side_b, side_p, b_cap, p_cap,
        out_cap if agg_spec is None else capacities["groups_per_rank"],
        capacities, model, out_row_bytes=agg_out_row_bytes)

    plan = JoinPlan(
        digest=sig.digest(),
        n_ranks=n,
        over_decomposition=k,
        key=tuple(keys_eff),
        shuffle=shuffle,
        compression_bits=comp_bits,
        with_metrics=bool(with_metrics),
        with_integrity=bool(resolved.get("with_integrity")),
        build=side_b,
        probe=side_p,
        capacities=capacities,
        skew=skew,
        wire=wire,
        memory=memory,
        resolved_options=_jsonable(resolved),
        cost={},
        n_slices=n_slices,
        pipeline="join" if agg_spec is None else "join_agg",
        aggregate=agg_record,
    )
    # cost needs the assembled plan; frozen dataclass -> rebuild field.
    object.__setattr__(plan, "cost", predict(plan, model))
    return plan


def _predict_memory(n, k, side_b, side_p, b_cap, p_cap, out_cap,
                    capacities, model: CostModel,
                    out_row_bytes: Optional[int] = None) -> dict:
    """Per-rank HBM footprint of the resident arrays the step
    materializes: the local table shards, one batch's shuffle
    send/recv blocks per side, and the k output blocks. A roofline
    bound (working-set copies during sorts are not modeled)."""
    input_b = (side_b.rows_local * side_b.row_bytes
               + side_p.rows_local * side_p.row_bytes)
    shuffle_b = 2 * n * (b_cap * side_b.row_bytes
                         + p_cap * side_p.row_bytes)
    if out_row_bytes is None:
        # Materializing join: each output row carries both sides.
        # Aggregation pushdown passes the PARTIALS row width instead
        # (group keys + combinable lanes; out_cap is then groups_cap).
        out_row_bytes = side_b.row_bytes + side_p.row_bytes
    output_b = k * out_cap * out_row_bytes
    hh_b = 0
    if "hh_build" in capacities:
        hh_b = (capacities["hh_build"] * side_b.row_bytes
                + capacities["hh_probe"] * side_p.row_bytes
                + capacities["hh_out"] * out_row_bytes)
    total = input_b + shuffle_b + output_b + hh_b
    return {
        "per_rank_bytes": {
            "input": int(input_b),
            "shuffle_blocks": int(shuffle_b),
            "output_blocks": int(output_b),
            "skew_blocks": int(hh_b),
        },
        "total_per_rank_bytes": int(total),
        "hbm_capacity_bytes": int(model.hbm_capacity_bytes),
        "fits_hbm": bool(total < model.hbm_capacity_bytes),
    }


def _jsonable(obj):
    """Canonical-options tuples -> JSON-stable lists/dicts."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in sorted(obj.items())}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    return repr(obj)


# -- dry-run surfaces --------------------------------------------------


def abstract_tables(build_rows: int, probe_rows: int,
                    key_dtype: str = "int64",
                    payload_dtype: str = "int64"):
    """Abstract (ShapeDtypeStruct) build/probe Tables matching the
    generator drivers' schema — the service ``explain`` op's dry-run
    inputs. No device, no data; shapes only."""
    import jax
    import jax.numpy as jnp

    from distributed_join_tpu.table import Table

    def tbl(rows, payload_name):
        return Table(
            {"key": jax.ShapeDtypeStruct((rows,),
                                         jnp.dtype(key_dtype)),
             payload_name: jax.ShapeDtypeStruct(
                 (rows,), jnp.dtype(payload_dtype))},
            jax.ShapeDtypeStruct((rows,), jnp.bool_),
        )

    return (tbl(build_rows, "build_payload"),
            tbl(probe_rows, "probe_payload"))


def explain_join(build, probe, comm, key="key",
                 verify_integrity: bool = False,
                 cost_model: Optional[CostModel] = None,
                 **opts) -> JoinPlan:
    """Dry-run ``distributed_inner_join``'s knob resolution — padding,
    capacity-factor defaults, skew-capacity resolution, the ladder's
    INITIAL rung — and return the plan, executing nothing. The library
    EXPLAIN surface; ``distributed_inner_join(explain=True)`` attaches
    the same plan (final rung) to its result."""
    from distributed_join_tpu.parallel.distributed_join import (
        resolve_join_ladder,
    )
    from distributed_join_tpu.table import Table

    n = comm.n_ranks

    def padded(table):
        cap = table.capacity
        target = _round_up(cap, n)
        if target == cap:
            return table
        import jax

        # Abstract pad: mirror Table.pad_to at the shape level so a
        # dry-run never concatenates real arrays.
        cols = {
            name: jax.ShapeDtypeStruct((target,) + tuple(c.shape[1:]),
                                       c.dtype)
            for name, c in table.columns.items()
        }
        return Table(cols, jax.ShapeDtypeStruct((target,), bool))

    build, probe = padded(build), padded(probe)
    opts = dict(opts)
    ladder = resolve_join_ladder(build, probe, n, opts,
                                 n_slices=getattr(comm, "n_slices", 1))
    return build_plan(
        comm, build, probe, key=key,
        with_integrity=verify_integrity,
        metrics_static={"retry_attempt_max": 0},
        cost_model=cost_model,
        **ladder.sizing(), **opts)


def build_probe_plan(comm, resident, probe, key="key",
                     digest: Optional[str] = None,
                     with_metrics=None,
                     cost_model: Optional[CostModel] = None,
                     **opts) -> JoinPlan:
    """The PROBE-ONLY plan variant (resident build tables,
    ``service/resident.py``): the program that ``make_probe_join_step``
    compiles against an already-registered build image. Wire bytes and
    partition work cover the PROBE side only — the build side's 2/3
    was paid once at registration — and the join stage merges each
    probe batch against the full resident shard. ``digest`` is the
    :class:`~..service.resident.ResidentSignature` digest of the
    corresponding cached program (plan == cache key, the EXPLAIN
    agreement contract); when omitted a plan-local hash stands in
    (dry runs without a registry)."""
    import hashlib

    from distributed_join_tpu import telemetry

    from distributed_join_tpu.parallel.distributed_join import (
        DEFAULT_OUT_CAPACITY_FACTOR,
        DEFAULT_SHUFFLE_CAPACITY_FACTOR,
        resolve_probe_capacities,
    )

    if with_metrics is None:
        with_metrics = telemetry.enabled()
    keys = [key] if isinstance(key, str) else list(key)
    n = comm.n_ranks
    k = int(opts.get("over_decomposition") or 1)
    nb = n * k
    shuffle = opts.get("shuffle") or "padded"
    comp_bits = opts.get("compression_bits")
    if k < 1:
        raise ValueError("over_decomposition must be >= 1")
    if shuffle not in ("padded", "ragged", "ppermute"):
        raise ValueError(f"unknown shuffle mode {shuffle!r}")
    shuffle_f = float(opts.get("shuffle_capacity_factor")
                      or DEFAULT_SHUFFLE_CAPACITY_FACTOR)
    out_f = float(opts.get("out_capacity_factor")
                  or DEFAULT_OUT_CAPACITY_FACTOR)
    out_rows = opts.get("out_rows_per_rank")

    r_global = int(next(iter(
        resident.columns.values())).shape[0])
    p_global = int(next(iter(probe.columns.values())).shape[0])
    r_local, p_local = r_global // n, p_global // n

    rcols = _sorted_cols(_schema_cols(resident))
    pcols = _sorted_cols(_schema_cols(probe))
    # The FULL registered image stays resident regardless of any
    # aggregate wire-column restriction below — the memory story
    # prices it at its true width.
    r_row_bytes_full = _row_bytes(rcols)
    # Aggregation pushdown on the probe-only dispatch
    # (make_probe_join_step(aggregate=), docs/AGGREGATION.md): the
    # probe side ships only the columns the fused reduction reads.
    agg_spec = opts.get("aggregate")
    agg_mode = None
    agg_schemas = None
    if agg_spec is not None:
        from distributed_join_tpu.ops import aggregate as agg_ops

        rsch = {name: (dtype, 1 + len(tr))
                for name, dtype, tr in rcols}
        psch = {name: (dtype, 1 + len(tr))
                for name, dtype, tr in pcols}
        agg_mode = agg_ops.resolve_agg_mode(agg_spec, keys, rsch, psch)
        if agg_mode == "build":
            raise agg_ops.AggregatePushdownUnsupported(
                "group keys live on the RESIDENT (build) side; the "
                "probe-only program keeps the build shards pinned and "
                "only exchanges probe rows, so build-keyed group-bys "
                "ride make_join_step(aggregate=) instead")
        agg_schemas = (rsch, psch)
        need_b, need_p = agg_ops.wire_columns(
            agg_spec, agg_mode, keys, rsch, psch)
        rcols = tuple(c for c in rcols if c[0] in set(need_b))
        pcols = tuple(c for c in pcols if c[0] in set(need_p))
    side_b = SidePlan(
        rows_global=r_global, rows_local=r_local, columns=rcols,
        varwidth=(), row_bytes=_row_bytes(rcols),
        row_bytes_fixed=_row_bytes(rcols),
    )
    side_p = SidePlan(
        rows_global=p_global, rows_local=p_local, columns=pcols,
        varwidth=(), row_bytes=_row_bytes(pcols),
        row_bytes_fixed=_row_bytes(pcols),
    )

    p_cap, out_cap = resolve_probe_capacities(
        p_local, n, k, shuffle_f, out_f, out_rows)
    capacities = {
        "shuffle_build_per_bucket": 0,
        "shuffle_probe_per_bucket": p_cap,
        "out_rows_per_batch": out_cap,
        "shuffle_capacity_factor": shuffle_f,
        "out_capacity_factor": out_f,
        "out_rows_per_rank": out_rows,
        "resident_rows_per_rank": r_local,
    }

    single = nb == 1
    if single:
        zero = {"bytes_per_rank": 0, "bytes_total": 0,
                "rows_estimate": 0}
        probe_wire = dict(zero)
        coll = 0
        exact = True
    elif shuffle == "ragged":
        per_rank = p_local * side_p.row_bytes
        probe_wire = {"bytes_per_rank": int(per_rank),
                      "bytes_total": int(per_rank) * n,
                      "rows_estimate": p_local * n}
        coll = k * (1 + len(pcols))
        exact = False
    else:
        per_rank, raw = _padded_side_bytes(n, k, p_cap, pcols,
                                           comp_bits)
        probe_wire = {"bytes_per_rank": int(per_rank),
                      "bytes_total": int(per_rank) * n,
                      "rows_estimate": p_local * n}
        if comp_bits is not None:
            probe_wire["raw_bytes_per_rank"] = int(raw)
        coll = k * (1 + (2 if comp_bits is not None else 1)
                    * len(pcols))
        exact = True
    wire = {
        "exact": exact,
        "build": {"bytes_per_rank": 0, "bytes_total": 0,
                  "rows_estimate": 0, "resident": True},
        "probe": probe_wire,
        "collectives_per_step": coll,
    }

    agg_record = None
    if agg_spec is not None:
        # agg_ops is bound above, under the same agg_spec gate.
        rsch, psch = agg_schemas
        groups_cap = agg_ops.resolve_groups_capacity(agg_spec, out_cap)
        capacities["groups_per_rank"] = groups_cap
        partial_cols = agg_ops.partial_columns(
            agg_spec, agg_mode, keys, rsch, psch)
        partial_row_bytes = sum(_itemsize(dt) for _, dt in partial_cols)
        agg_record = {
            "spec": agg_spec.as_record(),
            "mode": agg_mode,
            "groups_per_rank": groups_cap,
            "partial_columns": [list(c) for c in partial_cols],
            "partial_row_bytes": partial_row_bytes,
        }
        if agg_mode == "probe" and n > 1:
            block = n * groups_cap * partial_row_bytes
            wire["partials"] = {
                "bytes_per_rank": int(block),
                "bytes_total": int(block) * n,
                "rows_estimate": groups_cap,
            }
            wire["collectives_per_step"] += 1 + len(partial_cols)

    model = cost_model or CostModel()
    # Resident shards + one batch's probe shuffle blocks + outputs.
    # The input term prices the FULL registered image (it stays
    # resident whatever an aggregate spec reads); a fused plan's
    # output is the groups-sized partials block, not joined rows —
    # the build_plan discipline, mirrored.
    if agg_record is None:
        out_blocks = k * out_cap * (side_b.row_bytes
                                    + side_p.row_bytes)
    else:
        out_blocks = (k * agg_record["groups_per_rank"]
                      * agg_record["partial_row_bytes"])
    mem_total = (r_local * r_row_bytes_full
                 + p_local * side_p.row_bytes
                 + 2 * n * p_cap * side_p.row_bytes
                 + out_blocks)
    memory = {
        "per_rank_bytes": {
            "input": int(r_local * r_row_bytes_full
                         + p_local * side_p.row_bytes),
            "shuffle_blocks": int(2 * n * p_cap * side_p.row_bytes),
            "output_blocks": int(out_blocks),
            "skew_blocks": 0,
        },
        "total_per_rank_bytes": int(mem_total),
        "hbm_capacity_bytes": int(model.hbm_capacity_bytes),
        "fits_hbm": bool(mem_total < model.hbm_capacity_bytes),
    }

    if digest is None:
        digest = hashlib.sha256(json.dumps(
            {"probe_only": True, "n_ranks": n, "key": keys,
             "resident": [list(c) for c in rcols],
             "probe": [list(c) for c in pcols],
             "capacities": capacities, "shuffle": shuffle,
             "aggregate": agg_record},
            sort_keys=True, default=str).encode()).hexdigest()

    plan = JoinPlan(
        digest=digest,
        n_ranks=n,
        over_decomposition=k,
        key=tuple(keys),
        shuffle=shuffle,
        compression_bits=comp_bits,
        with_metrics=bool(with_metrics),
        with_integrity=False,
        build=side_b,
        probe=side_p,
        capacities=capacities,
        skew=None,
        wire=wire,
        memory=memory,
        resolved_options=_jsonable(
            {k_: v for k_, v in opts.items()}),
        cost={},
        pipeline="probe_join" if agg_spec is None
        else "probe_join_agg",
        probe_only=True,
        aggregate=agg_record,
    )
    object.__setattr__(plan, "cost", predict(plan, model))
    return plan


def build_exchange_plan(n_ranks: int, buffer_bytes_per_rank: int,
                        cost_model: Optional[CostModel] = None) -> dict:
    """The all_to_all microbenchmark's explain artifact (no join
    pipeline — one fixed-size exchange)."""
    import hashlib

    body = {
        "pipeline": "all_to_all",
        "n_ranks": int(n_ranks),
        "buffer_bytes_per_rank": int(buffer_bytes_per_rank),
        "wire": {
            "exact": True,
            "bytes_per_rank": int(buffer_bytes_per_rank),
            "bytes_total": int(buffer_bytes_per_rank) * int(n_ranks),
            "offchip_bytes_per_rank": int(
                buffer_bytes_per_rank * (n_ranks - 1) // n_ranks),
        },
    }
    body["signature_digest"] = hashlib.sha256(
        json.dumps(body, sort_keys=True).encode()).hexdigest()
    return {
        "schema_version": EXPLAIN_SCHEMA_VERSION,
        "kind": "explain",
        "plan": body,
        "cost": predict_exchange(n_ranks, buffer_bytes_per_rank,
                                 cost_model),
    }
