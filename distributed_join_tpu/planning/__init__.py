"""Plan introspection + roofline cost model (EXPLAIN / EXPLAIN
ANALYZE for the distributed join; docs/OBSERVABILITY.md "Explain &
cost model").

- :mod:`.plan` — :class:`JoinPlan` / :func:`build_plan` /
  :func:`explain_join`: the fully-resolved program description
  (capacities, wire bytes, HBM footprint, canonical cache-key digest)
  from table shapes alone, with zero traces or compiles;
- :mod:`.cost` — :class:`CostModel` / :func:`predict`: ROOFLINE.md's
  measured per-primitive costs as an executable per-stage wall-time
  predictor, graded post-run by ``analyze explain`` and the
  workload-history store, refit from that store's measured wall
  ratios via :func:`calibrate_from_history` (one global scale), and
  refit PER CONSTANT from a stage-segmented profile via
  :func:`calibrate_from_stage_profile` (``telemetry/stageprof.py``);
- :mod:`.tuner` — :class:`JoinTuner`: the history-driven autotuner
  (ROADMAP item 5's closed loop) pre-sizing repeat workloads from the
  per-signature trends so the retry ladder never recompiles twice for
  the same lesson.
"""

from distributed_join_tpu.planning.cost import (
    COST_MODEL_VERSION,
    DEFAULT_COST_MODEL,
    DEFAULT_PREDICTION_BAND,
    STAGE_CONSTANTS,
    CostModel,
    calibrate_from_history,
    calibrate_from_stage_profile,
    predict,
    predict_exchange,
)
from distributed_join_tpu.planning.plan import (
    EXPLAIN_SCHEMA_VERSION,
    JoinPlan,
    SidePlan,
    abstract_tables,
    build_exchange_plan,
    build_plan,
    build_probe_plan,
    explain_join,
)
from distributed_join_tpu.planning.query import (
    QUERY_SCHEMA_VERSION,
    QueryOp,
    QueryPlan,
    explain_query,
    tpch_query_plan,
)
from distributed_join_tpu.planning.tuner import (
    TUNER_SCHEMA_VERSION,
    JoinTuner,
    TunedConfig,
    workload_signature,
)

__all__ = [
    "COST_MODEL_VERSION",
    "DEFAULT_COST_MODEL",
    "DEFAULT_PREDICTION_BAND",
    "EXPLAIN_SCHEMA_VERSION",
    "QUERY_SCHEMA_VERSION",
    "STAGE_CONSTANTS",
    "TUNER_SCHEMA_VERSION",
    "CostModel",
    "JoinPlan",
    "JoinTuner",
    "QueryOp",
    "QueryPlan",
    "SidePlan",
    "TunedConfig",
    "abstract_tables",
    "build_exchange_plan",
    "build_plan",
    "build_probe_plan",
    "calibrate_from_history",
    "calibrate_from_stage_profile",
    "explain_join",
    "explain_query",
    "predict",
    "predict_exchange",
    "tpch_query_plan",
    "workload_signature",
]
