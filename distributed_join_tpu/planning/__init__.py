"""Plan introspection + roofline cost model (EXPLAIN / EXPLAIN
ANALYZE for the distributed join; docs/OBSERVABILITY.md "Explain &
cost model").

- :mod:`.plan` — :class:`JoinPlan` / :func:`build_plan` /
  :func:`explain_join`: the fully-resolved program description
  (capacities, wire bytes, HBM footprint, canonical cache-key digest)
  from table shapes alone, with zero traces or compiles;
- :mod:`.cost` — :class:`CostModel` / :func:`predict`: ROOFLINE.md's
  measured per-primitive costs as an executable per-stage wall-time
  predictor, graded post-run by ``analyze explain`` and the
  workload-history store.
"""

from distributed_join_tpu.planning.cost import (
    COST_MODEL_VERSION,
    DEFAULT_COST_MODEL,
    DEFAULT_PREDICTION_BAND,
    CostModel,
    predict,
    predict_exchange,
)
from distributed_join_tpu.planning.plan import (
    EXPLAIN_SCHEMA_VERSION,
    JoinPlan,
    SidePlan,
    abstract_tables,
    build_exchange_plan,
    build_plan,
    explain_join,
)

__all__ = [
    "COST_MODEL_VERSION",
    "DEFAULT_COST_MODEL",
    "DEFAULT_PREDICTION_BAND",
    "EXPLAIN_SCHEMA_VERSION",
    "CostModel",
    "JoinPlan",
    "SidePlan",
    "abstract_tables",
    "build_exchange_plan",
    "build_plan",
    "explain_join",
    "predict",
    "predict_exchange",
]
