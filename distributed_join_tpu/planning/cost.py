"""The roofline cost model — ROOFLINE.md's measured numbers, in code.

``docs/ROOFLINE.md`` §1 measured what every primitive of the join
pipeline costs on a real v5e (sorts ~7 ns/element with value lanes
riding ~free, random gathers a serialized ~10-21 ns/element loop,
scans ~2 ns/element, the Pallas expand ~11-16 ns/output-row), and §6-8
refined the per-stage split (sort 138 ms / compaction 116 ms / expand
80 ms of a 20M-element 360 ms join). Until this module those numbers
lived only in a doc; :class:`CostModel` makes them an executable
predictor over a :class:`~.plan.JoinPlan`: per-stage wall seconds and
the derived rows/s, before anything traces or compiles.

Honesty contract:

- Every constant is either MEASURED (named row of ROOFLINE.md §1/§6,
  chip wall clocks) or SPEC-DERIVED (the ICI bandwidth — this
  environment exposes one chip, so the all-to-all has never been
  measured on real ICI; ROADMAP item 1's hardware session is the
  calibration path). ``CostModel.provenance`` says which is which.
- Predictions model the **v5e roofline**, not whatever backend the
  process happens to run on. On the 8-virtual-device CPU mesh the
  predicted WALL is deliberately wrong (emulation measures the host);
  the predicted WIRE BYTES are exact in padded/compressed modes and
  are gated in CI. ``analyze explain`` grades both against measured
  counters after a run, and the workload-history store records the
  error per workload signature so the autotuner (ROADMAP item 5)
  learns where this model lies.

Everything here is plain host arithmetic — no jax import, no device
touch, deterministic to the byte (the explain artifact is
byte-identical across runs of the same query spec).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

COST_MODEL_VERSION = 1

# Prediction band for grading: a measured wall within
# [predicted / BAND, predicted * BAND] is "inside the model"; outside
# means the model (or the machine) moved for that workload. Wide on
# purpose — the model is a v5e roofline, and §5-8 of ROOFLINE.md show
# real implementations landing within ~1.5-4x of primitive floors.
DEFAULT_PREDICTION_BAND = 4.0


@dataclasses.dataclass(frozen=True)
class CostModel:
    """Per-primitive costs (ns/element unless noted), v5e.

    Measured constants cite their ROOFLINE.md row; spec-derived ones
    say so. Replace any field and re-run ``predict`` — the explain
    artifact embeds the constants used, so a graded run is always
    attributable to one concrete model.
    """

    # lax.sort, 20M x (i64 key + small lanes): 139-168 ms (§1, §6).
    sort_ns_per_elem: float = 7.0
    # batched short-run lax.sort — the §6 run-length regime the
    # segmented-sort pipeline rides (ops/segmented.py, §9): the same
    # 20M x (i64, i8, i64) operands sort in 24-45 ms as independent
    # runs ((8192, 2048): 24 ms; (512, 32768): 38 ms) => ~1.2-2.2
    # ns/elem; the conservative midpoint ships until the first
    # real-chip segmented stage profile refits it
    # (calibrate_from_stage_profile; relay_session_r6 step 10).
    sort_run_ns_per_elem: float = 1.9
    # each extra i64 value lane on a 139 ms sort: +6 ms (§1).
    sort_lane_ns_per_elem: float = 0.3
    # cumsum/cummax 20M i32: 30-43 ms (§1).
    scan_ns_per_elem: float = 2.0
    # random gather, any index order: 161-205 ms / 7.5M i64 (§1).
    gather_ns_per_elem: float = 21.0
    # packed (rows, k<=4) row gather: ~110 ms / 7.5M rows (§1 fact 3).
    row_gather_ns_per_row: float = 14.7
    # log-shift plane compaction: 116 ms / 20M merged elements (§6).
    compact_ns_per_elem: float = 5.8
    # Pallas streaming expand: ~80 ms / 7.5M output rows (§6).
    expand_ns_per_out_row: float = 10.7
    # sequential HBM stream (§1 fact 2's contrast case).
    hbm_bytes_per_s: float = 8.0e11
    # FoR+bitpack codec encode/decode: 26/28 GB/s (BASELINE.md row 18).
    codec_bytes_per_s: float = 2.6e10
    # SPEC-DERIVED: v5e ICI per-chip all-to-all egress. Never measured
    # here (one-chip environment; BASELINE.md row 17) — recalibrate
    # from the first real `tpu-all-to-all` session (ROADMAP item 1).
    ici_bytes_per_s: float = 4.5e10
    # SPEC-DERIVED: per-chip cross-slice (DCN) egress of a multi-slice
    # v5e pod — ~25 GB/s NIC per 8-chip host, ~3 GB/s per chip. Never
    # measured here (no multi-slice allocation yet); flagged
    # uncalibrated until the first multi-slice relay session refits it
    # through the same calibrate_from_stage_profile seam as ICI
    # (scripts/relay_session_r6.py, ROADMAP item 5). Sits BELOW the
    # codec's ~5-7 GB/s break-even — the whole reason the FoR+bitpack
    # wire flips from NO-GO to win on the cross-slice tier
    # (docs/HIERARCHY.md).
    dcn_bytes_per_s: float = 3.0e9
    # per-collective dispatch/sync overhead (spec-derived order).
    collective_latency_s: float = 2.0e-5
    # v5e HBM per chip, for the footprint verdict (16 GiB).
    hbm_capacity_bytes: int = 16 * 1024**3
    # Set by calibrate_from_history: the global measured/predicted
    # wall scale this model was refit with (None = the as-shipped
    # ROOFLINE.md constants).
    calibrated_scale: Optional[float] = None
    # Set by calibrate_from_stage_profile: ((stage, scale), ...) of
    # the PER-STAGE measured/predicted ratios the stage-owned
    # constants were refit with (None = no stage-level refit). A tuple
    # (not a dict) so the frozen dataclass stays hashable.
    calibrated_stage_scales: Optional[tuple] = None

    @property
    def provenance(self) -> dict:
        return {
            "measured": [
                "sort_ns_per_elem", "sort_run_ns_per_elem",
                "sort_lane_ns_per_elem",
                "scan_ns_per_elem", "gather_ns_per_elem",
                "row_gather_ns_per_row", "compact_ns_per_elem",
                "expand_ns_per_out_row", "hbm_bytes_per_s",
                "codec_bytes_per_s",
            ],
            "spec_derived": [
                "ici_bytes_per_s", "dcn_bytes_per_s",
                "collective_latency_s", "hbm_capacity_bytes",
            ],
            "source": "docs/ROOFLINE.md §1/§6; BASELINE.md"
                      + ("" if self.calibrated_scale is None else
                         f"; calibrated x{self.calibrated_scale:g} "
                         "from measured history")
                      + ("" if self.calibrated_stage_scales is None
                         else "; stage-calibrated "
                         + " ".join(f"{s}=x{v:g}" for s, v in
                                    self.calibrated_stage_scales)
                         + " from a stage profile"),
        }

    def as_record(self) -> dict:
        rec = dataclasses.asdict(self)
        rec["model_version"] = COST_MODEL_VERSION
        rec["provenance"] = self.provenance
        return rec


DEFAULT_COST_MODEL = CostModel()

# The FoR+bitpack codec's measured break-even wire bandwidth
# (results/compression_for_bitpack.json, docs/ROOFLINE.md: ~5-7 GB/s;
# the conservative upper edge): below it, encode+decode time is
# cheaper than the bytes it removes. ICI (~45 GB/s) sits far above —
# codec NO-GO; DCN (~3 GB/s) sits below — codec win. This one number
# is what the hierarchical shuffle's `dcn_codec="auto"` resolves
# against.
CODEC_BREAK_EVEN_BYTES_PER_S = 7.0e9

DCN_CODEC_KNOBS = ("off", "auto", "on")


def resolve_dcn_codec(knob: str,
                      model: Optional[CostModel] = None) -> bool:
    """Resolve the hierarchical shuffle's ``dcn_codec`` knob to a
    static on/off: ``"auto"`` turns the codec on exactly when the
    configured cross-slice bandwidth sits below the codec's measured
    break-even. Deterministic host arithmetic — the verdict is baked
    into the compiled program (and its signature carries the knob, so
    a changed model constant cannot silently alias two programs)."""
    if knob not in DCN_CODEC_KNOBS:
        raise ValueError(
            f"unknown dcn_codec {knob!r}; pick one of "
            f"{DCN_CODEC_KNOBS}")
    if knob == "auto":
        m = model or DEFAULT_COST_MODEL
        return m.dcn_bytes_per_s < CODEC_BREAK_EVEN_BYTES_PER_S
    return knob == "on"


def resolve_dcn_bits(knob: str, compression_bits: Optional[int] = None,
                     *, n_slices: int,
                     model: Optional[CostModel] = None) -> Optional[int]:
    """THE one resolution of the hierarchical shuffle's cross-slice
    residual width, shared by the capacity ladder, the drivers, and
    the stage profiler: the caller's bits (defaulting to
    ``DEFAULT_DCN_CODEC_BITS``) exactly when the codec resolves on
    AND the mesh has a cross-slice tier to ride. One slice has no DCN
    payload — the degenerate hierarchy routes the flat raw padded
    path — so bits resolve to ``None`` there (armed bits would burn
    the first retry rung widening a knob the program ignores). The
    knob VALUE is validated regardless, so a typo fails loudly on
    every topology."""
    on = resolve_dcn_codec(knob, model)
    if n_slices <= 1 or not on:
        return None
    from distributed_join_tpu.parallel.distributed_join import (
        DEFAULT_DCN_CODEC_BITS,
    )

    return compression_bits or DEFAULT_DCN_CODEC_BITS


def _round_s(x: float) -> float:
    """Deterministic second-rounding for the artifact (9 digits keeps
    ns resolution without float repr jitter)."""
    return round(float(x), 9)


def predict(plan, model: Optional[CostModel] = None) -> dict:
    """Per-stage predicted wall seconds (per rank — the pipeline is
    symmetric, so per-rank == critical path) for one join step plus
    the derived throughput. ``plan`` is a :class:`~.plan.JoinPlan`.

    Stage decomposition mirrors make_join_step: partition (one
    bucket sort per side + the to_padded gathers), shuffle (wire
    bytes over ICI + per-collective latency + codec time when
    compression is on), join per batch (merged sort + scans +
    compaction over the merged domain, expand over the output block),
    and the skew sidecar when the PRPD path is on.
    """
    m = model or DEFAULT_COST_MODEL
    n = plan.n_ranks
    k = plan.over_decomposition
    ns = 1e-9
    # Probe-only plans (resident build tables, plan.build_probe_plan):
    # the build side is an on-device image prepared at registration —
    # no per-request build partition work or wire bytes — while the
    # join stage still merges each batch against the full resident
    # shard (capacities["resident_rows_per_rank"]).
    probe_only = bool(getattr(plan, "probe_only", False))
    # Aggregation pushdown (pipeline "join_agg", docs/AGGREGATION.md):
    # the fused pipeline reduces in the merged domain and NEVER runs
    # the output expand/gathers that dominate materialization
    # (ROOFLINE §1-§3) — the expand constant drops out of the join
    # stage entirely, and the shuffle stage gains the groups-sized
    # partials exchange (plan.wire["partials"], probe mode only).
    fused_agg = getattr(plan, "pipeline", "join") in (
        "join_agg", "probe_join_agg")
    wire_sides = ("build", "probe", "partials") if fused_agg \
        else ("build", "probe")

    b_local = plan.build.rows_local
    p_local = plan.probe.rows_local
    b_cols = max(len(plan.build.columns), 1)
    p_cols = max(len(plan.probe.columns), 1)

    single = plan.n_buckets == 1
    # Rows materialized into the shuffle layout per side (k batches of
    # the n x cap padded block; ragged ships the same rows unpadded).
    b_shipped = 0 if single else k * n * plan.capacities["shuffle_build_per_bucket"]
    p_shipped = 0 if single else k * n * plan.capacities["shuffle_probe_per_bucket"]

    # -- partition: bucket sort (2 int32 lanes) + one composed gather
    # per column into the padded/ragged layout.
    if single:
        partition_s = 0.0
    elif probe_only:
        partition_s = ns * (
            p_local * m.sort_ns_per_elem
            + p_shipped * m.row_gather_ns_per_row * _col_groups(p_cols)
        )
    else:
        partition_s = ns * (
            (b_local + p_local) * m.sort_ns_per_elem
            + b_shipped * m.row_gather_ns_per_row * _col_groups(b_cols)
            + p_shipped * m.row_gather_ns_per_row * _col_groups(p_cols)
        )

    # -- shuffle: off-chip bytes at ICI bandwidth + dispatch latency.
    shuffle_tiers = None
    if single:
        shuffle_s = 0.0
    elif (plan.shuffle == "hierarchical"
          and getattr(plan, "n_slices", 1) > 1):
        # Two sequential tiers (phase 2 consumes phase 1's output, so
        # the honest price is the SUM; both appear separately in the
        # record so `analyze stages`/the tuner can see which tier
        # dominates). Off-chip fractions: (c-1)/c of the ICI block
        # leaves the chip within a slice, (s-1)/s of the DCN block
        # crosses slices.
        s_ = getattr(plan, "n_slices", 1)
        c_ = max(n // s_, 1)
        ici_rank = sum((plan.wire.get(side) or {})
                       .get("ici_bytes_per_rank", 0)
                       for side in wire_sides)
        dcn_rank = sum((plan.wire.get(side) or {})
                       .get("dcn_bytes_per_rank", 0)
                       for side in wire_sides)
        ici_s = (ici_rank * (c_ - 1) / c_) / m.ici_bytes_per_s
        dcn_s = (dcn_rank * (s_ - 1) / s_) / m.dcn_bytes_per_s
        codec_s = 0.0
        raw = sum((plan.wire.get(side) or {})
                  .get("dcn_raw_bytes_per_rank", 0)
                  for side in wire_sides)
        if raw:
            # encode + decode of the raw cross-slice block bytes.
            codec_s = 2.0 * raw / m.codec_bytes_per_s
        shuffle_s = (ici_s + dcn_s + codec_s
                     + plan.wire["collectives_per_step"]
                     * m.collective_latency_s)
        shuffle_tiers = {"ici_s": _round_s(ici_s),
                         "dcn_s": _round_s(dcn_s),
                         "codec_s": _round_s(codec_s)}
    else:
        wire_rank = sum((plan.wire.get(side) or {})
                        .get("bytes_per_rank", 0)
                        for side in wire_sides)
        offchip = wire_rank * (n - 1) / n
        shuffle_s = (offchip / m.ici_bytes_per_s
                     + plan.wire["collectives_per_step"]
                     * m.collective_latency_s)
        if plan.compression_bits is not None:
            # encode + decode of the raw (uncompressed) block bytes.
            raw = (plan.wire["build"].get("raw_bytes_per_rank", 0)
                   + plan.wire["probe"].get("raw_bytes_per_rank", 0))
            shuffle_s += 2.0 * raw / m.codec_bytes_per_s

    # -- local join, per batch: sort both received sides into the
    # merged domain, scans + compaction over it, expand the output.
    if single:
        merged = b_local + p_local
        out_total = plan.capacities["out_rows_per_batch"]
        batches = 1
    elif probe_only:
        merged = (plan.capacities.get("resident_rows_per_rank",
                                      b_local)
                  + n * plan.capacities["shuffle_probe_per_bucket"])
        out_total = plan.capacities["out_rows_per_batch"]
        batches = k
    else:
        merged = (n * plan.capacities["shuffle_build_per_bucket"]
                  + n * plan.capacities["shuffle_probe_per_bucket"])
        out_total = plan.capacities["out_rows_per_batch"]
        batches = k
    if fused_agg:
        # Zero materialization: no record expand, no output gathers —
        # the segmented scans and the groups-sized compaction ride the
        # scan/compact constants over the merged domain.
        out_total = 0
    # Segmented-sort mode (capacities["sort_segments"] > 1): the
    # merged + record sorts run as batched short runs at the §6
    # run-length rate instead of the flat superlinear rate — the whole
    # point of the pipeline (ROOFLINE §9); scans/compaction/expand
    # costs are unchanged (same total elements, batched).
    sort_c = (m.sort_run_ns_per_elem
              if (plan.capacities.get("sort_segments") or 1) > 1
              else m.sort_ns_per_elem)
    join_s = batches * ns * (
        merged * (sort_c
                  + m.sort_lane_ns_per_elem * 2
                  + m.scan_ns_per_elem
                  + m.compact_ns_per_elem)
        + out_total * m.expand_ns_per_out_row
    )

    # -- skew sidecar: HH detection scans the probe keys; the HH join
    # runs over the compacted HH blocks.
    skew_s = 0.0
    if plan.skew is not None:
        hh_rows = (plan.capacities.get("hh_build") or 0) * n \
            + (plan.capacities.get("hh_probe") or 0)
        skew_s = ns * (
            (b_local + p_local) * m.scan_ns_per_elem
            + hh_rows * m.sort_ns_per_elem
            + (plan.capacities.get("hh_out") or 0)
            * m.expand_ns_per_out_row
        )

    total = partition_s + shuffle_s + join_s + skew_s
    rows = plan.build.rows_global + plan.probe.rows_global
    out = {
        "model": m.as_record(),
        "platform": "tpu-v5e-roofline",
        "stages": {
            "partition": _round_s(partition_s),
            "shuffle": _round_s(shuffle_s),
            "join": _round_s(join_s),
            "skew": _round_s(skew_s),
        },
        "total_s": _round_s(total),
        "predicted_rows_per_sec": _round_s(rows / total) if total else None,
        "predicted_m_rows_per_sec_per_rank": (
            _round_s(rows / total / 1e6 / n) if total else None),
    }
    if shuffle_tiers is not None:
        # Sibling of "stages" (never inside it — the stage set is 1:1
        # with STAGE_CONSTANTS/stageprof.STAGE_KEYS by contract): the
        # per-tier split of the shuffle price, so `analyze explain`
        # and the tuner can see which tier dominates.
        out["shuffle_tiers"] = shuffle_tiers
    return out


def _col_groups(n_cols: int) -> float:
    """ROOFLINE §1 fact 3: a packed row gather is flat in k for k <= 4
    columns, so materialization pays one gather per group of 4."""
    return max((n_cols + 3) // 4, 1)


# The per-stage cost constants a calibration scale applies to:
# time-per-element constants scale WITH the measured/predicted ratio,
# bandwidth constants scale AGAINST it (wall = bytes / bandwidth).
_TIME_CONSTANTS = (
    "sort_ns_per_elem", "sort_lane_ns_per_elem", "scan_ns_per_elem",
    "gather_ns_per_elem", "row_gather_ns_per_row",
    "compact_ns_per_elem", "expand_ns_per_out_row",
    "collective_latency_s",
)
_BANDWIDTH_CONSTANTS = ("hbm_bytes_per_s", "codec_bytes_per_s",
                        "ici_bytes_per_s", "dcn_bytes_per_s")


def calibrate_from_history(entries, model: Optional[CostModel] = None,
                           *, min_entries: int = 3,
                           platform: Optional[str] = "tpu"):
    """Refit the cost model from a workload-history store's
    measured/predicted wall ratios (``prediction.wall_ratio`` per
    entry, ``telemetry/history.py``) — the calibration seam ROADMAP
    item 1's hardware session feeds.

    Honesty contract: per-run entries carry ONE total-wall ratio, so
    the only fit the data supports is a single multiplicative
    correction applied uniformly — time constants scale with the
    median ratio, bandwidth constants against it. Separating
    per-stage error needs per-stage measurements: that is
    :func:`calibrate_from_stage_profile`, fed by a ``--stage-profile``
    run (``telemetry/stageprof.py``). Only entries measured on ``platform`` count (default
    "tpu": CPU-mesh walls measure emulation, and a model refit from
    them would be confidently wrong about the chip — the exact
    failure mode the provenance block exists to prevent); pass
    ``platform=None`` to calibrate against whatever was measured
    (testing only).

    Returns ``(model_or_None, report)``: None with
    ``report["calibrated"] = False`` when fewer than ``min_entries``
    eligible entries exist — an uncalibratable store refuses loudly
    instead of shipping a model refit from noise.
    """
    base = model or DEFAULT_COST_MODEL
    ratios = []
    for e in entries or []:
        pred = e.get("prediction")
        if not isinstance(pred, dict) or not pred.get("wall_ratio"):
            continue
        if e.get("outcome") not in ("ok", "served", "recovered"):
            continue
        if platform is not None and e.get("platform") != platform:
            continue
        ratios.append(float(pred["wall_ratio"]))
    report = {
        "platform": platform,
        "n_eligible": len(ratios),
        "min_entries": min_entries,
        "base_calibrated_scale": base.calibrated_scale,
    }
    if len(ratios) < min_entries:
        report.update(
            calibrated=False,
            reason=(f"need >= {min_entries} measured "
                    f"{platform or 'any'}-platform entries with a "
                    f"wall ratio, have {len(ratios)}"))
        return None, report
    ratios.sort()
    scale = ratios[len(ratios) // 2]
    fields = {k: getattr(base, k) * scale for k in _TIME_CONSTANTS}
    fields.update({k: getattr(base, k) / scale
                   for k in _BANDWIDTH_CONSTANTS})
    calibrated = dataclasses.replace(
        base, calibrated_scale=round(scale, 6), **fields)
    report.update(
        calibrated=True,
        scale=round(scale, 6),
        ratio_min=round(ratios[0], 4),
        ratio_median=round(scale, 4),
        ratio_max=round(ratios[-1], 4),
    )
    return calibrated, report


# Which constants each pipeline stage OWNS for the per-constant refit
# (calibrate_from_stage_profile). Honesty note: sort_ns_per_elem
# appears in both the partition and join predictions; the partition
# stage — a pure bucket sort + materialization gather — owns it, and
# the join stage's merged-sort share of any error is absorbed into the
# join-owned constants. hbm_bytes_per_s feeds no stage prediction and
# is never refit here.
STAGE_CONSTANTS = {
    "partition": {
        "time": ("sort_ns_per_elem", "gather_ns_per_elem",
                 "row_gather_ns_per_row"),
        "bandwidth": (),
    },
    "shuffle": {
        "time": ("collective_latency_s",),
        "bandwidth": ("ici_bytes_per_s", "dcn_bytes_per_s",
                      "codec_bytes_per_s"),
    },
    "join": {
        "time": ("sort_run_ns_per_elem", "sort_lane_ns_per_elem",
                 "scan_ns_per_elem", "compact_ns_per_elem",
                 "expand_ns_per_out_row"),
        "bandwidth": (),
    },
}


def calibrate_from_stage_profile(profiles,
                                 model: Optional[CostModel] = None,
                                 *, min_profiles: int = 1,
                                 platform: Optional[str] = "tpu"):
    """Refit INDIVIDUAL cost constants from stage-segmented profiles
    (``telemetry/stageprof.py``'s ``stageprofile.json`` records) — the
    per-constant seam ``calibrate_from_history`` cannot provide: a
    history entry carries one total-wall ratio, while a stage profile
    carries one measured/predicted ratio PER stage, so the sort
    constants (partition stage), the ICI bandwidth + collective
    latency (shuffle stage), and the merge/compact/expand constants
    (join stage) refit independently (:data:`STAGE_CONSTANTS` is the
    ownership map).

    ``profiles`` is one record dict or a sequence of them. Per stage
    the median measured/predicted ratio over eligible profiles becomes
    that stage's scale: stage-owned time constants multiply by it,
    bandwidth constants divide. Eligibility mirrors
    ``calibrate_from_history``: overflowed profiles never count, and
    only ``platform``-stamped profiles do (default "tpu" — a CPU-mesh
    stage wall measures emulation; pass ``platform=None`` to calibrate
    against whatever was measured, testing only).

    Returns ``(model_or_None, report)``; fewer than ``min_profiles``
    eligible profiles refuses loudly (``report["calibrated"] =
    False``) instead of shipping a model refit from noise.
    """
    base = model or DEFAULT_COST_MODEL
    if isinstance(profiles, dict):
        profiles = [profiles]
    ratios: dict = {}
    dcn_ratios: list = []
    sort_run_ratios: list = []
    eligible = 0
    for p in profiles or []:
        if not isinstance(p, dict) or p.get("kind") != "stageprofile":
            continue
        if p.get("overflow"):
            continue
        if platform is not None and p.get("platform") != platform:
            continue
        counted = False
        for stage, info in (p.get("stages") or {}).items():
            if stage not in STAGE_CONSTANTS:
                continue
            if not isinstance(info, dict) or not info.get("ran"):
                continue
            pred, wall = info.get("predicted_s"), info.get("wall_s")
            if pred and wall:
                r = float(wall) / float(pred)
                # Per-tier attribution cuts BOTH ways: a flat
                # profile's shuffle ratio carries zero DCN evidence
                # (scaling the spec constant from it could silently
                # cross the codec break-even), and a DCN-carrying
                # profile's shuffle wall is dominated by the slow
                # tier — folding its ratio into ici/codec would
                # corrupt the fast-tier constants with DCN error.
                # Each shuffle ratio refits exactly one tier.
                if stage == "shuffle" and any(
                        (info.get("counters") or {}).get(
                            f"{s}.wire_bytes_dcn")
                        for s in ("build", "probe")):
                    dcn_ratios.append(r)
                elif stage == "join" and (
                        p.get("sort_segments") or 1) > 1:
                    # Same discipline for the sort modes: a SEGMENTED
                    # profile's join wall is dominated by the batched
                    # short-run sort, so its ratio refits ONLY
                    # sort_run_ns_per_elem — and a flat profile (no
                    # batched sort ever ran) must never touch it.
                    sort_run_ratios.append(r)
                else:
                    ratios.setdefault(stage, []).append(r)
                counted = True
        if counted:
            eligible += 1
    report = {
        "platform": platform,
        "n_eligible": eligible,
        "min_profiles": min_profiles,
    }
    if eligible < min_profiles:
        report.update(
            calibrated=False,
            reason=(f"need >= {min_profiles} non-overflowed "
                    f"{platform or 'any'}-platform stage profiles "
                    f"with per-stage ratios, have {eligible}"))
        return None, report
    fields: dict = {}
    scales: dict = {}
    refit: dict = {}
    for stage, rs in sorted(ratios.items()):
        rs.sort()
        scale = round(rs[len(rs) // 2], 6)
        scales[stage] = scale
        owned = STAGE_CONSTANTS[stage]
        fit_time = list(owned["time"])
        if stage == "join":
            # sort_run_ns_per_elem refits ONLY from segmented
            # profiles (their own median below) — a flat join ratio
            # carries zero batched-short-run-sort evidence.
            fit_time.remove("sort_run_ns_per_elem")
        for k in fit_time:
            fields[k] = getattr(base, k) * scale
        fit_bw = list(owned["bandwidth"])
        if stage == "shuffle":
            # dcn_bytes_per_s refits ONLY from profiles whose shuffle
            # stage actually moved cross-slice bytes — a flat
            # profile's ratio carries zero DCN evidence, and scaling
            # the uncalibrated spec constant from it could silently
            # cross the codec break-even. Hierarchical profiles get
            # their own median below.
            fit_bw.remove("dcn_bytes_per_s")
        for k in fit_bw:
            fields[k] = getattr(base, k) / scale
        refit[stage] = fit_time + fit_bw
    sort_run_scale = None
    if sort_run_ratios:
        sort_run_ratios.sort()
        sort_run_scale = round(
            sort_run_ratios[len(sort_run_ratios) // 2], 6)
        fields["sort_run_ns_per_elem"] = \
            base.sort_run_ns_per_elem * sort_run_scale
        refit.setdefault("join", []).append("sort_run_ns_per_elem")
        scales.setdefault("join", sort_run_scale)
    dcn_scale = None
    if dcn_ratios:
        dcn_ratios.sort()
        dcn_scale = round(dcn_ratios[len(dcn_ratios) // 2], 6)
        fields["dcn_bytes_per_s"] = base.dcn_bytes_per_s / dcn_scale
        refit.setdefault("shuffle", []).append("dcn_bytes_per_s")
    calibrated = dataclasses.replace(
        base,
        calibrated_stage_scales=tuple(sorted(scales.items())),
        **fields)
    report.update(
        calibrated=True,
        stage_scales=scales,
        dcn_scale=dcn_scale,
        sort_run_scale=sort_run_scale,
        refit=refit,
        # the stage the shipped model mispredicts hardest (log scale:
        # x4 optimistic and x0.25 pessimistic are equally wrong)
        worst_stage=max(scales, key=lambda s: abs(math.log(scales[s]))),
        unfit_stages=[s for s in STAGE_CONSTANTS if s not in scales],
    )
    return calibrated, report


def predict_exchange(n_ranks: int, bytes_per_rank: int,
                     model: Optional[CostModel] = None) -> dict:
    """The all_to_all microbenchmark's reduced prediction: one
    fixed-size exchange (`tpu-all-to-all`'s --explain)."""
    m = model or DEFAULT_COST_MODEL
    offchip = bytes_per_rank * (n_ranks - 1) / n_ranks
    total = offchip / m.ici_bytes_per_s + m.collective_latency_s
    return {
        "model": m.as_record(),
        "platform": "tpu-v5e-roofline",
        "stages": {"all_to_all": _round_s(total)},
        "total_s": _round_s(total),
        "predicted_aggregate_offchip_gb_per_sec": _round_s(
            n_ranks * offchip / total / 1e9),
    }
